"""Command-line entry point: ``python -m apex_tpu.lint [paths...]``.

Exit status is 0 when every check passes, 1 when any finding survives
suppression — suitable as a blocking CI step. ``--no-trace`` skips the
trace-time VMEM budget pass (APX102) for a pure-AST run that needs no
jax import; ``--trace`` additionally runs the jaxpr-level trace tier
(APX501/502/503/511/512) over the ``apex_tpu.lint.traced`` entry
registry; ``--cost`` runs the APX6xx cost tier (static HBM-traffic /
collective-volume budgets vs ``budgets.json`` — combine with
``--report`` to dump the per-entry table as JSON on stdout with
findings on stderr, or ``--write-budgets`` to regenerate the manifest,
``--write-budgets --prune`` to also drop manifest entries whose
registry entry no longer exists — both also sweep the scaling grid so
the per-mesh ``<entry>@<tag>`` rows regenerate alongside the base
rows); ``--sharding`` runs the APX7xx sharding tier (partition-rule
tables plus the rule-staged shard_map programs) over the
``apex_tpu.lint.sharded`` entry registry; ``--scaling`` runs the
APX9xx scale-invariance tier (registered programs re-staged across the
swept mesh grid: schedule isomorphism, volume scaling laws, memory
monotonicity, rule-table divisibility);
``--select`` narrows the *output* to a comma-separated code list;
``--codes APX511,APX70*`` instead names the checks to *run* — globs
expand against the catalogue and the owning tiers are enabled
automatically.
"""

import argparse
import fnmatch
import sys

from apex_tpu.lint import CODES
from apex_tpu.lint.engine import lint_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.lint",
        description="apxlint — static contract checker for apex_tpu "
                    "Pallas kernels, collectives, and AMP op lists.")
    ap.add_argument("paths", nargs="*", default=["apex_tpu"],
                    help="files or directories to lint "
                         "(default: apex_tpu)")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the trace-time VMEM budget pass (APX102)")
    ap.add_argument("--trace", action="store_true",
                    help="also run the jaxpr trace tier (APX5xx) over "
                         "the registered entrypoints")
    ap.add_argument("--cost", action="store_true",
                    help="also run the APX6xx cost tier: per-entry "
                         "static HBM/collective byte budgets vs "
                         "budgets.json")
    ap.add_argument("--sharding", action="store_true",
                    help="also run the APX7xx sharding tier: "
                         "partition-rule table coverage/consistency "
                         "and rule-staged shard_map verification")
    ap.add_argument("--scaling", action="store_true",
                    help="also run the APX9xx scale-invariance tier: "
                         "registered programs re-staged across the "
                         "swept mesh grid (schedule isomorphism, "
                         "collective-volume scaling laws vs the "
                         "per-mesh budgets.json rows, per-device "
                         "memory monotonicity, rule-table "
                         "divisibility)")
    ap.add_argument("--determinism", action="store_true",
                    help="also run the APX8xx determinism tier: "
                         "tick-path ordering/RNG/clock discipline, "
                         "fault-contract coverage, error-taxonomy "
                         "closure, and observe-name coherence over "
                         "the serving stack (pure AST, no jax)")
    ap.add_argument("--report", action="store_true",
                    help="with --cost: print the per-entry cost table "
                         "as JSON to stdout (findings go to stderr)")
    ap.add_argument("--write-budgets", action="store_true",
                    help="retrace the registry and regenerate "
                         "budgets.json (hand-tightened ceilings/caps "
                         "are preserved; stale entries are kept unless "
                         "--prune), then exit")
    ap.add_argument("--prune", action="store_true",
                    help="with --write-budgets: drop budgets.json "
                         "entries whose registry entry no longer "
                         "exists (each pruned name is printed)")
    ap.add_argument("--select", default=None, metavar="CODES",
                    help="comma-separated codes to report "
                         "(e.g. APX101,APX201)")
    ap.add_argument("--codes", default=None, metavar="GLOBS",
                    help="run a named subset of checks across tiers: "
                         "comma-separated codes or globs expanded "
                         "against the catalogue (e.g. APX511,APX70*); "
                         "the tiers owning the matched codes (--trace "
                         "for APX5xx, --cost for APX6xx, --sharding "
                         "for APX7xx, --determinism for APX8xx, "
                         "--scaling for APX9xx) are enabled "
                         "automatically and only the matched codes "
                         "are reported")
    ap.add_argument("--include-fixtures", action="store_true",
                    help="also lint files marked '# apxlint: fixture'")
    ap.add_argument("--list-codes", action="store_true",
                    help="print the error-code catalogue and exit")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code, doc in sorted(CODES.items()):
            print(f"{code}  {doc}")
        return 0

    if args.prune and not args.write_budgets:
        print("--prune only makes sense with --write-budgets",
              file=sys.stderr)
        return 2

    if args.write_budgets:
        from apex_tpu.lint.scaling import registry as scaling_registry
        from apex_tpu.lint.traced import budgets, registry

        registry.ensure_cpu_devices()
        reports = []
        findings = registry.run_entries(registry.repo_entries(),
                                        run_checks=False,
                                        cost_out=reports)
        # the scaling sweep's per-shape reports pin the <entry>@<tag>
        # rows alongside the base entries
        sweep_reports, sweep_findings = \
            scaling_registry.sweep_cost_reports()
        reports.extend(sweep_reports)
        findings.extend(sweep_findings)
        for f in findings:
            print(f.render(), file=sys.stderr)
        if findings:  # refuse to pin budgets from a broken trace
            return 1
        previous = budgets.load_manifest()
        if args.prune:
            for name in budgets.pruned_names(reports, previous):
                print(f"apxlint: pruned stale budget entry '{name}'")
        manifest = budgets.write_manifest(reports, previous=previous,
                                          prune=args.prune)
        print(f"apxlint: wrote {budgets.manifest_path()} "
              f"({len(manifest['entries'])} entries)")
        return 0

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",") if
                  c.strip()}
        unknown = select - set(CODES)
        if unknown:
            print(f"unknown codes: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    if args.codes:
        chosen = set()
        for pat in (p.strip().upper() for p in args.codes.split(",")):
            if not pat:
                continue
            hits = fnmatch.filter(CODES, pat)
            if not hits:
                print(f"--codes pattern {pat!r} matches no known code "
                      f"(see --list-codes)", file=sys.stderr)
                return 2
            chosen.update(hits)
        # enable the tiers that own the requested codes; pure-AST codes
        # run in every mode, --select filters the output either way
        if any(c.startswith("APX5") for c in chosen):
            args.trace = True
        if any(c.startswith("APX6") for c in chosen):
            args.cost = True
        if any(c.startswith("APX7") for c in chosen):
            args.sharding = True
        if any(c.startswith("APX8") for c in chosen):
            args.determinism = True
        if any(c.startswith("APX9") for c in chosen):
            args.scaling = True
        select = chosen if select is None else (select & chosen)

    paths = args.paths or ["apex_tpu"]
    reports: list = []
    sweep_timings: list = []
    findings, n_files = lint_paths(paths,
                                   include_fixtures=args.include_fixtures,
                                   trace=not args.no_trace,
                                   trace_registry=args.trace,
                                   cost_registry=args.cost,
                                   sharding_registry=args.sharding,
                                   scaling_registry=args.scaling,
                                   determinism=args.determinism,
                                   cost_report_out=reports,
                                   scaling_timings_out=sweep_timings,
                                   select=select)
    if sweep_timings:
        # per-shape staging cost, so the run_tests.sh wall budget is
        # attributable when the sweep grid or an entry grows
        total = sum(t for _, t in sweep_timings)
        shapes = ", ".join(f"{name} {t:.1f}s"
                           for name, t in sweep_timings)
        print(f"apxlint: scaling sweep {total:.1f}s over "
              f"{len(sweep_timings)} shape(s): {shapes}",
              file=sys.stderr)
    # in --report mode stdout carries ONLY the JSON table (CI pipes it
    # to an artifact file); findings move to stderr
    report_mode = args.report and args.cost
    out = sys.stderr if report_mode else sys.stdout
    for f in findings:
        print(f.render(), file=out)
    if report_mode:
        from apex_tpu.lint.traced import cost
        print(cost.render_table(reports))
    tail = f"{n_files} file(s) checked"
    if findings:
        print(f"apxlint: {len(findings)} finding(s), {tail}",
              file=sys.stderr)
        return 1
    print(f"apxlint: clean, {tail}", file=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""APX9xx — scale-invariance lint tier.

Every other traced tier verifies its contract at exactly one mesh
shape. This tier re-stages registered programs across a swept mesh grid
(:mod:`grid`) and verifies the properties that make a distributed
program *scale-invariant*:

- APX901 (:mod:`isomorphism`) — the collective schedule is the same
  program at every swept shape;
- APX902 (:mod:`volume`)      — per-collective bytes follow the
  entry's declared scaling law, pinned per shape in budgets.json;
- APX903 (:mod:`memory`)      — per-device state and peak-live bytes
  never grow with the data axis; APX703 re-run per shape;
- APX904 (:mod:`tables_check`) — rule tables cover their trees and
  divide evenly at every swept shape.

Entry points: :func:`registry.check_repo` (the lint driver),
:func:`registry.sweep_cost_reports` (the ``--write-budgets`` input).
"""

from apex_tpu.lint.scaling.grid import (  # noqa: F401
    FULL_GRID, HALO_GRID, ZERO_GRID, MeshShape, parse_tag,
)
from apex_tpu.lint.scaling.registry import (  # noqa: F401
    ScalingEntry, StagedShape, check_repo, repo_entries, run_entries,
    stage_entry, sweep_cost_reports,
)

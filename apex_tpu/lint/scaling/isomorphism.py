"""APX901 — collective-schedule isomorphism across swept mesh shapes.

APX511 proves all ranks of ONE mesh agree on the collective schedule;
this check proves the schedule is the *same program* at every swept
mesh size. Two obligations per entry:

1. **Per-shape agreement** — the APX511 simulator is re-issued at every
   swept shape (pairwise rank equality modulo axis index, ppermute
   well-formedness). A schedule that happens to agree at dp2 but
   branches on ``axis_index < 2`` diverges the moment dp grows; it
   fires here at the swept shape, re-coded APX901 with the shape tag.
2. **Cross-shape structural equality** — the rank-0 footprint of every
   ``shard_map`` body is normalized to its *structure*: collective
   items keep ``(primitive, axes)`` and drop byte counts; loop nesting
   is kept with scan lengths erased (trip counts may legally track a
   hyperparameter); a ``ppermute`` permutation is classified as a ring
   ``shift(delta)`` when it is a full single-step rotation of its axis,
   else kept verbatim. Structures must be identical across every swept
   shape — a hardcoded axis size shows up as an extra/missing
   collective, a diverging explicit permutation, or a shift whose
   delta moves with the mesh.

The normalization deliberately keeps a hardcoded permutation visible:
``[(0, 1), (1, 0)]`` classifies as ``shift(1)`` on a 2-ring but stays
an explicit pair list on a 4-ring, so sweeping cp flags it. A 2-ring
shift matches either rotation direction (delta +1 and -1 coincide at
size 2), so a reverse ring swept from cp2 to cp4 stays clean.
"""

import itertools
from typing import List, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.traced import jaxprlib as jl
from apex_tpu.lint.traced import schedule


def _classify_perm(perm: tuple, axis_size: int):
    """A full single-step-uniform rotation -> ('shift', delta, n);
    anything else stays ('perm', perm)."""
    if axis_size > 1 and len(perm) == axis_size:
        srcs = {p[0] for p in perm}
        if srcs == set(range(axis_size)):
            deltas = {(dst - src) % axis_size for src, dst in perm}
            if len(deltas) == 1:
                return ("shift", deltas.pop(), axis_size)
    return ("perm", tuple(tuple(p) for p in perm))


def _shift_equal(a, b) -> bool:
    """Two shift classifications are isomorphic when their deltas are
    congruent as signed single steps; on a 2-ring both directions
    coincide, so a size-2 shift matches any shift."""
    _, da, na = a
    _, db, nb = b
    if na == 2 or nb == 2:
        return True
    sa = da if da <= na // 2 else da - na
    sb = db if db <= nb // 2 else db - nb
    return sa == sb


def _structural(fp, axis_sizes) -> Tuple:
    out = []
    for item in fp:
        if item[0] == "coll":
            prim, axes, extra = item[1], item[2], item[3]
            if prim == "ppermute" and extra:
                n = 1
                for ax in axes:
                    n *= int(axis_sizes.get(ax, 1))
                out.append(("coll", prim, axes,
                            _classify_perm(extra[0], n)))
            else:
                out.append(("coll", prim, axes))
        elif item[0] == "scan":
            out.append(("scan", _structural(item[2], axis_sizes)))
        elif item[0] == "while":
            out.append(("while", _structural(item[1], axis_sizes),
                        _structural(item[2], axis_sizes)))
    return tuple(out)


def _iso_equal(a, b) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x[0] != y[0]:
            return False
        if x[0] == "coll":
            if x[1] != y[1] or x[2] != y[2]:
                return False
            xp = x[3] if len(x) > 3 else None
            yp = y[3] if len(y) > 3 else None
            if (xp is None) != (yp is None):
                return False
            if xp is not None:
                if xp[0] == "shift" and yp[0] == "shift":
                    if not _shift_equal(xp, yp):
                        return False
                elif xp != yp:
                    return False
        elif x[0] == "scan":
            if not _iso_equal(x[1], y[1]):
                return False
        elif x[0] == "while":
            if not (_iso_equal(x[1], y[1]) and _iso_equal(x[2], y[2])):
                return False
    return True


def _first_diff(a, b) -> str:
    for i, (x, y) in enumerate(itertools.zip_longest(a, b)):
        if x is None or y is None or not _iso_equal((x,), (y,)):
            return f"step {i}: {x!r} vs {y!r}"
    return f"lengths {len(a)} vs {len(b)}"


def shape_structures(closed) -> List[Tuple]:
    """Normalized rank-0 structural footprint per shard_map equation,
    in program order."""
    structures: List[Tuple] = []
    for eqn in jl.all_eqns(closed, into_pallas=False):
        if eqn.primitive.name != "shard_map":
            continue
        try:
            axis_sizes = dict(eqn.params["mesh"].shape)
        except Exception:  # noqa: BLE001
            axis_sizes = {}
        rank0 = {ax: 0 for ax in axis_sizes}
        fp = schedule._footprint(eqn.params["jaxpr"], {}, rank0)
        structures.append(_structural(fp, axis_sizes))
    return structures


def check(staged, path: str, entry) -> List[Finding]:
    findings: List[Finding] = []
    baseline = None
    base_tag = None
    for s in staged:
        tag = s.shape.tag
        # (1) APX511 re-issued at this shape, re-coded with the tag
        for f in schedule.check(s.closed, path, entry.name):
            findings.append(Finding(
                "APX901", path, 1, f"[{tag}] {f.message}"))
        # (2) structural comparison against the first staged shape
        try:
            structures = shape_structures(s.closed)
        except schedule._ScheduleError as e:
            findings.append(Finding(
                "APX901", path, 1,
                f"[{tag}] entry '{entry.name}': {e}"))
            continue
        if baseline is None:
            baseline, base_tag = structures, tag
            continue
        if len(structures) != len(baseline):
            findings.append(Finding(
                "APX901", path, 1,
                f"entry '{entry.name}': {len(structures)} shard_map "
                f"program(s) at {tag} vs {len(baseline)} at {base_tag} "
                f"— the staged program's structure depends on the mesh "
                f"size"))
            continue
        for i, (got, want) in enumerate(zip(structures, baseline)):
            if not _iso_equal(got, want):
                findings.append(Finding(
                    "APX901", path, 1,
                    f"entry '{entry.name}': collective schedule of "
                    f"shard_map {i} is not scale-invariant — "
                    f"{_first_diff(want, got)} between {base_tag} and "
                    f"{tag} (a schedule must be a function of axis "
                    f"names, not axis sizes)"))
                break
    return findings

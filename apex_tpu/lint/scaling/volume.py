"""APX902 — collective-volume scaling law over the swept mesh grid.

The APX6xx cost interpreter prices every collective of a staged
program; APX603 pins that number at one mesh shape. This check makes
the *function* bytes(mesh) part of the reviewed contract:

1. **Per-mesh pinned rows** — every swept shape's total collective
   volume must equal its ``<entry>@<tag>`` row in ``budgets.json``
   byte-exact (rows are written by ``--write-budgets``, pruned by
   ``--write-budgets --prune``). A missing, stale, or drifted row is a
   finding: a PR that changes the communication schedule at ANY swept
   shape must regenerate the manifest so the delta is reviewable.
2. **Declared scaling model** — each entry declares, per collective
   primitive, a basis of shape functions (e.g. the ZeRO law
   ``all_gather: flat_params(tp)``, ``reduce_scatter:
   flat_params(tp) * dp``). The measured bytes are least-squares
   fitted against the basis over the whole grid and must be
   reproduced exactly (0.5% / 64-byte slack for float fitting) at
   every shape — a hardcoded size or a rank-count branch bends the
   curve away from the declared law at some swept point.
3. **Super-linear drift guard** — a measured collective the model does
   not cover must still scale at most linearly along every swept axis:
   between two shapes differing in exactly one axis, the byte ratio
   may not exceed the axis-size ratio. Catches the classic
   quadratic-in-ranks regression (all-to-all emulated with per-pair
   sends) without requiring a model for every incidental collective.
"""

from typing import Dict, List, Tuple

from apex_tpu.lint import Finding

_FIT_RTOL = 0.005
_FIT_ATOL = 64
_DRIFT_TOL = 0.01


def _solve(ata: List[List[float]], atb: List[float]) -> List[float]:
    """Gaussian elimination with partial pivoting; singular columns get
    coefficient 0 (an over-parameterized basis is not an error)."""
    n = len(atb)
    a = [row[:] + [atb[i]] for i, row in enumerate(ata)]
    for col in range(n):
        piv = max(range(col, n), key=lambda r: abs(a[r][col]))
        if abs(a[piv][col]) < 1e-9:
            continue
        a[col], a[piv] = a[piv], a[col]
        for r in range(n):
            if r == col:
                continue
            f = a[r][col] / a[col][col]
            for c in range(col, n + 1):
                a[r][c] -= f * a[col][c]
    out = []
    for i in range(n):
        out.append(a[i][n] / a[i][i] if abs(a[i][i]) > 1e-9 else 0.0)
    return out


def fit(basis: Tuple[Tuple[str, object], ...], shapes,
        measured: List[float]) -> Tuple[List[float], List[float]]:
    """Least-squares coefficients for ``measured ~= sum c_j * f_j`` and
    the per-shape predictions."""
    design = [[float(fn(s)) for _, fn in basis] for s in shapes]
    k = len(basis)
    ata = [[sum(design[i][p] * design[i][q] for i in range(len(shapes)))
            for q in range(k)] for p in range(k)]
    atb = [sum(design[i][p] * measured[i] for i in range(len(shapes)))
           for p in range(k)]
    coeffs = _solve(ata, atb)
    preds = [sum(c * design[i][j] for j, c in enumerate(coeffs))
             for i in range(len(shapes))]
    return coeffs, preds


def _model_findings(staged, path: str, entry) -> List[Finding]:
    findings: List[Finding] = []
    model = entry.volume_model() if entry.volume_model else {}
    shapes = [s.shape for s in staged]
    per_coll: Dict[str, List[float]] = {}
    for s in staged:
        for prim in s.report.per_collective:
            per_coll.setdefault(prim, [])
    for prim in per_coll:
        per_coll[prim] = [float(s.report.per_collective.get(prim, 0))
                          for s in staged]

    for prim, measured in sorted(per_coll.items()):
        basis = model.get(prim)
        if basis is not None:
            coeffs, preds = fit(basis, shapes, measured)
            for s, m, p in zip(shapes, measured, preds):
                if abs(m - p) > max(_FIT_RTOL * m, _FIT_ATOL):
                    terms = ", ".join(
                        f"{c:.1f}*{name}"
                        for (name, _), c in zip(basis, coeffs))
                    findings.append(Finding(
                        "APX902", path, 1,
                        f"entry '{entry.name}': {prim} volume at "
                        f"{s.tag} is {int(m)} B but the declared "
                        f"scaling model fits {int(p)} B ({terms}) — "
                        f"the measured bytes(mesh) curve does not "
                        f"follow the declared law"))
            continue
        # no declared law: super-linear drift guard along single axes
        for i, si in enumerate(shapes):
            for j, sj in enumerate(shapes):
                diffs = [(a, getattr(si, a), getattr(sj, a))
                         for a in ("dp", "tp", "cp")
                         if getattr(si, a) != getattr(sj, a)]
                if len(diffs) != 1:
                    continue
                axis, vi, vj = diffs[0]
                if vj <= vi or measured[i] <= 0:
                    continue
                ratio = measured[j] / measured[i]
                if ratio > (vj / vi) * (1 + _DRIFT_TOL):
                    findings.append(Finding(
                        "APX902", path, 1,
                        f"entry '{entry.name}': {prim} volume grows "
                        f"super-linearly in {axis} — "
                        f"{int(measured[i])} B at {si.tag} vs "
                        f"{int(measured[j])} B at {sj.tag} "
                        f"(x{ratio:.2f} for a x{vj // vi} axis); "
                        f"declare a scaling model for it or fix the "
                        f"schedule"))
    for prim in sorted(set(model) - set(per_coll)):
        findings.append(Finding(
            "APX902", path, 1,
            f"entry '{entry.name}': declared scaling model covers "
            f"'{prim}' but no swept shape issues it — stale model"))
    return findings


def check(staged, path: str, entry, manifest) -> List[Finding]:
    from apex_tpu.lint.traced import budgets

    findings: List[Finding] = []
    base = entry.budget_name or entry.name
    # a missing or malformed manifest is reported once per run by
    # check_manifest_rows; here it just disables the row gate
    if manifest is not None and not budgets.validate(manifest):
        rows = manifest.get("entries", {})
        for s in staged:
            name = f"{base}@{s.shape.tag}"
            row = rows.get(name)
            if row is None:
                findings.append(Finding(
                    "APX902", path, 1,
                    f"entry '{entry.name}': no per-mesh budget row "
                    f"'{name}' — regenerate with "
                    f"`python -m apex_tpu.lint --write-budgets`"))
                continue
            got = s.report.collective_bytes
            if got != row["collective_bytes"]:
                findings.append(Finding(
                    "APX902", path, 1,
                    f"entry '{entry.name}': collective volume {got} B "
                    f"at {s.shape.tag} != pinned "
                    f"{row['collective_bytes']} B ('{name}') — the "
                    f"communication schedule changed at this mesh "
                    f"shape; regenerate budgets.json if intentional"))
    findings.extend(_model_findings(staged, path, entry))
    return findings


def check_manifest_rows(swept: Dict[str, set], manifest
                        ) -> List[Finding]:
    """Manifest-level findings, emitted once per run: a missing or
    malformed budgets.json, and stale ``@``-rows — every per-mesh row
    must belong to a registered sweep entry and a currently swept
    shape."""
    from apex_tpu.lint.traced import budgets

    findings: List[Finding] = []
    if manifest is None:
        if swept:
            findings.append(Finding(
                "APX902", budgets.manifest_path(), 1,
                "budgets.json does not exist — seed it (and the "
                "per-mesh @-rows) with "
                "`python -m apex_tpu.lint --write-budgets`"))
        return findings
    errs = budgets.validate(manifest)
    if errs:
        findings.append(Finding(
            "APX902", budgets.manifest_path(), 1,
            "budgets.json fails schema validation: " + "; ".join(errs)))
        return findings
    if not swept:
        # no volume sweep ran (e.g. a --codes-narrowed run over table
        # entries only) — nothing to compare the @-rows against
        return findings
    rows = (manifest or {}).get("entries", {})
    for name in sorted(rows):
        if "@" not in name:
            continue
        b, _, tag = name.partition("@")
        if tag not in swept.get(b, ()):
            findings.append(Finding(
                "APX902", budgets.manifest_path(), 1,
                f"budgets.json per-mesh row '{name}' matches no "
                f"registered sweep shape — regenerate with "
                f"`python -m apex_tpu.lint --write-budgets --prune`"))
    return findings

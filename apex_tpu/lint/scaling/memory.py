"""APX903 — per-device memory must not grow with the mesh.

The point of sharding is that adding devices shrinks (or at worst
holds) every device's footprint. Three obligations per swept entry,
all evaluated along the ``dp`` axis within each (tp, cp) family:

1. **Optimizer-state bytes** — the entry's declared per-device state
   accounting (e.g. ``DistributedFusedAdam.state_bytes_per_device``)
   must be non-increasing in dp. A ZeRO shard that stops scaling —
   a spec flipped back to replicated, a buffer sized off the global
   rather than the local batch — shows up as a flat or rising curve.
2. **Per-device peak-live** — the APX5xx liveness walk
   (:func:`apex_tpu.lint.traced.cost._peak_live`) re-run on every
   ``shard_map`` body at every swept shape; the maximum body peak must
   be non-increasing in dp. This is the device-local number (the
   body sees local shapes), unlike APX604's whole-program estimate.
3. **Replication taint** — the APX703 walk (rule-derived in_specs
   survive into the traced ``shard_map``; no large replicated
   dot_general operand) re-issued at every swept shape, re-coded
   APX903 with the shape tag. A spec that degenerates only at tp=4
   fires here, not on a pod.
"""

from typing import Dict, List, Tuple

from apex_tpu.lint import Finding


def body_peak_live(closed) -> int:
    """Max peak-live over every shard_map body of the staged program —
    the per-device high-water estimate at this shape."""
    from apex_tpu.lint.traced import cost
    from apex_tpu.lint.traced import jaxprlib as jl

    peak = 0
    for eqn in jl.all_eqns(closed, into_pallas=False):
        if eqn.primitive.name == "shard_map":
            peak = max(peak, cost._peak_live(eqn.params["jaxpr"]))
    return peak


def _dp_families(staged) -> Dict[Tuple[int, int], list]:
    """(tp, cp) -> staged shapes sorted by dp (only families with at
    least two dp points can express a monotonicity claim)."""
    fams: Dict[Tuple[int, int], list] = {}
    for s in staged:
        fams.setdefault((s.shape.tp, s.shape.cp), []).append(s)
    return {k: sorted(v, key=lambda s: s.shape.dp)
            for k, v in fams.items() if len(v) > 1}


def _monotone(series, path: str, entry, what: str) -> List[Finding]:
    findings: List[Finding] = []
    for (prev_shape, prev), (cur_shape, cur) in zip(series, series[1:]):
        if cur > prev:
            findings.append(Finding(
                "APX903", path, 1,
                f"entry '{entry.name}': per-device {what} grows with "
                f"the data axis — {prev} B at {prev_shape.tag} but "
                f"{cur} B at {cur_shape.tag}; adding data-parallel "
                f"devices must never cost a device memory"))
    return findings


def check(staged, path: str, entry) -> List[Finding]:
    from apex_tpu.lint.sharded import propagation

    findings: List[Finding] = []
    for fam in _dp_families(staged).values():
        if entry.state_bytes is not None:
            findings.extend(_monotone(
                [(s.shape, int(entry.state_bytes(s.shape)))
                 for s in fam],
                path, entry, "optimizer-state bytes"))
        findings.extend(_monotone(
            [(s.shape, body_peak_live(s.closed)) for s in fam],
            path, entry, "peak-live estimate"))
    for s in staged:
        if s.in_specs is None:
            continue
        for f in propagation.check(s.closed, s.in_specs, path, entry):
            findings.append(Finding(
                "APX903", path, 1, f"[{s.shape.tag}] {f.message}"))
    return findings

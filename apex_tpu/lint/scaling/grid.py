"""Swept mesh shapes for the APX9xx scale-invariance tier.

A :class:`MeshShape` is one point of the sweep: ``(dp, tp, cp)`` sizes
for the ``data`` / ``model`` / ``context`` axes (``pipe`` stays 1 — the
pipeline schedules carry their own per-stage entries in the trace
tier). Each shape renders to a stable *tag* (``dp4xtp2``,
``dp1xtp1xcp2``) used to key the per-mesh budget rows in
``budgets.json`` (``<entry>@<tag>``) and to label findings.

The default grids fit the 8-virtual-device CPU world the dryrun phases
use (``ensure_cpu_devices``): the ZeRO train-step grid covers
dp ∈ {2, 4, 8} × tp = 1, dp ∈ {2, 4} × tp = 2, and dp = 2 × tp = 4;
dp8 × tp2 (16 devices) is the one point of the full dp∈{2,4,8} ×
tp∈{1,2} product that cannot be staged on 8 devices — it joins the
grid automatically on a larger world only if a future PR raises the
device count AND regenerates budgets.json. The halo grid sweeps the
``context`` ring at cp ∈ {2, 4}. The union is 8 distinct shapes.
"""

from typing import NamedTuple, Tuple


class MeshShape(NamedTuple):
    """One swept mesh point: axis sizes for data/model/context."""
    dp: int = 1
    tp: int = 1
    cp: int = 1

    @property
    def devices(self) -> int:
        return self.dp * self.tp * self.cp

    @property
    def tag(self) -> str:
        t = f"dp{self.dp}xtp{self.tp}"
        if self.cp > 1:
            t += f"xcp{self.cp}"
        return t

    def axis_sizes(self) -> dict:
        """Mesh-axis name -> size at this shape (pipe always 1)."""
        from apex_tpu.transformer import parallel_state as ps

        return {ps.DATA_AXIS: self.dp, ps.PIPE_AXIS: 1,
                ps.CONTEXT_AXIS: self.cp, ps.TENSOR_AXIS: self.tp}


#: dp x tp sweep for the ZeRO train step (6 shapes, all <= 8 devices).
ZERO_GRID: Tuple[MeshShape, ...] = (
    MeshShape(dp=2, tp=1),
    MeshShape(dp=4, tp=1),
    MeshShape(dp=8, tp=1),
    MeshShape(dp=2, tp=2),
    MeshShape(dp=4, tp=2),
    MeshShape(dp=2, tp=4),
)

#: context-ring sweep for the spatial bottleneck halo exchange.
HALO_GRID: Tuple[MeshShape, ...] = (
    MeshShape(dp=1, tp=1, cp=2),
    MeshShape(dp=1, tp=1, cp=4),
)

#: every distinct shape any entry sweeps — the grid the rule-table
#: scale-safety audit (APX904) runs its divisibility pass over.
FULL_GRID: Tuple[MeshShape, ...] = ZERO_GRID + HALO_GRID


def parse_tag(tag: str) -> MeshShape:
    """Inverse of :attr:`MeshShape.tag` (raises ValueError on junk)."""
    import re

    m = re.fullmatch(r"dp(\d+)xtp(\d+)(?:xcp(\d+))?", tag)
    if not m:
        raise ValueError(f"not a mesh-shape tag: {tag!r}")
    return MeshShape(dp=int(m.group(1)), tp=int(m.group(2)),
                     cp=int(m.group(3) or 1))


__all__ = ["MeshShape", "ZERO_GRID", "HALO_GRID", "FULL_GRID",
           "parse_tag"]

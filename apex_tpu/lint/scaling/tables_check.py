"""APX904 — partition-rule tables must be safe at every swept shape.

The sharded tier (APX701) proves a rule table covers its trees with no
dead or ambiguous rules — a shape-independent property. This check adds
the shape-dependent half, across the full sweep grid:

1. **Coverage under the sweep** — the APX701 coverage/dead-rule
   analysis is re-issued (through the same :mod:`rules_check`
   implementation, so the two tiers cannot drift) and re-coded APX904:
   a table registered for scaling must hold its own contract before
   divisibility even makes sense.
2. **Divisibility audit** — for every matched leaf, every sharded dim
   must divide evenly by the product of its mesh-axis sizes at every
   swept shape. ``dim % axis_size != 0`` is exactly the crash an
   8-chip pod produces from a table that looked fine at tp=2: a head
   count of 2 sharded over ``model`` works at tp<=2 and throws at
   tp=4. The finding names the leaf, the dim, the axes, and every
   failing shape tag, so the fix (pad the dim, gate the shape, or
   re-spec the rule) is mechanical.
"""

from typing import List

from apex_tpu.lint import Finding


class _Apx701Shim:
    """The slice of a sharded-tier entry that rules_check's APX701 half
    reads; the APX702 derived-tree attributes are disabled so only the
    coverage analysis runs under the sweep."""

    def __init__(self, entry):
        self.name = entry.name
        self.rules = entry.rules
        self.trees = entry.trees
        self.optimizer_families = ()
        self.reference_specs = None
        self.kv_cache_tree = None
        self.qkv_kernel_re = ""


def _spec_dim_axes(spec) -> List[tuple]:
    """(dim, (axis, ...)) per sharded dim of a PartitionSpec."""
    out = []
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        out.append((dim, tuple(entry) if isinstance(entry, tuple)
                    else (entry,)))
    return out


def divisibility_findings(entry, path: str) -> List[Finding]:
    from apex_tpu.partition import rule_match_table

    rules = tuple(entry.rules())
    trees = entry.trees() if entry.trees is not None else {}
    findings: List[Finding] = []
    for tree_name, tree in sorted(trees.items()):
        for leaf_path, leaf, hits in rule_match_table(rules, tree):
            if len(hits) != 1:
                continue  # uncovered/ambiguous: APX904 coverage finding
            spec = rules[hits[0]][1]
            shape = tuple(getattr(leaf, "shape", ()))
            for dim, axes in _spec_dim_axes(spec):
                if dim >= len(shape):
                    continue  # rank mismatch: APX904 coverage finding
                bad = []
                for mesh in entry.grid:
                    sizes = mesh.axis_sizes()
                    prod = 1
                    for ax in axes:
                        prod *= int(sizes.get(ax, 1))
                    if prod > 1 and shape[dim] % prod != 0:
                        bad.append((mesh.tag, prod))
                if bad:
                    tags = ", ".join(
                        f"{t} ({p} ways)" for t, p in bad)
                    findings.append(Finding(
                        "APX904", path, 1,
                        f"entry '{entry.name}': '{tree_name}' leaf "
                        f"'{leaf_path}' dim {dim} (size {shape[dim]}) "
                        f"shards over {list(axes)} but does not divide "
                        f"at swept shape(s) {tags} — rule "
                        f"{rules[hits[0]][0]!r} would crash there"))
    return findings


def check(entry, path: str) -> List[Finding]:
    from apex_tpu.lint.sharded import rules_check

    findings = [
        Finding("APX904", f.path, f.line, f.message)
        for f in rules_check.check(_Apx701Shim(entry), path)
        if f.code == "APX701"
    ]
    findings.extend(divisibility_findings(entry, path))
    return findings

"""Scaling-tier entry registry and driver (APX901-904).

A :class:`ScalingEntry` names either a *swept program* — a builder
``build(shape) -> (fn, args, in_specs)`` re-staged under
``jax.make_jaxpr`` at every :class:`~apex_tpu.lint.scaling.grid
.MeshShape` of its grid — or a *rule table* audited for scale safety
across the same grid. Every other tier verifies its contract at exactly
one mesh shape; this tier is the claim that those contracts are
functions of *axis names*, not axis sizes:

- ``schedule``  -> APX901 (:mod:`isomorphism`): the APX511 per-rank
  simulator re-issued at every swept shape, plus cross-shape structural
  equality of the collective schedule;
- ``volume``    -> APX902 (:mod:`volume`): per-collective bytes from
  the APX6xx cost interpreter fitted against the entry's declared
  scaling model, pinned byte-exact per shape in ``budgets.json``
  (``<entry>@<tag>`` rows written by ``--write-budgets``);
- ``memory``    -> APX903 (:mod:`memory`): per-device optimizer-state
  and peak-live bytes non-increasing in dp, and the APX703
  replicated-operand taint walk re-run at every shape;
- ``tables``    -> APX904 (:mod:`tables_check`): the APX701
  coverage/dead-rule analysis re-issued under the sweep plus a
  divisibility audit — any ``dim % axis_size != 0`` a table or a staged
  operand would induce at a swept shape is a finding here, not a crash
  on an 8-chip pod.

The driver mirrors the trace tier's contract: abstract staging only
(``jax.make_jaxpr``, CPU-safe), parallel state snapshotted/restored
around every shape, and a shape that fails to stage is an APX100
finding, never a silent skip.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.scaling.grid import (
    FULL_GRID, HALO_GRID, ZERO_GRID, MeshShape,
)
from apex_tpu.lint.traced.registry import (
    _mesh,
    _module_path,
    _restore_parallel_state,
    _snapshot_parallel_state,
    bottleneck_parts,
    ensure_cpu_devices,
    zero_parts,
)

#: APX703 re-run floor, same default as the sharded tier.
_REPLICATION_FLOOR = 1 << 20


@dataclass
class ScalingEntry:
    name: str
    module: str  # dotted module whose scaling contract this verifies
    # swept program: shape -> (fn, args, in_specs); staged per shape
    build: Optional[Callable[[MeshShape], Tuple[Callable, tuple, Any]]] = None
    grid: Tuple[MeshShape, ...] = FULL_GRID
    checks: Tuple[str, ...] = ("schedule", "volume", "memory")
    # APX902: collective primitive -> ((term_name, fn(shape)->float),
    # ...) basis; measured bytes must be a non-negative combination of
    # the terms, exact at every swept shape (see volume.py)
    volume_model: Optional[
        Callable[[], Dict[str, Tuple[Tuple[str, Callable], ...]]]] = None
    # APX903: declared per-device optimizer-state bytes at rest
    state_bytes: Optional[Callable[[MeshShape], int]] = None
    # APX904: rule table + abstract trees audited across the grid
    rules: Optional[Callable[[], tuple]] = None
    trees: Optional[Callable[[], Dict[str, Any]]] = None
    replication_floor: int = _REPLICATION_FLOOR
    budget_name: Optional[str] = None  # base name of the @-rows


@dataclass
class StagedShape:
    """One staged sweep point, shared by every checker."""
    shape: MeshShape
    closed: Any        # jax.make_jaxpr output
    in_specs: Any
    report: Any        # traced.cost.CostReport (entry name '<base>@<tag>')


def stage_entry(entry: ScalingEntry, *,
                findings: Optional[List[Finding]] = None,
                timings_out: Optional[list] = None
                ) -> List[StagedShape]:
    """Stage ``entry.build`` at every grid shape; APX100 per failure.
    ``timings_out`` collects ``('<base>@<tag>', seconds)`` per shape."""
    import time

    import jax

    from apex_tpu.lint.traced import cost

    path = _module_path(entry.module)
    base = entry.budget_name or entry.name
    staged: List[StagedShape] = []
    if entry.build is None:
        return staged
    for shape in entry.grid:
        t0 = time.monotonic()
        snap = _snapshot_parallel_state()
        try:
            try:
                have = jax.device_count()
                if have < shape.devices:
                    raise RuntimeError(
                        f"shape {shape.tag} needs {shape.devices} "
                        f"devices, have {have} (backend initialized "
                        f"before ensure_cpu_devices)")
                _mesh(tp=shape.tp, cp=shape.cp,
                      n_devices=shape.devices)()
                fn, args, in_specs = entry.build(shape)
                closed = jax.make_jaxpr(fn)(*args)
            finally:
                _restore_parallel_state(snap)
            report = cost.compute(closed, path, f"{base}@{shape.tag}")
        except Exception as exc:  # noqa: BLE001 - surfaced as a finding
            if findings is not None:
                findings.append(Finding(
                    "APX100", path, 1,
                    f"scaling entry '{entry.name}' failed to stage at "
                    f"{shape.tag}: {type(exc).__name__}: {exc}"))
            continue
        finally:
            if timings_out is not None:
                timings_out.append(
                    (f"{base}@{shape.tag}", time.monotonic() - t0))
        staged.append(StagedShape(shape, closed, in_specs, report))
    return staged


def run_entries(entries: List[ScalingEntry], *,
                manifest: Any = "__load__",
                cost_out: Optional[list] = None,
                timings_out: Optional[list] = None) -> List[Finding]:
    """All scaling-tier findings. ``manifest`` is the budgets.json dict
    (or the default sentinel to load the committed one) for APX902's
    per-mesh volume gate; ``cost_out`` collects the per-shape
    CostReports (the ``--write-budgets`` path); ``timings_out``
    collects ``(entry@tag, seconds)`` per staged shape so run_tests.sh
    can report where the wall budget goes."""
    ensure_cpu_devices()
    from apex_tpu.lint.scaling import (
        isomorphism, memory, tables_check, volume,
    )
    from apex_tpu.lint.traced import budgets

    if manifest == "__load__":
        manifest = budgets.load_manifest()

    findings: List[Finding] = []
    swept_rows: Dict[str, set] = {}
    for e in entries:
        path = _module_path(e.module)
        staged = stage_entry(e, findings=findings,
                             timings_out=timings_out)
        if cost_out is not None:
            cost_out.extend(s.report for s in staged)
        base = e.budget_name or e.name
        # @-rows exist only for volume-checked entries; schedule- or
        # memory-only sweeps never consult the manifest
        if staged and "volume" in e.checks:
            swept_rows.setdefault(base, set()).update(
                s.shape.tag for s in staged)
        if "schedule" in e.checks and staged:
            findings.extend(isomorphism.check(staged, path, e))
        if "volume" in e.checks and staged:
            findings.extend(volume.check(staged, path, e, manifest))
        if "memory" in e.checks and staged:
            findings.extend(memory.check(staged, path, e))
        if "tables" in e.checks:
            try:
                findings.extend(tables_check.check(e, path))
            except Exception as exc:  # noqa: BLE001 - surfaced
                findings.append(Finding(
                    "APX100", path, 1,
                    f"scaling entry '{e.name}' table audit failed to "
                    f"evaluate: {type(exc).__name__}: {exc}"))
    findings.extend(volume.check_manifest_rows(swept_rows, manifest))
    return findings


# ---------------------------------------------------------------------------
# registered sweeps
# ---------------------------------------------------------------------------

def _zero_flat_local_bytes(tp: int) -> int:
    """Exact fp32 byte size of the ZeRO flat master buffer built from
    the TP-local gpt_tiny param shard — the ``P(tp)`` every declared
    ZeRO volume law below is stated in. Uses the same
    ``flatten.make_spec`` row layout the optimizer uses, so per-leaf
    ALIGN_ROWS padding is part of the law, not noise around it."""
    import jax

    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.multi_tensor_apply import flatten as _flatten
    from apex_tpu.partition import gpt_rules, match_partition_rules
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.lint.traced.registry import _local_shapes

    params = jax.eval_shape(
        lambda k: init_gpt(k, gpt_tiny()), jax.random.PRNGKey(0))
    specs = match_partition_rules(gpt_rules(), params)
    local = _local_shapes(params, specs, {ps.TENSOR_AXIS: tp})
    spec = _flatten.make_spec(jax.tree_util.tree_leaves(local))
    return spec.total_rows * _flatten.LANES * 4


def _zero_volume_model():
    """The ZeRO communication law under the APX6xx pricing convention
    (rendezvous volume = operand bytes x axis size; the wire-level
    ``(dp-1)/dp`` ring refinement divides out of every cross-shape
    comparison):

    - ``reduce_scatter`` (grad psum_scatter over ``data``):
      ``P(tp) * dp`` — the whole TP-local flat grad buffer enters the
      rendezvous on each of the dp ranks;
    - ``all_gather`` (master-row regather over ``data``): ``P(tp)`` —
      each rank contributes its 1/dp row shard, dp ranks;
    - ``psum`` (TP activation reductions + the scalar loss pmean):
      ``A * tp + 4 * dp`` with the activation coefficient fitted (the
      local batch is fixed per data rank, so it is dp-independent);
    - ``pmax`` (vocab-parallel CE max over the ``model`` shard):
      ``B * tp``, coefficient fitted.
    """
    P = _zero_flat_local_bytes
    return {
        "reduce_scatter": (
            ("flat_params(tp)*dp", lambda s: float(P(s.tp) * s.dp)),),
        "all_gather": (
            ("flat_params(tp)", lambda s: float(P(s.tp))),),
        "psum": (
            ("act*tp", lambda s: float(s.tp)),
            ("loss_pmean*dp", lambda s: float(4 * s.dp)),),
        "pmax": (
            ("ce_max*tp", lambda s: float(s.tp)),),
    }


def _zero_state_bytes(shape: MeshShape) -> int:
    """Declared per-device ZeRO optimizer-state bytes at rest (the
    ~1/dp claim) — ``DistributedFusedAdam.state_bytes_per_device`` over
    the TP-local gpt_tiny shard at this shape."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.models.gpt import gpt_tiny, init_gpt
    from apex_tpu.partition import gpt_rules, match_partition_rules
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.lint.traced.registry import _local_shapes

    params = jax.eval_shape(
        lambda k: init_gpt(k, gpt_tiny()), jax.random.PRNGKey(0))
    specs = match_partition_rules(gpt_rules(), params)
    local = _local_shapes(params, specs, {ps.TENSOR_AXIS: shape.tp})
    opt = DistributedFusedAdam(dp_size=shape.dp, m_dtype=jnp.bfloat16)
    return opt.state_bytes_per_device(local)


def _halo_volume_model():
    """The context-ring halo law: each rank ships one fixed-width halo
    strip left and one right per conv, so the priced ppermute volume
    (bytes x hop count) is linear in cp with a fitted per-hop
    coefficient. Anything super-linear means the halo width grew with
    the ring — a hardcoded-size bug."""
    return {"ppermute": (("halo*cp", lambda s: float(s.cp)),)}


def _sharded_table_trees():
    """name -> (rules, trees) for every rule table the sharded tier
    registers, re-used for the APX904 audit so the two tiers can never
    drift apart on what a 'registered table' is."""
    from apex_tpu.lint.sharded import registry as sharded

    out = {}
    for e in sharded.repo_entries():
        if e.trees is not None:
            out[e.name] = (e.rules, e.trees)
    return out


def _draft_medium_trees():
    """The medium-config drafter trees: the serving headline pairs
    ``draft_gpt_medium`` with ``gpt_medium`` on ONE mesh, so its param
    tree and lockstep cache must survive the same swept tp sizes as the
    target's — a head count indivisible at a swept tp fires APX904 here
    before the drafter ever shares a pod slice."""
    import functools as ft

    import jax

    from apex_tpu.models.gpt import draft_gpt_medium, init_gpt
    from apex_tpu.serving.cache import init_cache

    cfg = draft_gpt_medium()
    params = jax.eval_shape(
        lambda k: init_gpt(k, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(ft.partial(init_cache, cfg, 2, 37))
    return {"params": params, "kv_cache": cache}


def repo_entries() -> List[ScalingEntry]:
    from apex_tpu.partition import draft_gpt_rules

    entries = [
        # the ROADMAP item-5 headline program swept across the whole
        # (dp, tp) grid — gpt_tiny_dp4xtp2_zero's shape is one point;
        # every shape's collective volume is pinned byte-exact in
        # budgets.json as gpt_tiny_zero@<tag>
        ScalingEntry(
            "gpt_tiny_zero_sweep",
            "apex_tpu.contrib.optimizers.distributed_fused_adam",
            build=lambda shape: zero_parts(dp=shape.dp, tp=shape.tp),
            grid=ZERO_GRID,
            checks=("schedule", "volume", "memory"),
            volume_model=_zero_volume_model,
            state_bytes=_zero_state_bytes,
            budget_name="gpt_tiny_zero"),
        # the context-parallel halo exchange swept across ring sizes —
        # the cp axis's first scale-invariance coverage (ROADMAP item
        # 5's ring-attention prerequisite)
        ScalingEntry(
            "bottleneck_halo_sweep",
            "apex_tpu.contrib.bottleneck.bottleneck",
            build=lambda shape: bottleneck_parts(),
            grid=HALO_GRID,
            checks=("schedule", "volume", "memory"),
            volume_model=_halo_volume_model,
            budget_name="bottleneck_halo"),
    ]
    # one table-audit entry per sharded-tier rule table, plus the
    # medium drafter trees against the draft table (the tp-envelope the
    # serving headline actually needs)
    for name, (rules, trees) in sorted(_sharded_table_trees().items()):
        entries.append(ScalingEntry(
            f"{name}_scale", "apex_tpu.partition.tables",
            checks=("tables",), rules=rules, trees=trees,
            grid=FULL_GRID))
    entries.append(ScalingEntry(
        "gpt_draft_medium_rules_scale", "apex_tpu.partition.tables",
        checks=("tables",), rules=draft_gpt_rules,
        trees=_draft_medium_trees, grid=FULL_GRID))
    return entries


def sweep_cost_reports() -> Tuple[list, List[Finding]]:
    """Per-shape CostReports for every swept entry — the
    ``--write-budgets`` input that regenerates the @-tagged rows."""
    findings: List[Finding] = []
    reports: list = []
    for e in repo_entries():
        if e.build is None:
            continue
        reports.extend(
            s.report for s in stage_entry(e, findings=findings))
    return reports, findings


def check_repo() -> List[Finding]:
    return run_entries(repo_entries())

"""Kernel-contract checks: APX101 (in-place aliasing) and APX103
(fp32 statistics tiles).

**APX101** — the optimizer kernels update state buffers in place; the
whole one-pass-over-HBM design rests on ``input_output_aliases``. The
repo's kernels follow a strict naming convention: an input ref
``X_ref`` whose updated value is written to an output ``X_out`` (same
stem) IS an in-place update, and the ``pallas_call`` must declare the
matching ``{input_operand_index: output_index}`` alias — otherwise XLA
materializes a second buffer and the "donated" state silently doubles
its HBM footprint. The check maps kernel parameters to operands
positionally (inputs = first ``len(in_specs)`` params, outputs next),
so it only fires when the call site's spec lists are statically
countable; ``*refs``-style kernels are skipped, never guessed at.

**APX103** — flash attention keeps its online-softmax statistics
(running max ``m``, normalizer ``l``, logsumexp ``lse``) and layer norm
its ``mean``/``rstd`` in fp32 even when ``_P_BF16`` casts the
probability tiles to bf16: the normalizer sums the fp32 tile *before*
the cast, and a half-precision ``l`` or ``lse`` corrupts every row that
spans more than one k tile. The check flags (a) stores into a
stats-named ref that round through ``astype(bf16/f16)``, (b) stats
scratch buffers allocated below fp32, (c) stats outputs whose
``ShapeDtypeStruct`` dtype is below fp32.
"""

import ast
from typing import Dict, List, Optional

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import (
    attr_chain,
    call_name,
    functions_in,
    kwarg,
    static_elements,
    static_len,
)

_STATS_STEMS = {"m", "l", "lse", "mean", "rstd"}
_LOW_PRECISION = {"bfloat16", "float16"}


def _stem(param: str) -> str:
    for suffix in ("_ref", "_out"):
        if param.endswith(suffix):
            return param[: -len(suffix)]
    return param


def _kernel_name(node: ast.AST) -> Optional[str]:
    """First positional arg of pallas_call: a function name, possibly
    wrapped in functools.partial."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call) and call_name(node) == "partial":
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
    return None


def _alias_map(node: Optional[ast.AST]) -> Optional[Dict[int, int]]:
    """Literal ``{in_operand: out_index}`` dict; {} if absent; None if
    present but not statically readable."""
    if node is None:
        return {}
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[int, int] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, int)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, int)):
            return None
        out[k.value] = v.value
    return out


def _is_low_precision(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    chain = attr_chain(node)
    return bool(chain) and chain[-1] in _LOW_PRECISION


def _downcasts(expr: ast.AST) -> bool:
    """Does the expression round through astype(bf16/f16) anywhere?"""
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "astype" and n.args
                and _is_low_precision(n.args[0])):
            return True
    return False


def check_module(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    defs: Dict[str, ast.FunctionDef] = {}
    for fn in functions_in(tree):
        # first definition wins; ambiguous names are skipped below
        defs.setdefault(fn.name, fn)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) == "pallas_call" and node.args):
            continue
        kname = _kernel_name(node.args[0])
        kernel = defs.get(kname) if kname else None
        if kernel is None:
            continue

        n_in = static_len(kwarg(node, "in_specs"))
        n_out = static_len(kwarg(node, "out_specs"))
        params = [a.arg for a in kernel.args.posonlyargs + kernel.args.args]
        if n_in is None:
            continue
        if n_out is None:
            if kwarg(node, "scratch_shapes") is not None:
                continue  # can't split outputs from scratch params
            n_out = len(params) - n_in
        if n_out < 0 or len(params) < n_in + n_out:
            continue

        in_params = params[:n_in]
        out_params = params[n_in:n_in + n_out]
        scratch_params = params[n_in + n_out:]

        findings.extend(_check_aliases(node, kernel, path, in_params,
                                       out_params))
        findings.extend(_check_stats_decls(node, path, out_params,
                                           scratch_params))
    findings.extend(_check_stats_stores(tree, path, defs))
    return findings


def _check_aliases(node: ast.Call, kernel: ast.FunctionDef, path: str,
                   in_params: List[str],
                   out_params: List[str]) -> List[Finding]:
    aliases = _alias_map(kwarg(node, "input_output_aliases"))
    if aliases is None:
        return []
    in_stems: Dict[str, int] = {}
    dup = set()
    for i, p in enumerate(in_params):
        s = _stem(p)
        dup.add(s) if s in in_stems else in_stems.setdefault(s, i)
    findings = []
    for o, p in enumerate(out_params):
        s = _stem(p)
        if s in dup or s not in in_stems:
            continue
        i = in_stems[s]
        if aliases.get(i) != o:
            findings.append(Finding(
                "APX101", path, node.lineno,
                f"kernel '{kernel.name}' writes output '{p}' from input "
                f"'{in_params[i]}' (same stem '{s}') but pallas_call "
                f"declares no input_output_aliases entry {{{i}: {o}}} — "
                "the in-place update materializes a second HBM buffer"))
    return findings


def _check_stats_decls(node: ast.Call, path: str, out_params: List[str],
                       scratch_params: List[str]) -> List[Finding]:
    findings = []
    scratch = static_elements(kwarg(node, "scratch_shapes")) or []
    for p, elem in zip(scratch_params, scratch):
        if _stem(p) not in _STATS_STEMS:
            continue
        if (isinstance(elem, ast.Call) and len(elem.args) >= 2
                and _is_low_precision(elem.args[1])):
            findings.append(Finding(
                "APX103", path, elem.lineno,
                f"stats scratch '{p}' allocated in reduced precision — "
                "online-softmax statistics must stay fp32"))
    outs = static_elements(kwarg(node, "out_shape")) or []
    for p, elem in zip(out_params, outs):
        if _stem(p) not in _STATS_STEMS:
            continue
        if (isinstance(elem, ast.Call) and len(elem.args) >= 2
                and _is_low_precision(elem.args[1])):
            findings.append(Finding(
                "APX103", path, elem.lineno,
                f"stats output '{p}' declared in reduced precision — "
                "lse/mean/rstd residuals must stay fp32"))
    return findings


def _check_stats_stores(tree: ast.Module, path: str,
                        defs: Dict[str, ast.FunctionDef]) -> List[Finding]:
    """(a) of APX103: any ``m_ref[...] = (...).astype(bf16)`` store, in
    any function — stats refs are unambiguous by naming convention, so
    this needs no call-site mapping and also covers ``*refs`` kernels
    (where the refs are rebound via ``next(it)``)."""
    findings = []
    seen = set()
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)):
                continue
            name = t.value.id
            if not name.endswith(("_ref", "_out")):
                continue
            if _stem(name) not in _STATS_STEMS:
                continue
            if _downcasts(node.value) and node.lineno not in seen:
                seen.add(node.lineno)
                findings.append(Finding(
                    "APX103", path, node.lineno,
                    f"store into stats ref '{name}' rounds through a "
                    "reduced-precision astype — m/l/lse/mean/rstd must "
                    "stay fp32 (even under _P_BF16)"))
    return findings

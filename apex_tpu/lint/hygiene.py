"""Tracer-hygiene checks (APX401, APX402).

A function traced by jax (a ``jit``/``grad``/``scan`` body, a
``custom_vjp`` rule, a Pallas kernel) runs ONCE at trace time; any host
state it reads is baked into the compiled program as a constant. A
``time.time()`` timestamp, an ``np.random`` draw, or a mutated global
inside such a function is a silent staleness bug: the program keeps
replaying the value captured at trace time. Host-side code (metrics,
mesh initialization) is free to do all of these — so the check first
builds the set of functions *reachable from a trace root* and only
flags violations inside that set.

Trace roots in a module: functions decorated with (or passed to)
``jax.custom_vjp``/``custom_jvp``/``jit``/``checkpoint``/``remat``,
arguments of ``.defvjp(...)``, Pallas kernel bodies (first argument of
``pallas_call``, through ``functools.partial``), and named functions
passed to ``grad``/``value_and_grad``/``vjp``/``vmap``/``pmap``/
``shard_map``/``scan``/``cond``/``switch``/``while_loop``/
``fori_loop``. Reachability closes transitively over calls to
module-local function names.

Host-module references (``time``, ``random``, ``numpy``/``np.random``,
``datetime``) are matched against the module's actual imports, so
``from jax import random`` never false-positives.

Roots also propagate *across modules*: ``jax.jit(sample_tokens)`` in
``serving/scheduler.py`` makes ``sample_tokens`` — defined in
``serving/sampling.py`` — a traced body, even though sampling.py itself
never mentions jit. :func:`check_files` collects such imported-name
roots per file (via the importing module's ``from apex_tpu.x import
name`` statements), maps each dotted module back to its file in the
linted set, and seeds them into that file's reachability frontier.

Beyond the stdlib host modules, apex_tpu's OWN host state is
registered: ``serving.faults`` (fault schedules, call counters),
``serving.health`` (``ServingStats`` degradation counters, replica
health ladders), ``serving.observe`` (tracer flags, metric registries,
flight-recorder rings), ``serving.transfer`` (handoff attempt
counters), and ``serving.router`` (replica roles, admission charges)
exist to be mutated between ticks, so reading them inside a
traced body freezes a counter value into the compiled program — the
canonical staleness bug this tier exists for. Any use of those
modules' stateful classes — or of a module-level instance constructed
from them — inside a reachable function is APX401 (see
``_HOST_STATE_MODULES``/``_HOST_STATE_SYMBOLS`` and the
``apx401_hoststate_*`` / ``apx401_observe_*`` fixtures).
"""

import ast
import os
from typing import Dict, Iterable, List, Set, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import attr_chain, call_name

_TRANSFORMS = {
    "jit", "grad", "value_and_grad", "vjp", "jvp", "vmap", "pmap",
    "shard_map", "scan", "cond", "switch", "while_loop", "fori_loop",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "pallas_call",
    "named_call",
}
_DECORATOR_ROOTS = {"custom_vjp", "custom_jvp", "jit", "checkpoint",
                    "remat"}

#: apex_tpu modules whose contents are host state by design: their
#: counters/schedules mutate between scheduler ticks, so a traced body
#: reading them bakes one stale value into the compiled program.
_HOST_STATE_MODULES = {"apex_tpu.serving.faults",
                       "apex_tpu.serving.health",
                       "apex_tpu.serving.observe",
                       "apex_tpu.serving.transfer",
                       "apex_tpu.serving.router",
                       "apex_tpu.serving.tenancy",
                       "apex_tpu.serving.streaming"}
#: The stateful classes those modules export (re-exported by
#: ``apex_tpu.serving``); instances are mutated on the host every tick.
_HOST_STATE_SYMBOLS = {"FaultInjector", "ServingStats", "Tracer",
                       "MetricsRegistry", "FlightRecorder",
                       "PageTransfer", "ReplicaHealth",
                       "DisaggregatedRouter", "TenancyPolicy",
                       "StreamMux"}


def _host_modules(tree: ast.Module) -> Dict[str, str]:
    """Local alias -> canonical host-module name, from this module's
    imports only."""
    out: Dict[str, str] = {}
    interesting = {"time", "random", "numpy", "datetime"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root in interesting:
                    out[a.asname or root] = root
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "numpy":
                for a in node.names:
                    if a.name == "random":
                        out[a.asname or "random"] = "numpy.random"
    return out


def _host_state_names(tree: ast.Module) -> Dict[str, str]:
    """Local alias -> origin for names bound to serving fault/health
    host state: imports of the registered modules or their stateful
    classes (from the defining module or the ``apex_tpu.serving``
    re-export), plus module-level instances constructed from an
    imported stateful class (``STATS = ServingStats()``)."""
    names: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ImportFrom) and node.module
                and not node.level):
            continue
        if node.module in _HOST_STATE_MODULES:
            for a in node.names:
                if a.name != "*":
                    names[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        elif node.module.split(".")[0] == "apex_tpu":
            for a in node.names:
                full = f"{node.module}.{a.name}"
                if full in _HOST_STATE_MODULES \
                        or a.name in _HOST_STATE_SYMBOLS:
                    names[a.asname or a.name] = full
    if not names:
        return names
    for node in tree.body:  # module-level singletons only
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if isinstance(value, ast.Call) and call_name(value) in names:
            for t in targets:
                if isinstance(t, ast.Name):
                    names[t.id] = f"{names[call_name(value)]} instance"
    return names


def _function_table(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    table: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            table.setdefault(n.name, n)
    return table


def _decorator_is_root(dec: ast.AST) -> bool:
    chain = attr_chain(dec if not isinstance(dec, ast.Call) else dec.func)
    if chain and chain[-1] in _DECORATOR_ROOTS:
        return True
    # @functools.partial(jax.custom_vjp, ...) / @partial(jit, ...)
    if isinstance(dec, ast.Call) and call_name(dec) == "partial" \
            and dec.args:
        inner = attr_chain(dec.args[0])
        return bool(inner) and inner[-1] in _DECORATOR_ROOTS
    return False


def _roots(tree: ast.Module, table: Dict[str, ast.FunctionDef]
           ) -> Set[str]:
    roots: Set[str] = set()
    for fn in table.values():
        if any(_decorator_is_root(d) for d in fn.decorator_list):
            roots.add(fn.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        is_defvjp = (isinstance(node.func, ast.Attribute)
                     and node.func.attr in ("defvjp", "defjvp"))
        if name not in _TRANSFORMS and not is_defvjp:
            continue
        args = list(node.args)
        # functools.partial(kernel, ...) as a pallas_call argument
        for a in list(args):
            if isinstance(a, ast.Call) and call_name(a) == "partial":
                args.extend(a.args)
        for a in args:
            if isinstance(a, ast.Name) and a.id in table:
                roots.add(a.id)
    return roots


def _calls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            out.add(n.func.id)
        elif isinstance(n, ast.Name):
            # a bare reference (closure capture, callback arg) keeps the
            # callee reachable too
            out.add(n.id)
    return out


def _import_map(tree: ast.Module) -> Dict[str, Tuple[str, str]]:
    """Local alias -> (dotted apex_tpu module, original name) for every
    ``from apex_tpu.x import name [as alias]`` in this module."""
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom) and node.module
                and node.module.split(".")[0] == "apex_tpu"
                and not node.level):
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = (node.module, a.name)
    return out


def _external_roots(tree: ast.Module) -> Set[Tuple[str, str]]:
    """(dotted module, function name) pairs this module passes into a
    tracing transform — roots it creates in OTHER files."""
    imports = _import_map(tree)
    if not imports:
        return set()
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        is_defvjp = (isinstance(node.func, ast.Attribute)
                     and node.func.attr in ("defvjp", "defjvp"))
        if name not in _TRANSFORMS and not is_defvjp:
            continue
        args = list(node.args)
        for a in list(args):
            if isinstance(a, ast.Call) and call_name(a) == "partial":
                args.extend(a.args)
        for a in args:
            if isinstance(a, ast.Name) and a.id in imports:
                out.add(imports[a.id])
    return out


def _resolve_module(dotted: str, trees: Dict[str, ast.Module]
                    ) -> str:
    rel = dotted.replace(".", os.sep)
    suffixes = (os.sep + rel + ".py",
                os.sep + rel + os.sep + "__init__.py")
    for path in trees:
        if path.endswith(suffixes):
            return path
    return ""


def check_files(trees: Dict[str, ast.Module]) -> List[Finding]:
    """Project pass: per-module hygiene with cross-module root
    propagation (the only way a ``jax.jit(imported_fn)`` call site can
    taint the defining module)."""
    extra: Dict[str, Set[str]] = {}
    for tree in trees.values():
        for dotted, fname in _external_roots(tree):
            target = _resolve_module(dotted, trees)
            if target:
                extra.setdefault(target, set()).add(fname)
    findings: List[Finding] = []
    for path in sorted(trees):
        findings.extend(check_module(
            trees[path], path, extra_roots=sorted(extra.get(path, ()))))
    return findings


def check_module(tree: ast.Module, path: str,
                 extra_roots: Iterable[str] = ()) -> List[Finding]:
    table = _function_table(tree)
    host = _host_modules(tree)
    host_state = _host_state_names(tree)
    if not table:
        return []
    reachable = set()
    frontier = list(_roots(tree, table))
    frontier.extend(n for n in extra_roots if n in table)
    while frontier:
        name = frontier.pop()
        if name in reachable or name not in table:
            continue
        reachable.add(name)
        frontier.extend(_calls(table[name]) & set(table))

    findings: List[Finding] = []
    seen: Set[int] = set()
    for name in sorted(reachable):
        fn = table[name]
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                if node.lineno not in seen:
                    seen.add(node.lineno)
                    findings.append(Finding(
                        "APX402", path, node.lineno,
                        f"'global {', '.join(node.names)}' inside "
                        f"'{name}', which is reachable from a traced "
                        "body — trace-time global mutation is baked in "
                        "as a constant"))
                continue
            if host_state and isinstance(node, (ast.Attribute,
                                                ast.Name)):
                chain = attr_chain(node)
                if chain and chain[0] in host_state \
                        and node.lineno not in seen:
                    seen.add(node.lineno)
                    findings.append(Finding(
                        "APX401", path, node.lineno,
                        f"serving host state '{'.'.join(chain)}' "
                        f"({host_state[chain[0]]}) inside '{name}', "
                        "which is reachable from a traced body — fault "
                        "schedules and ServingStats counters mutate "
                        "between ticks; a traced read freezes one "
                        "stale value into the compiled program"))
                continue
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[0] not in host:
                continue
            root = host[chain[0]]
            full = [root] + chain[1:]
            bad = (
                root == "time"
                or root == "random"
                or root == "numpy.random"
                or (root == "numpy" and len(full) > 1
                    and full[1] == "random")
                or (root == "datetime" and full[-1] in ("now", "today",
                                                        "utcnow"))
            )
            if bad and node.lineno not in seen:
                seen.add(node.lineno)
                findings.append(Finding(
                    "APX401", path, node.lineno,
                    f"host-state read '{'.'.join(chain)}' inside "
                    f"'{name}', which is reachable from a traced body — "
                    "the value is frozen at trace time"))
    return findings

"""apxlint driver: file walking, suppression comments, check dispatch.

The engine owns everything that is not a check: collecting ``.py``
files, parsing them once, reading ``# apxlint: disable=CODE`` comments
(flagged line, or a standalone comment line directly above it), and
skipping ``# apxlint: fixture`` files during directory walks so the
known-bad test fixtures don't fail the repo-wide run while still being
lintable when passed as explicit paths.

Checks come in two shapes:

- per-file AST checks (``kernels``, ``collectives``) get
  ``(tree, path)`` and return findings;
- project checks run once over the whole file set: ``amp_lists`` (needs
  the op-list module and every call site together), ``hygiene`` (roots
  jitted callables across module boundaries, so
  ``jax.jit(imported_fn)`` in one file taints the defining file),
  ``meta`` (APX105 tier-coverage of pallas_call families — needs only
  the registries' module lists, no jax import), and ``vmem`` (the
  trace-time budget evaluation of the registered kernel configs,
  skipped with ``trace=False``);
- the trace tier (``trace_registry=True`` / CLI ``--trace``) walks the
  ``apex_tpu.lint.traced`` entry registry under ``jax.make_jaxpr`` and
  runs the APX5xx jaxpr-level verifiers. Its findings land on the
  traced module's file at line 1 and pass through the same suppression
  machinery (use ``# apxlint: disable-file=CODE`` — trace findings have
  no meaningful source line);
- the cost tier (``cost_registry=True`` / CLI ``--cost``) shares the
  trace tier's single ``jax.make_jaxpr`` pass, computes a per-entry
  :class:`~apex_tpu.lint.traced.cost.CostReport`, and gates it against
  ``budgets.json`` (APX601-604, same line-1 attribution);
- the sharding tier (``sharding_registry=True`` / CLI ``--sharding``)
  walks the ``apex_tpu.lint.sharded`` entry registry: partition-rule
  table coverage, cross-tree spec consistency, and rule-staged
  shard_map verification (APX701-704, same line-1 attribution);
- the determinism tier (``determinism=True`` / CLI ``--determinism``)
  is a project check like ``hygiene``: a pure-AST pass over the
  serving-scope files (any ``serving/`` directory in the linted set)
  checking tick-path ordering, fault-contract coverage, taxonomy
  closure, observe coherence, and RNG key discipline (APX801-805);
- the scaling tier (``scaling_registry=True`` / CLI ``--scaling``)
  re-stages the ``apex_tpu.lint.scaling`` sweep entries across a
  parametrized mesh grid: collective-schedule isomorphism, volume
  scaling laws against per-mesh budget rows, per-device memory
  monotonicity, and rule-table divisibility (APX901-904, same line-1
  attribution).
"""

import ast
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from apex_tpu.lint import CODES, Finding

_SUPPRESS_RE = re.compile(r"#\s*apxlint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*apxlint:\s*disable-file=([A-Z0-9,\s]+)")
_FIXTURE_RE = re.compile(r"#\s*apxlint:\s*fixture")
_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", ".pytest_cache",
              "build", "dist"}


def collect_files(paths: Sequence[str],
                  include_fixtures: bool = False) -> List[str]:
    """Expand files/directories into a sorted list of lintable .py files."""
    out: Set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            out.add(os.path.abspath(p))  # explicit paths always lint
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
            for f in files:
                if not f.endswith(".py"):
                    continue
                fp = os.path.abspath(os.path.join(root, f))
                if not include_fixtures and is_fixture_file(fp):
                    continue
                out.add(fp)
    return sorted(out)


def is_fixture_file(path: str) -> bool:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            head = "".join(fh.readline() for _ in range(3))
    except OSError:
        return False
    return bool(_FIXTURE_RE.search(head))


def parse_suppressions(src: str) -> Dict[int, Set[str]]:
    """Map line number -> suppressed codes on that line.

    An inline comment suppresses its own line; a standalone comment line
    suppresses itself and the following line, so multi-code disables can
    sit above long statements.
    """
    sup: Dict[int, Set[str]] = {}
    lines = src.splitlines()
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        sup.setdefault(i, set()).update(codes)
        if line.lstrip().startswith("#"):  # standalone comment line
            sup.setdefault(i + 1, set()).update(codes)
    return sup


def parse_file_suppressions(src: str) -> Set[str]:
    """Codes suppressed for the whole file.

    ``# apxlint: disable-file=CODE[,CODE...]`` on any comment-only line
    (conventionally the module header) suppresses those codes at every
    line of the file — the shape needed for trace-tier findings, which
    are attributed to the traced module at line 1 rather than to the
    specific equation's source line.
    """
    out: Set[str] = set()
    for line in src.splitlines():
        if not line.lstrip().startswith("#"):
            continue
        m = _SUPPRESS_FILE_RE.search(line)
        if m:
            out.update(c.strip() for c in m.group(1).split(",")
                       if c.strip())
    return out


def _read(path: str) -> Optional[str]:
    try:
        with tokenize.open(path) as fh:  # honors PEP 263 encodings
            return fh.read()
    except (OSError, SyntaxError, UnicodeDecodeError):
        return None


def lint_paths(paths: Sequence[str], *, include_fixtures: bool = False,
               trace: bool = True, trace_registry: bool = False,
               cost_registry: bool = False,
               sharding_registry: bool = False,
               scaling_registry: bool = False,
               determinism: bool = False,
               cost_report_out: Optional[list] = None,
               scaling_timings_out: Optional[list] = None,
               select: Optional[Iterable[str]] = None
               ) -> Tuple[List[Finding], int]:
    """Run all checks over ``paths``; returns (findings, files_checked)."""
    from apex_tpu.lint import amp_lists, collectives, hygiene, kernels, quant

    files = collect_files(paths, include_fixtures=include_fixtures)
    findings: List[Finding] = []
    sources: Dict[str, str] = {}
    trees: Dict[str, ast.Module] = {}

    for path in files:
        src = _read(path)
        if src is None:
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                "APX100", path, e.lineno or 1,
                f"file does not parse: {e.msg}"))
            continue
        sources[path] = src
        trees[path] = tree
        for checker in (kernels, quant, collectives):
            findings.extend(checker.check_module(tree, path))

    findings.extend(hygiene.check_files(trees))
    findings.extend(amp_lists.check_files(trees))
    from apex_tpu.lint import meta
    findings.extend(meta.check_files(trees))
    if determinism:
        # pure-AST like hygiene/meta — no jax import, no execution
        from apex_tpu.lint import determinism as det
        findings.extend(det.check_files(trees))
    if (trace or trace_registry or cost_registry or sharding_registry
            or scaling_registry):
        # must precede first backend touch: the sharded entries (vmem's
        # bottleneck config, the trace tier's mesh entries) need the
        # 8-device CPU world
        from apex_tpu.lint.traced.registry import ensure_cpu_devices
        ensure_cpu_devices()
    if trace:
        from apex_tpu.lint import vmem
        findings.extend(vmem.check_repo())
    if trace_registry or cost_registry:
        from apex_tpu.lint import traced

        reports = cost_report_out if cost_report_out is not None else []
        findings.extend(traced.run_entries(
            traced.repo_entries(), run_checks=trace_registry,
            cost_out=reports if cost_registry else None))
        if cost_registry:
            from apex_tpu.lint.traced import budgets
            findings.extend(budgets.check(reports,
                                          budgets.load_manifest()))
    if sharding_registry:
        from apex_tpu.lint import sharded

        findings.extend(sharded.run_entries(sharded.repo_entries()))
    if scaling_registry:
        from apex_tpu.lint import scaling

        findings.extend(scaling.run_entries(
            scaling.repo_entries(), timings_out=scaling_timings_out))

    findings = _apply_suppressions(findings, sources)
    if select is not None:
        keep = tuple(select)
        findings = [f for f in findings if f.code.startswith(keep)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, len(trees)


def _apply_suppressions(findings: List[Finding],
                        sources: Dict[str, str]) -> List[Finding]:
    by_file: Dict[str, Dict[int, Set[str]]] = {}
    file_wide: Dict[str, Set[str]] = {}
    out = []
    for f in findings:
        if f.code not in CODES:
            raise ValueError(f"checker emitted unregistered code {f.code}")
        if f.path not in by_file:
            src = sources.get(f.path)
            if src is None:  # trace-tier path outside the linted set
                src = _read(f.path) or ""
            by_file[f.path] = parse_suppressions(src)
            file_wide[f.path] = parse_file_suppressions(src)
        if f.code in file_wide.get(f.path, ()):
            continue
        if f.code in by_file.get(f.path, {}).get(f.line, ()):
            continue
        out.append(f)
    return out

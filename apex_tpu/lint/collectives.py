"""Collective-order and axis-resolution checks (APX201, APX202).

**APX201** — inside a ``shard_map`` or scanned-schedule body every
participant must issue the same collectives in the same order; a
``psum`` that only some ranks reach is a multi-chip deadlock, not an
error message. Statically, the dangerous shape is a *rank-dependent*
conditional (a Python ``if`` whose predicate derives from
``axis_index`` / ``process_index`` / a ``parallel_state`` rank or stage
query) whose branches trace different collective sequences. The check
symbolically executes each function body, building the set of
collective sequences along every path (early returns terminate a
path), and compares the branch path-sets at each rank-dependent split.
Config-dependent branches (``if cp > 1:``, ``if p.dtype == bool:``)
are trace-time constants — identical on every rank — and are *not*
compared, which keeps the check silent on the static dispatch branches
in ``mappings.py`` / ``context_parallel.py``. ``lax.cond`` /
``lax.switch`` branch callables execute under a traced predicate, so
those are always compared when they resolve to local functions.

**APX202** — every axis name handed to a collective must resolve to a
``parallel_state`` mesh axis (or an axis literally declared in the same
file via ``Mesh``/``PartitionSpec``/``axis_name=`` — the test-local
mesh idiom). Axis arguments are resolved through string literals,
``ps.X_AXIS`` constants, module-level aliases (``_AXIS =
ps.TENSOR_AXIS``), parameter defaults, and single local assignments;
anything unresolvable is skipped, never guessed.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from apex_tpu.lint import Finding
from apex_tpu.lint.astutil import attr_chain, call_name, walk_scope

# collectives whose relative order is a cross-chip contract
_ORDERED = {"psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
            "all_gather", "all_to_all", "psum_scatter", "all_to_all_p"}
# axis-consuming calls checked by APX202 (ordered ones + index queries)
_AXIS_USERS = _ORDERED | {"axis_index", "axis_size"}
# (call name -> positional index of the axis-name argument)
_AXIS_ARG_POS = {name: 1 for name in _ORDERED}
_AXIS_ARG_POS.update({"axis_index": 0, "axis_size": 0})

_RANKISH_NAMES = re.compile(
    r"(^|_)(rank|stage)(_|$)|axis_index|process_index")
_MAX_PATHS = 64


def _parallel_state_axes() -> Set[str]:
    """Mesh axis names, read from parallel_state.py's own AST (no jax
    import needed at lint time)."""
    ps_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "transformer", "parallel_state.py")
    axes: Set[str] = set()
    try:
        with open(ps_path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return {"data", "pipe", "context", "model"}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                _AXIS_CONSTANTS[t.id] = node.value.value
                axes.add(node.value.value)
    return axes or {"data", "pipe", "context", "model"}


_AXIS_CONSTANTS: Dict[str, str] = {}  # e.g. DATA_AXIS -> "data"
_VALID_AXES: Optional[Set[str]] = None


def _valid_axes() -> Set[str]:
    global _VALID_AXES
    if _VALID_AXES is None:
        _VALID_AXES = _parallel_state_axes()
    return _VALID_AXES


def _local_axes(tree: ast.Module) -> Set[str]:
    """Axis names declared in this file: strings inside Mesh()/P()/
    PartitionSpec()/make_mesh() calls and axis_name(s)= kwargs."""
    axes: Set[str] = set()

    def strings_under(node):
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                axes.add(n.value)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in ("Mesh", "AbstractMesh", "make_mesh", "P",
                    "PartitionSpec"):
            strings_under(node)
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                strings_under(kw.value)
    return axes


class _Env:
    """Name -> axis-string resolution context for one function."""

    def __init__(self, module_aliases: Dict[str, str]):
        self.names: Dict[str, str] = dict(module_aliases)
        self.rank_vars: Set[str] = set()


def _module_aliases(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        val = _resolve_axis_expr(node.value, None)
        if val is not None:
            out[node.targets[0].id] = val
    return out


def _resolve_axis_expr(node: ast.AST,
                       env: Optional["_Env"]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        if node.attr in _AXIS_CONSTANTS:
            return _AXIS_CONSTANTS[node.attr]
        return None
    if isinstance(node, ast.Name) and env is not None:
        return env.names.get(node.id)
    return None


def _axis_arg(call: ast.Call) -> Optional[ast.AST]:
    name = call_name(call)
    kw_axis = None
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            kw_axis = kw.value
    pos = _AXIS_ARG_POS.get(name)
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return kw_axis


def _resolved_axes(call: ast.Call, env: _Env) -> Tuple[List[str], bool]:
    """(resolved axis names, fully_resolved). Tuples resolve per-element."""
    arg = _axis_arg(call)
    if arg is None:
        return [], False
    nodes = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
    out, complete = [], True
    for n in nodes:
        v = _resolve_axis_expr(n, env)
        if v is None:
            complete = False
        else:
            out.append(v)
    return out, complete


def _seed_env(fn: ast.FunctionDef, env: _Env) -> None:
    """Parameter defaults and simple local assigns, for axis resolution
    and rank-variable tracking."""
    a = fn.args
    pos = a.posonlyargs + a.args
    for param, default in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        v = _resolve_axis_expr(default, env)
        if v is not None:
            env.names[param.arg] = v
    for param, default in zip(a.kwonlyargs, a.kw_defaults):
        if default is not None:
            v = _resolve_axis_expr(default, env)
            if v is not None:
                env.names[param.arg] = v
    for node in walk_scope(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        v = _resolve_axis_expr(node.value, env)
        if v is not None:
            env.names.setdefault(tgt, v)
        if _is_rankish(node.value, env):
            env.rank_vars.add(tgt)


def _is_rankish(expr: ast.AST, env: _Env) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            if n.id in env.rank_vars or _RANKISH_NAMES.search(n.id):
                return True
        elif isinstance(n, ast.Attribute):
            if _RANKISH_NAMES.search(n.attr):
                return True
    return False


# -- path-sensitive collective sequences ------------------------------------

_Event = Tuple[str, Tuple[str, ...]]
_PathSet = Set[Tuple[_Event, ...]]


class _TooManyPaths(Exception):
    pass


def _expr_events(node: ast.AST, env: _Env,
                 defs: Dict[str, ast.FunctionDef],
                 depth: int) -> List[_Event]:
    """Collective events issued while evaluating an expression, in
    source order. Calls to local functions contribute their (merged)
    sequences only when unambiguous; unknown callees are opaque."""
    events: List[_Event] = []
    for n in ast.iter_child_nodes(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        events.extend(_expr_events(n, env, defs, depth))
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _ORDERED:
            axes, _ = _resolved_axes(node, env)
            events.append((name, tuple(axes)))
        elif name in ("cond", "switch"):
            pass  # handled as a statement-level split by the caller
        elif (isinstance(node.func, ast.Name) and node.func.id in defs
                and depth < 4):
            sub = defs[node.func.id]
            seqs = _function_paths(sub, env, defs, depth + 1)
            if len(seqs) == 1:
                events.extend(next(iter(seqs)))
            # divergent callees are reported at their own definition
    return events


def _branch_paths(call: ast.Call, env: _Env,
                  defs: Dict[str, ast.FunctionDef],
                  depth: int) -> Optional[List[_PathSet]]:
    """Path-sets of lax.cond/lax.switch branch callables that resolve
    to local named functions; None when any branch is opaque."""
    branches = []
    args = call.args[1:]
    if (call_name(call) == "switch" and len(args) == 1
            and isinstance(args[0], (ast.List, ast.Tuple))):
        args = args[0].elts
    for a in args:
        if isinstance(a, ast.Name) and a.id in defs:
            branches.append(_function_paths(defs[a.id], env, defs,
                                            depth + 1))
        elif isinstance(a, ast.Lambda):
            evs = tuple(_expr_events(a.body, env, defs, depth + 1))
            branches.append({evs})
        else:
            return None
    return branches if len(branches) >= 2 else None


def _stmt_paths(stmts, env, defs, depth, findings, path):
    """Returns (open_paths, closed_paths) for a statement list."""
    open_paths: _PathSet = {()}
    closed: _PathSet = set()

    def extend(events: List[_Event]):
        nonlocal open_paths
        if events:
            open_paths = {p + tuple(events) for p in open_paths}

    for stmt in stmts:
        if isinstance(stmt, ast.If):
            cond_events = _expr_events(stmt.test, env, defs, depth)
            extend(cond_events)
            t_open, t_closed = _stmt_paths(stmt.body, env, defs, depth,
                                           findings, path)
            e_open, e_closed = _stmt_paths(stmt.orelse, env, defs, depth,
                                           findings, path)
            if _is_rankish(stmt.test, env):
                t_all = t_open | t_closed
                e_all = e_open | e_closed
                if t_all != e_all:
                    findings.append(Finding(
                        "APX201", path, stmt.lineno,
                        "collective sequence differs between the "
                        "branches of this rank-dependent conditional "
                        f"({_describe(t_all)} vs {_describe(e_all)}) — "
                        "ranks would issue mismatched collectives"))
            new_open = {p + b for p in open_paths for b in t_open | e_open}
            closed |= {p + b for p in open_paths for b in t_closed | e_closed}
            open_paths = new_open
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                extend(_expr_events(stmt.value, env, defs, depth))
            closed |= open_paths
            open_paths = set()
            break
        elif isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                extend(_expr_events(stmt.iter, env, defs, depth))
            else:
                extend(_expr_events(stmt.test, env, defs, depth))
            b_open, b_closed = _stmt_paths(stmt.body, env, defs, depth,
                                           findings, path)
            closed |= {p + b for p in open_paths for b in b_closed}
            open_paths = {p + b for p in open_paths for b in b_open}
        elif isinstance(stmt, (ast.With, ast.Try)):
            body = stmt.body
            b_open, b_closed = _stmt_paths(body, env, defs, depth,
                                           findings, path)
            closed |= {p + b for p in open_paths for b in b_closed}
            open_paths = {p + b for p in open_paths for b in b_open}
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            continue
        else:
            for call in _calls_in_order(stmt):
                if call_name(call) in ("cond", "switch"):
                    branches = _branch_paths(call, env, defs, depth)
                    if branches:
                        base = branches[0]
                        for other in branches[1:]:
                            if other != base:
                                findings.append(Finding(
                                    "APX201", path, call.lineno,
                                    "lax.cond/lax.switch branches trace "
                                    "different collective sequences "
                                    f"({_describe(base)} vs "
                                    f"{_describe(other)})"))
                                break
                        if len(base) == 1:
                            extend(list(next(iter(base))))
            extend(_expr_events(stmt, env, defs, depth))
        if len(open_paths) + len(closed) > _MAX_PATHS:
            raise _TooManyPaths()
    return open_paths, closed


def _calls_in_order(stmt: ast.AST) -> List[ast.Call]:
    return [n for n in walk_scope(stmt) if isinstance(n, ast.Call)]


def _describe(paths: _PathSet) -> str:
    names = sorted({",".join(e[0] for e in p) or "<none>" for p in paths})
    return "{" + " | ".join(names[:4]) + "}"


def _function_paths(fn, env, defs, depth) -> _PathSet:
    sub_env = _Env(env.names)
    _seed_env(fn, sub_env)
    try:
        o, c = _stmt_paths(fn.body, sub_env, defs, depth, [], "")
    except (_TooManyPaths, RecursionError):
        return {()}
    return (o | c) or {()}


# -- module entry ------------------------------------------------------------

def check_module(tree: ast.Module, path: str) -> List[Finding]:
    # prescan: every APX201 event and APX202 axis argument originates
    # at a call whose name is in _AXIS_USERS (local callees included —
    # they live in this same module). A module with none can produce
    # no finding, so skip the exponential path enumeration outright.
    if not any(isinstance(n, ast.Call) and call_name(n) in _AXIS_USERS
               for n in ast.walk(tree)):
        return []
    findings: List[Finding] = []
    aliases = _module_aliases(tree)
    valid = _valid_axes() | _local_axes(tree)
    defs: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            defs.setdefault(n.name, n)

    # APX202: every resolvable axis argument must name a mesh axis
    for fn in defs.values():
        env = _Env(aliases)
        _seed_env(fn, env)
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _AXIS_USERS:
                continue
            axes, _ = _resolved_axes(node, env)
            for ax in axes:
                if ax not in valid:
                    findings.append(Finding(
                        "APX202", path, node.lineno,
                        f"collective axis {ax!r} is not a parallel_state "
                        f"mesh axis (known: {sorted(valid)[:8]})"))

    # APX201: rank-dependent branch divergence, per function
    for fn in defs.values():
        env = _Env(aliases)
        _seed_env(fn, env)
        local: List[Finding] = []
        try:
            _stmt_paths(fn.body, env, defs, 0, local, path)
        except (_TooManyPaths, RecursionError):
            continue
        findings.extend(local)
    return findings

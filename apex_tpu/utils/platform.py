"""Platform detection helpers.

Pallas kernels compile only on TPU backends; on CPU (the unit-test rig runs
on an 8-virtual-device CPU mesh) they run in interpreter mode. Every Pallas
entry point in this package accepts ``interpret=None`` meaning "pick
automatically via :func:`pallas_interpret`".
"""

import functools

import jax


@functools.cache
def has_tpu() -> bool:
    """True when the default backend exposes TPU devices (incl. tunneled
    platforms whose device_kind reports a TPU chip)."""
    try:
        devs = jax.devices()
    except RuntimeError:
        return False
    if not devs:
        return False
    d = devs[0]
    plat = (getattr(d, "platform", "") or "").lower()
    kind = (getattr(d, "device_kind", "") or "").lower()
    return "tpu" in plat or "tpu" in kind


def interpret_default() -> bool:
    """Default value for ``pallas_call(interpret=...)``: interpret off-TPU."""
    return not has_tpu()


def pallas_interpret(interpret=None) -> bool:
    """Resolve a user-supplied ``interpret`` flag (None → auto)."""
    if interpret is None:
        return interpret_default()
    return bool(interpret)

"""Platform detection helpers.

Pallas kernels compile only on TPU backends; on CPU (the unit-test rig runs
on an 8-virtual-device CPU mesh) they run in interpreter mode. Every Pallas
entry point in this package accepts ``interpret=None`` meaning "pick
automatically via :func:`pallas_interpret`".
"""

import functools
import os

import jax


def apply_test_platform_override() -> bool:
    """Honor ``APEX_TPU_TEST_PLATFORM`` via ``jax.config`` — the ONLY
    mechanism that works on hosts whose sitecustomize imports jax at
    interpreter startup (plain ``JAX_PLATFORMS`` in the env is latched
    away before it can apply, including for subprocesses). Must be
    called BEFORE any device use. For ``cpu``,
    ``APEX_TPU_TEST_NUM_DEVICES`` (default 8, the test rig's mesh
    width) sizes the virtual device world. Returns True when an
    override was applied. Entry points that tests drive as
    subprocesses (bench.py, examples) call this at import time."""
    plat = os.environ.get("APEX_TPU_TEST_PLATFORM")
    if not plat:
        return False
    jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        n = int(os.environ.get("APEX_TPU_TEST_NUM_DEVICES", "8"))
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            # older jax: fall back to the XLA flag (read at backend
            # init, so this still works when called before device use)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}")
    return True


@functools.cache
def has_tpu() -> bool:
    """True when the default backend exposes TPU devices (incl. tunneled
    platforms whose device_kind reports a TPU chip)."""
    try:
        devs = jax.devices()
    except RuntimeError:
        return False
    if not devs:
        return False
    d = devs[0]
    plat = (getattr(d, "platform", "") or "").lower()
    kind = (getattr(d, "device_kind", "") or "").lower()
    return "tpu" in plat or "tpu" in kind


def interpret_default() -> bool:
    """Default value for ``pallas_call(interpret=...)``: interpret off-TPU."""
    return not has_tpu()


def pallas_interpret(interpret=None) -> bool:
    """Resolve a user-supplied ``interpret`` flag (None → auto)."""
    if interpret is None:
        return interpret_default()
    return bool(interpret)

"""Training metrics (ref: the ``AverageMeter`` the examples roll by hand in
``examples/imagenet/main_amp.py``, promoted to a shared utility)."""

import time
from typing import Optional


class AverageMeter:
    def __init__(self, name: str = "", fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})").format(
            name=self.name, val=self.val, avg=self.avg)


class Throughput:
    """samples/sec with device-sync-aware timing: call ``start()`` after the
    warmup step (first call compiles), ``tick(n)`` per step."""

    def __init__(self):
        self._t0: Optional[float] = None
        self.samples = 0

    def start(self):
        self._t0 = time.perf_counter()
        self.samples = 0

    def tick(self, n: int):
        self.samples += n

    @property
    def per_sec(self) -> float:
        if self._t0 is None:
            return 0.0
        dt = time.perf_counter() - self._t0
        return self.samples / dt if dt > 0 else 0.0

"""Version-portability shims for jax API renames.

The CI rig pins an older jax than the driver; every shim here keeps ONE
call site per renamed API so the rest of the package never branches on
jax versions. (Siblings: ``utils.pallas.dimsem`` for the
``TPUCompilerParams`` rename, ``transformer.parallel_state.shard_map``
for the ``check_rep``/``check_vma`` rename.)
"""

from jax import lax


def axis_size(name):
    """``lax.axis_size`` where available; on older jax, ``psum(1, name)``
    — constant-folded to the concrete mesh size at trace time, and
    raising the same trace-time ``NameError`` when ``name`` is unbound
    (verified on 0.4.37), so bound-axis probes behave identically."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    return lax.psum(1, name)

"""Profiling hooks (SURVEY §5: tracing/profiling subsystem).

The reference leans on ``pyprof``/nvprof markers (removed upstream) and
``torch.cuda.nvtx`` ranges. The TPU-native story is XLA's own tracer:

- :func:`trace` wraps ``jax.profiler.trace`` — writes a TensorBoard-
  loadable trace (``tensorboard --logdir <dir>``, "Profile" tab, or
  ``xprof``). Device-side timelines come from XLA itself; nothing to
  instrument.
- :func:`annotate` (= ``jax.named_scope``) is the nvtx-range analogue:
  regions named here appear on the trace's Python/HLO-metadata rows, and
  the scope names survive into HLO op metadata so device kernels
  attribute back to model regions. The in-tree models and fused
  optimizers are pre-annotated (attention / mlp / optimizer scopes).

Typical use::

    from apex_tpu.utils.profiler import annotate, trace
    with trace("/tmp/tb"):
        for _ in range(3):
            state = train_step(state)   # named scopes inside
"""

import contextlib
import glob
import gzip
import json
import os
from typing import Dict, List, Optional

import jax

annotate = jax.named_scope


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture a device+host profile under ``log_dir``."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# Trace report — the parse-and-report half of the reference's pyprof
# (``apex/pyprof`` annotated with nvtx AND parsed nsys output into op
# tables; annotate+trace alone is only half the workflow). jax writes a
# chrome-trace JSON next to the xplane file; stdlib parsing keeps the
# report dependency-free (no tensorboard install needed on the pod).
# ---------------------------------------------------------------------------


def summarize_trace(log_dir: str, *, top: int = 20,
                    device_only: bool = True) -> List[Dict]:
    """Aggregate the newest trace under ``log_dir`` into per-op totals.

    Returns rows ``{"name", "process", "count", "total_us", "avg_us"}``
    sorted by total duration, descending. ``device_only`` keeps only
    device lanes (``/device:...`` processes — XLA ops as executed);
    pass False to include host-side Python events. Works on any trace
    written by :func:`trace` / ``jax.profiler.trace``.
    """
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile",
                                         "*")))
    if not runs:
        raise FileNotFoundError(f"no profile runs under {log_dir}")
    paths = glob.glob(os.path.join(runs[-1], "*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(
            f"profile run {runs[-1]} has no *.trace.json.gz (this jax "
            "build wrote only the xplane file — open it with "
            "tensorboard/xprof instead)")
    agg: Dict[tuple, Dict] = {}
    for path in paths:
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        pids = {e["pid"]: e.get("args", {}).get("name", str(e["pid"]))
                for e in events
                if e.get("ph") == "M" and e.get("name") == "process_name"}
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            proc = pids.get(e.get("pid"), str(e.get("pid")))
            if device_only and "/device" not in proc:
                continue
            key = (proc, e["name"].lstrip("$"))
            row = agg.setdefault(key, {"name": key[1], "process": proc,
                                       "count": 0, "total_us": 0.0})
            row["count"] += 1
            row["total_us"] += float(e["dur"])
    if not agg and device_only:
        raise ValueError(
            "trace has no device lanes (CPU-only traces record host "
            "events only) — pass device_only=False to summarize host "
            "Python/dispatch events")
    rows = sorted(agg.values(), key=lambda r: -r["total_us"])[:top]
    for r in rows:
        r["avg_us"] = r["total_us"] / max(r["count"], 1)
    return rows


def print_summary(log_dir: str, *, top: int = 20,
                  device_only: bool = True,
                  file: Optional[object] = None) -> None:
    """Print :func:`summarize_trace` as a fixed-width table (the
    pyprof-style report)."""
    rows = summarize_trace(log_dir, top=top, device_only=device_only)
    print(f"{'total_us':>12} {'avg_us':>10} {'count':>7}  name",
          file=file)
    for r in rows:
        print(f"{r['total_us']:>12.1f} {r['avg_us']:>10.1f} "
              f"{r['count']:>7d}  {r['name'][:90]}", file=file)

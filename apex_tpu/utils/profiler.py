"""Profiling hooks (SURVEY §5: tracing/profiling subsystem).

The reference leans on ``pyprof``/nvprof markers (removed upstream) and
``torch.cuda.nvtx`` ranges. The TPU-native story is XLA's own tracer:

- :func:`trace` wraps ``jax.profiler.trace`` — writes a TensorBoard-
  loadable trace (``tensorboard --logdir <dir>``, "Profile" tab, or
  ``xprof``). Device-side timelines come from XLA itself; nothing to
  instrument.
- :func:`annotate` (= ``jax.named_scope``) is the nvtx-range analogue:
  regions named here appear on the trace's Python/HLO-metadata rows, and
  the scope names survive into HLO op metadata so device kernels
  attribute back to model regions. The in-tree models and fused
  optimizers are pre-annotated (attention / mlp / optimizer scopes).

Typical use::

    from apex_tpu.utils.profiler import annotate, trace
    with trace("/tmp/tb"):
        for _ in range(3):
            state = train_step(state)   # named scopes inside
"""

import contextlib

import jax

annotate = jax.named_scope


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture a device+host profile under ``log_dir``."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

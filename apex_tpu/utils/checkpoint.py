"""Checkpoint save/restore for training state pytrees.

Reference: the ``--resume`` path of ``examples/imagenet/main_amp.py``
(``torch.save``/``torch.load`` of model + optimizer + ``amp.state_dict()``).
``torch.save`` is pickle; the faithful TPU equivalent is pickling the
numpy-ified pytree — dependency-free, dtype-exact (incl. bfloat16 via
ml_dtypes), and structure-preserving for dicts/lists/NamedTuples.

Writes are ATOMIC (tmp file + rename) so a kill mid-save never corrupts
the latest checkpoint — the property the resume test relies on. For
multi-host sharded state, production users should reach for orbax
(async, per-shard layout); this module is the single-controller path the
examples and tests use, mirroring the reference's single-file habit.
"""

import os
import pickle
import tempfile
from typing import Any

import jax
import numpy as np


def save_checkpoint(path: str, tree: Any) -> None:
    """Atomically pickle a pytree of arrays (device arrays are fetched)."""
    host = jax.tree.map(lambda a: np.asarray(a), tree)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path: str) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves —
    feed them straight into a jitted step; JAX transfers on use)."""
    with open(path, "rb") as f:
        return pickle.load(f)

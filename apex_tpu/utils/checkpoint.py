"""Checkpoint save/restore for training state pytrees.

Reference: the ``--resume`` path of ``examples/imagenet/main_amp.py``
(``torch.save``/``torch.load`` of model + optimizer + ``amp.state_dict()``).
``torch.save`` is pickle; the faithful TPU equivalent is pickling the
numpy-ified pytree — dependency-free, dtype-exact (incl. bfloat16 via
ml_dtypes), and structure-preserving for dicts/lists/NamedTuples.

Writes are ATOMIC (tmp file + rename) so a kill mid-save never corrupts
the latest checkpoint — the property the resume test relies on. For
multi-host sharded state, production users should reach for orbax
(async, per-shard layout); this module is the single-controller path the
examples and tests use, mirroring the reference's single-file habit.
"""

import os
import pickle
import tempfile
from typing import Any

import jax
import numpy as np


def save_checkpoint(path: str, tree: Any) -> None:
    """Atomically pickle a pytree of arrays (device arrays are fetched)."""
    _atomic_pickle(path, jax.tree.map(lambda a: np.asarray(a), tree))


def load_checkpoint(path: str) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves —
    feed them straight into a jitted step; JAX transfers on use)."""
    with open(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------------
# Sharded checkpointing — the ZeRO-state path.
#
# ``save_checkpoint``'s np.asarray silently GATHERS sharded leaves, undoing
# DistributedFusedAdam/LAMB's 1/dp at-rest memory win at save time (and
# needing dp× host memory). The sharded pair below fetches each device
# shard individually and stores it under its global slice index, so no
# full copy of a sharded leaf ever exists on the host; load rebuilds
# arrays shard-by-shard with ``jax.make_array_from_callback`` against the
# TEMPLATE's sharding (typically the freshly ``init``-ed state). Resuming
# on a different topology is refused rather than silently re-gathered.
# Multi-host note: each process saves only its addressable shards — give
# each process its own path (e.g. suffix ``jax.process_index()``).
# ---------------------------------------------------------------------------


def _norm_index(index, shape) -> tuple:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _atomic_pickle(path: str, obj: Any) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_sharded_checkpoint(path: str, tree: Any) -> None:
    """Atomically save a pytree keeping sharded leaves sharded (one
    record per device shard; replicated/host leaves stored dense)."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    recs = []
    for leaf in leaves:
        sharded = (isinstance(leaf, jax.Array)
                   and hasattr(leaf, "sharding")
                   and not leaf.sharding.is_fully_replicated)
        if not sharded:
            recs.append({"kind": "dense", "array": np.asarray(leaf)})
            continue
        shards = {}
        for sh in leaf.addressable_shards:
            key = _norm_index(sh.index, leaf.shape)
            if key not in shards:  # replicated sub-axes: keep one copy
                shards[key] = np.asarray(sh.data)
        recs.append({"kind": "sharded", "shape": tuple(leaf.shape),
                     "shards": shards})
    _atomic_pickle(path, recs)


def load_sharded_checkpoint(path: str, template: Any) -> Any:
    """Load a :func:`save_sharded_checkpoint` file. ``template`` is a
    pytree of arrays (e.g. the live/freshly-initialized state) supplying
    the target structure and shardings; sharded leaves are materialized
    per device shard, never assembled whole on host."""
    with open(path, "rb") as f:
        recs = pickle.load(f)
    leaves_t, treedef = jax.tree_util.tree_flatten(template)
    if len(recs) != len(leaves_t):
        raise ValueError(
            f"checkpoint has {len(recs)} leaves, template has "
            f"{len(leaves_t)} — structure mismatch")
    out = []
    for rec, tmpl in zip(recs, leaves_t):
        if rec["kind"] == "dense":
            arr = rec["array"]
            if getattr(tmpl, "shape", None) is not None \
                    and tuple(np.shape(arr)) != tuple(tmpl.shape):
                raise ValueError(
                    f"dense leaf shape {np.shape(arr)} != template "
                    f"{tuple(tmpl.shape)}")
            out.append(arr)
            continue
        if tuple(tmpl.shape) != rec["shape"]:
            raise ValueError(
                f"sharded leaf shape {rec['shape']} != template "
                f"{tuple(tmpl.shape)}")
        shards = rec["shards"]

        def cb(index, shape=rec["shape"], shards=shards):
            key = _norm_index(index, shape)
            try:
                return shards[key]
            except KeyError:
                raise ValueError(
                    "resume topology mismatch: checkpoint shard slices "
                    f"{sorted(shards)} do not cover requested {key}; "
                    "resume with the same mesh/dp layout it was saved "
                    "under (or gather via the dense checkpoint path)")

        out.append(jax.make_array_from_callback(
            rec["shape"], tmpl.sharding, cb))
    return jax.tree_util.tree_unflatten(treedef, out)

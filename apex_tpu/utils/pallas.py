"""Shared Pallas-kernel plumbing (padding, masking constants).

One home for the helpers every kernel module needs, so fixes to
padding/masking behavior apply everywhere at once.
"""

import jax.numpy as jnp

# Masked-score constant. Finite (not -inf) so running-max arithmetic
# (m_prev - m_cur etc.) never produces inf-inf NaNs; exp(-1e30 - m)
# underflows to exactly 0 for any realistically-scaled logits, matching
# the reference kernels' additive -10000 for fp16-scale inputs.
NEG_INF = -1e30


def pad_axis(x, size: int, axis: int, value=0.0):
    """Zero-pad (or ``value``-pad) ``axis`` of ``x`` up to ``size``."""
    if x.shape[axis] == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, size - x.shape[axis])
    return jnp.pad(x, pads, constant_values=value)


def pad2(x, rows: int, cols: int, value=0.0):
    """Pad a 2-D array to (rows, cols)."""
    return pad_axis(pad_axis(x, rows, 0, value), cols, 1, value)


def dimsem(*sem):
    """``pltpu.CompilerParams`` with grid dimension semantics:
    ``"parallel"`` = revisit-free tiles Mosaic may pipeline/partition
    freely (measured ~12% on the flash kernels); any dim that
    accumulates into scratch or a revisited output block MUST stay
    ``"arbitrary"`` — on megacore parts a ``"parallel"`` dim may be
    split across TensorCores, and a shared revisited output would lose
    one core's partial writes."""
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed TPUCompilerParams -> CompilerParams; support both so the
    # kernels import on every rig (CI pins an older jax than the driver)
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=sem)

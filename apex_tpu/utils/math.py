"""Small integer-math helpers shared across the package.

Reference: ``apex/transformer/utils.py :: divide, ensure_divisibility``.
"""


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(
            f"{numerator} is not divisible by {denominator}"
        )


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up_to_multiple(x: int, m: int) -> int:
    return cdiv(x, m) * m

from apex_tpu.utils.platform import (  # noqa: F401
    has_tpu,
    interpret_default,
    pallas_interpret,
)
from apex_tpu.utils.math import (  # noqa: F401
    cdiv,
    divide,
    ensure_divisibility,
    round_up_to_multiple,
)

"""Variable-sequence-length support via bucketing.

Reference: ``apex/transformer/pipeline_parallel/p2p_communication.py ::
_communicate`` ships a shape/dtype handshake (``variable_seq_lengths``)
so adjacent pipeline ranks can exchange ragged activations. XLA requires
static shapes, so the TPU-native equivalent is the standard bucketing
discipline: pad every batch up to one of a SMALL set of compiled
lengths. Each bucket compiles once; steady-state training touches one
or two buckets, and the padding fraction is bounded by the bucket
ratio (2x for the default power-of-two ladder, typically far less).

The helpers are deliberately tiny and explicit — they are the missing
piece that lets a ragged data loader feed the static-shape kernels and
schedules; masks produced here flow into the attention/loss masks the
models already consume.
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_DEFAULT_MIN = 128


def default_buckets(max_len: int, min_len: int = _DEFAULT_MIN
                    ) -> Tuple[int, ...]:
    """Power-of-two ladder ``min_len, 2*min_len, ... >= max_len``."""
    if max_len < 1:
        raise ValueError("max_len must be positive")
    out = []
    b = min_len
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(b)
    return tuple(out)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= length (raises if none fits — the loader's
    truncation policy, not padding, handles over-long examples)."""
    for b in sorted(buckets):
        if length <= b:
            return int(b)
    raise ValueError(
        f"sequence length {length} exceeds the largest bucket "
        f"{max(buckets)}; truncate upstream or extend the buckets")


def pad_to_bucket(batch: Any, length: int, *, seq_axis: int = 1,
                  buckets: Optional[Sequence[int]] = None,
                  pad_value=0) -> Tuple[Any, jax.Array]:
    """Pad every leaf of ``batch`` along ``seq_axis`` from ``length`` to
    its bucket; returns ``(padded_batch, mask)`` where ``mask`` is
    ``(bucket,)`` int32 with 1 = real position (broadcast it into the
    models' ``(b, s)`` attention-mask convention as needed).

    ``length`` is the CURRENT ragged length (leaves must agree on it);
    bucketing is a host-side, trace-free decision — call this in the
    data loader, outside jit, so each bucket length hits one compiled
    executable.
    """
    if buckets is None:
        buckets = default_buckets(length)
    target = bucket_for(length, buckets)

    def pad(a):
        a = np.asarray(a) if not isinstance(a, (jax.Array, np.ndarray)) \
            else a
        if a.shape[seq_axis] != length:
            raise ValueError(
                f"leaf has seq length {a.shape[seq_axis]}, expected "
                f"{length}")
        if target == length:
            return a
        widths = [(0, 0)] * a.ndim
        widths[seq_axis] = (0, target - length)
        return jnp.pad(a, widths, constant_values=pad_value)

    mask = (jnp.arange(target) < length).astype(jnp.int32)
    return jax.tree.map(pad, batch), mask

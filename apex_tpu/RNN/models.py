"""Stacked/bidirectional recurrent models (ref: ``apex/RNN/models.py`` +
``RNNBackend.py`` — ``LSTM``/``GRU``/``RNNReLU``/``RNNTanh``/``mLSTM``
builders over ``stackedRNN``/``bidirectionalRNN`` wrappers).

The time loop is ONE ``lax.scan`` per layer-direction (fused XLA while
loop — the fp16-era per-step Python loop the reference wraps simply does
not exist here); layers stack sequentially, the bidirectional variant
runs a reversed scan and concatenates features, and inter-layer dropout
matches torch semantics (not after the last layer).
"""

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.RNN import cells as C

_CELLS = {
    "lstm": (C.init_lstm_cell, C.lstm_cell, True),
    "mlstm": (C.init_mlstm_cell, C.mlstm_cell, True),
    "gru": (C.init_gru_cell, C.gru_cell, False),
    "rnn_tanh": (C.init_rnn_cell, C.rnn_tanh_cell, False),
    "rnn_relu": (C.init_rnn_cell, C.rnn_relu_cell, False),
}


class RNN:
    """``RNN(mode, input_size, hidden_size, num_layers, ...)``; apply on
    (seq, batch, input) returns (seq, batch, D·hidden) plus final states
    (D = 2 if bidirectional)."""

    def __init__(self, mode: str, input_size: int, hidden_size: int,
                 num_layers: int = 1, *, bias: bool = True,
                 dropout: float = 0.0, bidirectional: bool = False,
                 params_dtype=jnp.float32):
        if mode not in _CELLS:
            raise ValueError(f"mode must be one of {sorted(_CELLS)}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bias = bias
        self.dropout = dropout
        self.bidirectional = bidirectional
        self.params_dtype = params_dtype
        self.init_cell, self.cell, self.has_cell_state = _CELLS[mode]

    def init(self, key: jax.Array) -> List[Dict[str, Any]]:
        d = 2 if self.bidirectional else 1
        layers = []
        keys = jax.random.split(key, self.num_layers * d)
        for li in range(self.num_layers):
            in_sz = self.input_size if li == 0 else self.hidden_size * d
            layer = {"fwd": self.init_cell(keys[li * d], in_sz,
                                           self.hidden_size,
                                           self.params_dtype, self.bias)}
            if self.bidirectional:
                layer["bwd"] = self.init_cell(keys[li * d + 1], in_sz,
                                              self.hidden_size,
                                              self.params_dtype, self.bias)
            layers.append(layer)
        return layers

    def _zero_state(self, batch: int, dtype):
        h = jnp.zeros((batch, self.hidden_size), dtype)
        return (h, jnp.zeros_like(h)) if self.has_cell_state else h

    def _run_direction(self, p, xs, reverse: bool):
        if reverse:
            xs = jnp.flip(xs, axis=0)
        state0 = self._zero_state(xs.shape[1], xs.dtype)

        def step(state, x):
            new = self.cell(p, x, state)
            h = new[0] if self.has_cell_state else new
            return new, h

        final, hs = lax.scan(step, state0, xs)
        if reverse:
            hs = jnp.flip(hs, axis=0)
        return hs, final

    def apply(self, params: List[Dict[str, Any]], xs: jax.Array, *,
              dropout_rng: Optional[jax.Array] = None
              ) -> Tuple[jax.Array, List[Any]]:
        finals = []
        for li, layer in enumerate(params):
            hs_f, fin_f = self._run_direction(layer["fwd"], xs, False)
            if self.bidirectional:
                hs_b, fin_b = self._run_direction(layer["bwd"], xs, True)
                xs = jnp.concatenate([hs_f, hs_b], axis=-1)
                finals.append((fin_f, fin_b))
            else:
                xs = hs_f
                finals.append(fin_f)
            if (dropout_rng is not None and self.dropout > 0
                    and li < self.num_layers - 1):
                keep = jax.random.bernoulli(
                    jax.random.fold_in(dropout_rng, li),
                    1 - self.dropout, xs.shape)
                xs = xs * keep / (1 - self.dropout)
        return xs, finals

    __call__ = apply


LSTM = functools.partial(RNN, "lstm")
mLSTM = functools.partial(RNN, "mlstm")
GRU = functools.partial(RNN, "gru")


def RNNReLU(*args, **kw):
    return RNN("rnn_relu", *args, **kw)


def RNNTanh(*args, **kw):
    return RNN("rnn_tanh", *args, **kw)

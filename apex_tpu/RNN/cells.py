"""Functional RNN cells (ref: ``apex/RNN/cells.py`` — the fp16-era
``mLSTMRNNCell``/``mLSTMCell`` plus the torch builtins the backend wraps).

The reference tier exists to make recurrent cells fp16-safe; it is
deprecated upstream but still in-tree, so the surface is reproduced.
TPU design: cells are pure step functions ``(params, x_t, state) ->
state`` driven by ``lax.scan`` in :mod:`apex_tpu.RNN.models` — the
recurrence compiles to one fused loop, and the gate matmuls are packed
(one (in+hidden, 4·hidden) GEMM per step) to feed the MXU. Gate math is
fp32 regardless of storage dtype (the tier's original purpose).
"""

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


def _uniform(key, shape, bound, dtype):
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def _init_gates(key, input_size, hidden_size, n_gates, dtype, bias=True):
    """Packed torch-style init: U(-1/sqrt(H), 1/sqrt(H))."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    bound = 1.0 / math.sqrt(hidden_size)
    p = {
        "w_ih": _uniform(k1, (input_size, n_gates * hidden_size), bound,
                         dtype),
        "w_hh": _uniform(k2, (hidden_size, n_gates * hidden_size), bound,
                         dtype),
    }
    if bias:
        p["b_ih"] = _uniform(k3, (n_gates * hidden_size,), bound, dtype)
        p["b_hh"] = _uniform(k4, (n_gates * hidden_size,), bound, dtype)
    return p


def _gates(p: Params, x, h):
    g = jnp.dot(x, p["w_ih"].astype(x.dtype)) \
        + jnp.dot(h, p["w_hh"].astype(h.dtype))
    if "b_ih" in p:
        g = g + p["b_ih"].astype(g.dtype) + p["b_hh"].astype(g.dtype)
    return g.astype(jnp.float32)


# -- LSTM -------------------------------------------------------------------

def init_lstm_cell(key, input_size: int, hidden_size: int,
                   dtype=jnp.float32, bias: bool = True) -> Params:
    return _init_gates(key, input_size, hidden_size, 4, dtype, bias)


def lstm_cell(p: Params, x: jax.Array,
              state: Tuple[jax.Array, jax.Array]
              ) -> Tuple[jax.Array, jax.Array]:
    """(h, c) -> (h', c'); torch gate order i, f, g, o."""
    h, c = state
    i, f, g, o = jnp.split(_gates(p, x, h), 4, axis=-1)
    c32 = c.astype(jnp.float32)
    c_new = jax.nn.sigmoid(f) * c32 + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new.astype(h.dtype), c_new.astype(c.dtype)


# -- mLSTM (multiplicative LSTM, the reference's own cell) ------------------

def init_mlstm_cell(key, input_size: int, hidden_size: int,
                    dtype=jnp.float32, bias: bool = True) -> Params:
    k1, k2 = jax.random.split(key)
    p = _init_gates(k1, input_size, hidden_size, 4, dtype, bias)
    bound = 1.0 / math.sqrt(hidden_size)
    km1, km2 = jax.random.split(k2)
    p["w_mih"] = _uniform(km1, (input_size, hidden_size), bound, dtype)
    p["w_mhh"] = _uniform(km2, (hidden_size, hidden_size), bound, dtype)
    return p


def mlstm_cell(p: Params, x: jax.Array,
               state: Tuple[jax.Array, jax.Array]
               ) -> Tuple[jax.Array, jax.Array]:
    """Krause et al. multiplicative LSTM (ref ``mLSTMCell``): the hidden
    state is replaced by m = (x Wmx) ⊙ (h Wmh) before the LSTM gates."""
    h, c = state
    m = (jnp.dot(x, p["w_mih"].astype(x.dtype))
         * jnp.dot(h, p["w_mhh"].astype(h.dtype)))
    i, f, g, o = jnp.split(_gates(p, x, m), 4, axis=-1)
    c32 = c.astype(jnp.float32)
    c_new = jax.nn.sigmoid(f) * c32 + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new.astype(h.dtype), c_new.astype(c.dtype)


# -- GRU --------------------------------------------------------------------

def init_gru_cell(key, input_size: int, hidden_size: int,
                  dtype=jnp.float32, bias: bool = True) -> Params:
    return _init_gates(key, input_size, hidden_size, 3, dtype, bias)


def gru_cell(p: Params, x: jax.Array, state: jax.Array) -> jax.Array:
    """torch GRU: r, z from packed gates; n mixes b_ih/b_hh asymmetrically."""
    h = state
    gi = jnp.dot(x, p["w_ih"].astype(x.dtype))
    gh = jnp.dot(h, p["w_hh"].astype(h.dtype))
    if "b_ih" in p:
        gi = gi + p["b_ih"].astype(gi.dtype)
        gh = gh + p["b_hh"].astype(gh.dtype)
    gi, gh = gi.astype(jnp.float32), gh.astype(jnp.float32)
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    h_new = (1.0 - z) * n + z * h.astype(jnp.float32)
    return h_new.astype(h.dtype)


# -- vanilla RNN ------------------------------------------------------------

def init_rnn_cell(key, input_size: int, hidden_size: int,
                  dtype=jnp.float32, bias: bool = True) -> Params:
    return _init_gates(key, input_size, hidden_size, 1, dtype, bias)


def rnn_tanh_cell(p: Params, x: jax.Array, state: jax.Array) -> jax.Array:
    return jnp.tanh(_gates(p, x, state)).astype(state.dtype)


def rnn_relu_cell(p: Params, x: jax.Array, state: jax.Array) -> jax.Array:
    return jax.nn.relu(_gates(p, x, state)).astype(state.dtype)

"""Recurrent cells and stacks (ref: ``apex/RNN``)."""

from apex_tpu.RNN.cells import (  # noqa: F401
    gru_cell,
    init_gru_cell,
    init_lstm_cell,
    init_mlstm_cell,
    init_rnn_cell,
    lstm_cell,
    mlstm_cell,
    rnn_relu_cell,
    rnn_tanh_cell,
)
from apex_tpu.RNN.models import (  # noqa: F401
    GRU,
    LSTM,
    RNN,
    RNNReLU,
    RNNTanh,
    mLSTM,
)

"""Partition-rule engine: one regex table shards everything.

``match_partition_rules`` turns an ordered ``(pattern, PartitionSpec)``
table into the spec pytree for any parameter-shaped tree;
``gpt_rules``/``bert_rules`` are the default Megatron-layout tables;
``optimizer_state_specs`` re-derives moment/master-weight specs from
the same table; ``make_shard_and_gather_fns`` materializes per-leaf
placement closures; ``make_mesh`` builds the dp x tp x pp x cp mesh
through ``parallel_state``. The APX7xx lint tier
(``python -m apex_tpu.lint --sharding``) statically verifies the
tables and every tree derived from them — see
``docs/source/partitioning.rst``.
"""

from apex_tpu.partition.mesh import make_mesh
from apex_tpu.partition.rules import (
    make_shard_and_gather_fns,
    match_partition_rules,
    optimizer_state_specs,
    rule_match_table,
    spec_axis_names,
    tree_path_name,
    tree_paths,
)
from apex_tpu.partition.tables import (
    bert_rules,
    draft_gpt_rules,
    gpt_quant_rules,
    gpt_rules,
    kv_cache_quant_rules,
    kv_cache_rules,
)

__all__ = [
    "bert_rules",
    "draft_gpt_rules",
    "gpt_quant_rules",
    "gpt_rules",
    "kv_cache_quant_rules",
    "kv_cache_rules",
    "make_mesh",
    "make_shard_and_gather_fns",
    "match_partition_rules",
    "optimizer_state_specs",
    "rule_match_table",
    "spec_axis_names",
    "tree_path_name",
    "tree_paths",
]

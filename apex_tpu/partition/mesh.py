"""dp x tp x pp x cp mesh factory over ``parallel_state``.

The SNIPPETS.md [2] ``get_mesh(num_nodes, gpus_per_node, mp_size,
dp_size)`` idiom, restated in this repo's vocabulary: callers name the
parallelism degrees they want and the factory builds/installs the
global mesh through :func:`parallel_state.initialize_model_parallel`
(which owns the canonical axis names and the dp-innermost /
model-outermost device order) — it never constructs a second,
subtly-different ``Mesh`` of its own. The explicit ``dp`` argument is
forwarded as the initializer's validation hook, so asking for
``make_mesh(dp=4, tp=2)`` on an 8-device world fails loudly instead of
silently landing on a different data-parallel degree.
"""

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from apex_tpu.transformer import parallel_state as ps


def make_mesh(dp: int = 1, tp: int = 1, pp: int = 1, cp: int = 1, *,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build and install the global ``(data, pipe, context, model)``
    mesh for the requested degrees, using the first ``dp*tp*pp*cp``
    devices (all devices must be consumed exactly when ``devices`` is
    passed explicitly). Returns the installed mesh."""
    for name, n in (("dp", dp), ("tp", tp), ("pp", pp), ("cp", cp)):
        if int(n) < 1:
            raise ValueError(f"{name} must be a positive integer, got {n}")
    need = int(dp) * int(tp) * int(pp) * int(cp)
    if devices is None:
        devices = jax.devices()
        if need > len(devices):
            raise ValueError(
                f"mesh dp{dp} x tp{tp} x pp{pp} x cp{cp} needs {need} "
                f"devices, have {len(devices)}")
        devices = devices[:need]
    elif len(devices) != need:
        raise ValueError(
            f"mesh dp{dp} x tp{tp} x pp{pp} x cp{cp} needs exactly "
            f"{need} devices, got {len(devices)}")
    return ps.initialize_model_parallel(
        tensor_model_parallel_size_=int(tp),
        pipeline_model_parallel_size_=int(pp),
        context_parallel_size_=int(cp),
        data_parallel_size_=int(dp),
        devices=devices)

"""Default partition-rule tables (GPT / BERT / serving KV cache).

One table per model family covers everything that family shards: the
parameter tree, the optimizer moments/master weights derived from it
(see :func:`apex_tpu.partition.rules.optimizer_state_specs`), and — for
GPT — the serving KV cache
(:func:`apex_tpu.serving.cache.cache_partition_specs` matches its
``KVCache`` template against the same table). The tables are written
OVERLAP-FREE: every leaf matches exactly one rule, which APX701
enforces for each registered tree, and the layouts reproduce the
hand-maintained references (``models.gpt.gpt_partition_specs``,
``models.bert.bert_partition_specs``) that APX702 cross-checks them
against.

Layout recap (Megatron over the ``model`` mesh axis):

- vocab-sharded word embeddings ``P(model, None)``; position /
  token-type tables replicated;
- Column-parallel qkv/fc1: output dim sharded (kernel last dim, bias);
- Row-parallel out/fc2: input dim sharded, bias replicated (added
  after the psum);
- layer norms replicated;
- GPT layer leaves carry a leading stacked-``num_layers`` dim (the
  ``lax.scan`` depth loop), hence the extra leading ``None``;
- KV cache: heads (axis 2 of ``(L, slots, heads, S, d)``) shard over
  ``model`` — each rank caches exactly the heads its head-major qkv
  column shard produces; slot lengths are replicated.
"""

from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps

# KV-cache rules, shared by both model tables: the paths are the
# ``KVCache``/``PagedKVCache`` namedtuple fields, matched at
# end-of-path so a model param ending differently can never collide.
# The k/v rule covers BOTH layouts — dense ``(L, slots, heads, S, d)``
# and paged ``(L, pages, heads, page, d)`` keep heads on axis 2; block
# tables (paged only) replicate, every rank indexes the same mapping.
_KV_CACHE_RULES = (
    (r"(^|/)(k|v)$", P(None, None, ps.TENSOR_AXIS, None, None)),
    (r"(^|/)lengths$", P()),
    (r"(^|/)block_tables$", P()),
)


def kv_cache_rules():
    """The serving-cache slice of the default tables."""
    return _KV_CACHE_RULES


def kv_cache_quant_rules():
    """KV-cache rules for the INT8 paged pool: the base rules plus the
    per-page-per-head fp32 scales ``(L, pages, heads)`` — heads (axis
    2, same as the pool's) shard over ``model``, so each rank's scale
    shard dequantizes exactly its local heads' pages."""
    return _KV_CACHE_RULES + (
        (r"(^|/)(k|v)_scale$", P(None, None, ps.TENSOR_AXIS)),
    )


def gpt_quant_rules():
    """Rule table for the weight-only int8 GPT tree
    (``apex_tpu.quant.quantize_params``) plus the int8 paged cache.
    Kernel leaves keep their bf16 paths and specs (int8 swaps the
    dtype, never the layout); each ``scale`` rule is the kernel's spec
    with the CONTRACTED axis dropped — Column (qkv/fc1) scales follow
    their output channels onto ``model`` like the bias, Row (out/fc2)
    scales replicate, the word-table scale rides the vocab shard.
    Overlap-free against APX701 like the base table (the scale paths
    end differently from every kernel/bias path)."""
    t = ps.TENSOR_AXIS
    return (
        ("embedding/word/embedding", P(t, None)),
        ("embedding/word/scale", P(t)),
        ("embedding/position/embedding", P()),
        ("layers/(ln1|ln2)/(weight|bias)", P(None)),
        ("layers/qkv/kernel", P(None, None, t)),
        ("layers/qkv/(bias|scale)", P(None, t)),
        ("layers/out/kernel", P(None, t, None)),
        ("layers/out/(bias|scale)", P(None)),
        ("layers/fc1/kernel", P(None, None, t)),
        ("layers/fc1/(bias|scale)", P(None, t)),
        ("layers/fc2/kernel", P(None, t, None)),
        ("layers/fc2/(bias|scale)", P(None)),
        ("final_ln/(weight|bias)", P()),
    ) + kv_cache_quant_rules()


def gpt_rules():
    """Rule table for the GPT param tree (``models.gpt.init_gpt``) plus
    the serving KV cache. First match wins; table is overlap-free."""
    t = ps.TENSOR_AXIS
    return (
        ("embedding/word/embedding", P(t, None)),
        ("embedding/position/embedding", P()),
        ("layers/(ln1|ln2)/(weight|bias)", P(None)),
        ("layers/qkv/kernel", P(None, None, t)),
        ("layers/qkv/bias", P(None, t)),
        ("layers/out/kernel", P(None, t, None)),
        ("layers/out/bias", P(None)),
        ("layers/fc1/kernel", P(None, None, t)),
        ("layers/fc1/bias", P(None, t)),
        ("layers/fc2/kernel", P(None, t, None)),
        ("layers/fc2/bias", P(None)),
        ("final_ln/(weight|bias)", P()),
    ) + _KV_CACHE_RULES


def draft_gpt_rules():
    """Rule table for the speculative DRAFT model's param tree + its
    lockstep KV cache (``serving.draft_model.DraftModel``). The draft
    is a GPT sharded on the SAME mesh as the target, so the layout is
    :func:`gpt_rules` minus the rows that can never match a draft tree:
    draft configs (``models.gpt.draft_gpt_tiny``/``draft_gpt_medium``)
    are RoPE-only — no ``embedding/position`` leaf — and the lockstep
    draft cache is DENSE (``KVCache``: k/v/lengths, no block tables).
    A rule that can never match would be an APX701 dead-rule finding
    (the BERT table's KV-cache omission, same reasoning)."""
    dead = ("embedding/position/embedding", r"(^|/)block_tables$")
    return tuple(rule for rule in gpt_rules() if rule[0] not in dead)


def bert_rules():
    """Rule table for the BERT param tree (``models.bert.init_bert``).
    BERT layers are a list (paths carry ``encoder/<i>/``), so patterns
    stay unanchored; layer norms everywhere replicate via one rule."""
    t = ps.TENSOR_AXIS
    return (
        ("embeddings/word/embedding", P(t, None)),
        ("embeddings/(position|token_type)/embedding", P()),
        ("layernorm/(weight|bias)", P()),
        ("(qkv|fc1)/kernel", P(None, t)),
        ("(qkv|fc1)/bias", P(t)),
        ("(attention/out|fc2)/kernel", P(t, None)),
        ("(attention/out|fc2)/bias", P()),
        ("mlm_head/transform/(kernel|bias)", P()),
        ("mlm_head/bias", P()),
        ("pooler/(kernel|bias)", P()),
        # no KV-cache rules: BERT is not served incrementally, and a
        # rule that can never match would be an APX701 dead-rule finding
    )

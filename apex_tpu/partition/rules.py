"""Regex-rule -> PartitionSpec-pytree engine.

ROADMAP item 3's fix for bespoke per-subsystem sharding wiring: ONE
ordered rule table — ``(pattern, PartitionSpec)`` pairs matched with
``re.search`` against each leaf's ``/``-joined tree path — produces the
spec pytree for any parameter-shaped tree. Model params, optimizer
moments/master weights, and the serving KV cache all derive their specs
from the same table (see :mod:`apex_tpu.partition.tables`), which is
what makes the APX7xx lint tier's cross-tree consistency checks
(``apex_tpu/lint/sharded/``) possible: the table is the single source
of truth the checker verifies every derived tree against.

Conventions (the JAX LM-community idiom, e.g. EasyLM/levanter-style
``match_partition_rules``):

- matching is ``re.search``, so unanchored patterns apply at any tree
  depth (``layers/qkv/kernel`` matches the stacked GPT layer leaves and
  the same leaves under an ``m/``- or ``v/``-prefixed optimizer tree);
- rank-0 (scalar) leaves are replicated (``P()``) without consulting
  the table — step counters and loss scalars never need rules;
- the FIRST matching rule wins, but the default tables are written
  overlap-free and APX701 flags any leaf matched by more than one rule;
- a leaf no rule matches raises ``ValueError`` — silent full
  replication of an unmatched tensor is exactly the bug class this
  engine exists to kill.
"""

import re
from typing import Any, Callable, List, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec

Rule = Tuple[str, PartitionSpec]


def tree_path_name(path) -> str:
    """``/``-joined name of one ``tree_flatten_with_path`` key path
    (dict keys, namedtuple fields, and sequence indices all render as
    their plain string form)."""
    parts = []
    for k in path:
        part = getattr(k, "key", None)
        if part is None:
            part = getattr(k, "name", None)
        if part is None:
            part = getattr(k, "idx", None)
        parts.append(str(k) if part is None else str(part))
    return "/".join(parts)


def tree_paths(tree: Any) -> List[str]:
    """The ``/``-joined path of every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [tree_path_name(path) for path, _ in flat]


def _is_scalar(leaf) -> bool:
    return len(getattr(leaf, "shape", ())) == 0


def match_partition_rules(rules: Sequence[Rule], params: Any) -> Any:
    """Spec pytree for ``params``: first ``re.search`` match per leaf
    path wins; scalar leaves are replicated; an unmatched non-scalar
    leaf raises ``ValueError``."""
    def assign(path, leaf):
        name = tree_path_name(path)
        if _is_scalar(leaf):
            return PartitionSpec()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(
            f"no partition rule matches leaf '{name}' "
            f"(shape {tuple(getattr(leaf, 'shape', ()))}) — every "
            "non-scalar leaf must be covered by the rule table")

    return jax.tree_util.tree_map_with_path(assign, params)


def rule_match_table(rules: Sequence[Rule],
                     params: Any) -> List[Tuple[str, Any, List[int]]]:
    """Per-leaf match bookkeeping for the APX701 coverage check:
    ``(path, leaf, [indices of every rule whose pattern matches])`` for
    each leaf, scalars included (their index list is informational —
    scalars replicate regardless)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = tree_path_name(path)
        hits = [i for i, (pattern, _) in enumerate(rules)
                if re.search(pattern, name)]
        out.append((name, leaf, hits))
    return out


def spec_axis_names(spec: PartitionSpec) -> List[str]:
    """Every mesh axis named in a spec, in order, flattening tuple
    entries like ``(("model", "data"), None)``."""
    out: List[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            out.append(str(ax))
    return out


def optimizer_state_specs(rules: Sequence[Rule], params: Any,
                          families: Sequence[str] = ("m", "v", "master"),
                          ) -> dict:
    """Spec trees for params-shaped optimizer state, derived from the
    SAME rule table by re-matching under a per-family path prefix
    (``m/<param path>`` etc).

    Because matching is ``re.search``, an unanchored table yields specs
    identical to the params' — which is the contract APX702 verifies. A
    table that anchors a pattern at the tree root (``^embedding/...``)
    silently stops matching the prefixed moment paths, and the moments
    fall through to a later rule or to the unmatched error: exactly the
    per-tensor-family drift the lint tier reports instead of raising.
    """
    return {fam: match_partition_rules(rules, {fam: params})[fam]
            for fam in families}


def make_shard_and_gather_fns(partition_specs: Any, mesh=None,
                              ) -> Tuple[Any, Any]:
    """Pytrees of per-leaf ``shard_fn(x)`` / ``gather_fn(x)`` matching
    ``partition_specs`` (the SNIPPETS.md [1] idiom on NamedSharding):
    shard places a host or replicated array onto the mesh under its
    spec; gather pulls a sharded array back to a fully-replicated host
    value (checkpoint save path)."""
    from jax.sharding import NamedSharding

    if mesh is None:
        from apex_tpu.transformer import parallel_state as ps

        mesh = ps.get_mesh()

    def make_shard(spec) -> Callable:
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sharding)

    replicated = NamedSharding(mesh, PartitionSpec())

    def make_gather(spec) -> Callable:
        del spec  # gather target is always the replicated layout
        return lambda x: jax.device_get(jax.device_put(x, replicated))

    is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
    shard_fns = jax.tree_util.tree_map(make_shard, partition_specs,
                                       is_leaf=is_spec)
    gather_fns = jax.tree_util.tree_map(make_gather, partition_specs,
                                        is_leaf=is_spec)
    return shard_fns, gather_fns

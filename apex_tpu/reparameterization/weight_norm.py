"""Weight normalization — w = g · v/‖v‖ (Salimans & Kingma 2016).

Reference: ``apex/reparameterization/weight_norm.py`` +
``reparameterization.py`` (module hooks splitting a weight into
magnitude ``g`` and direction ``v``, with an fp16-safe fused norm). The
reference marks this tier deprecated; kept for API completeness.

Functional translation: a pytree transform pair instead of module hooks.
``apply_weight_norm`` splits selected leaves into ``{"g", "v"}`` dicts;
``compute_weight`` reconstitutes w (differentiable — grads flow to g and
v exactly as the reference's autograd does); ``remove_weight_norm``
re-fuses. The norm is taken over all but ``dim`` (reference default
dim=0), computed in fp32 regardless of storage dtype (the fp16-safety
that motivated apex's version).
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


def _norm_keep(v: jax.Array, dim: int) -> jax.Array:
    axes = tuple(i for i in range(v.ndim) if i != dim % max(v.ndim, 1))
    v32 = v.astype(jnp.float32)
    return jnp.sqrt(jnp.sum(v32 * v32, axis=axes, keepdims=True))


def apply_weight_norm(weight: jax.Array, dim: int = 0
                      ) -> Tuple[jax.Array, jax.Array]:
    """Split w -> (g, v) with w == g · v/‖v‖ initially (v = w,
    g = ‖w‖ over all axes but ``dim``)."""
    g = _norm_keep(weight, dim).astype(weight.dtype)
    return g, weight


def compute_weight(g: jax.Array, v: jax.Array, dim: int = 0) -> jax.Array:
    """w = g · v/‖v‖, norm in fp32 (the reference kernel's fp16-safe
    promotion), result in v's dtype."""
    norm = _norm_keep(v, dim)
    w = v.astype(jnp.float32) / jnp.maximum(norm, 1e-12) \
        * g.astype(jnp.float32)
    return w.astype(v.dtype)


def remove_weight_norm(g: jax.Array, v: jax.Array,
                       dim: int = 0) -> jax.Array:
    """Fuse (g, v) back into a plain weight (ref:
    ``remove_weight_norm``)."""
    return compute_weight(g, v, dim)

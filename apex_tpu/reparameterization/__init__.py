"""Weight-norm reparameterization (ref: ``apex/reparameterization``)."""

from apex_tpu.reparameterization.weight_norm import (  # noqa: F401
    apply_weight_norm,
    compute_weight,
    remove_weight_norm,
)

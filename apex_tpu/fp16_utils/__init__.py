"""Legacy fp16 utilities (ref: ``apex/fp16_utils``)."""

from apex_tpu.fp16_utils.fp16_optimizer import (  # noqa: F401
    FP16_Optimizer,
    FP16OptimizerState,
)
from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    tofp16,
)

"""FP16_Optimizer — the legacy master-weights wrapper.

Reference: ``apex/fp16_utils/fp16_optimizer.py :: FP16_Optimizer`` — wraps
a torch optimizer, keeps fp32 master copies of fp16 params, scales the
loss (static or dynamic), copies fp16 grads into fp32, unscales, checks
overflow, steps the wrapped optimizer on the masters, and copies back.

Functional translation: the wrapper owns a ``FP16OptimizerState``
(master pytree + inner optimizer state + scaler state); ``scale_loss``
stands in for ``backward(loss)`` (JAX differentiates the scaled loss —
there is no .grad buffer to scale in place), and ``step`` performs
grads→master-grads, unscale, overflow-gated inner step, master→model.
The wrapped optimizer is any ``apex_tpu.optimizers`` fused optimizer
(they expose ``init``/``step(grads, params, state, grad_scale,
found_inf)``). New code should use ``amp.initialize`` (O2); this class
exists for script parity, same as the reference keeps it.
"""

from typing import Any, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.fp16_utils.fp16util import (
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
)


class FP16OptimizerState(NamedTuple):
    master: Any
    inner: Any
    scaler: LossScalerState


class FP16_Optimizer:
    def __init__(self, optimizer,
                 static_loss_scale: Union[float, str] = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = optimizer
        if dynamic_loss_scale:
            self.loss_scaler = LossScaler("dynamic",
                                          **(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(float(static_loss_scale))
        self.verbose = verbose

    # -- state ----------------------------------------------------------
    def init(self, model_params: Any) -> FP16OptimizerState:
        _, master = prep_param_lists(model_params)
        return FP16OptimizerState(
            master=master,
            inner=self.optimizer.init(master),
            scaler=self.loss_scaler.init_state())

    def loss_scale(self, state: FP16OptimizerState) -> jnp.ndarray:
        return self.loss_scaler.loss_scale(state.scaler)

    # -- the backward()/step() pair -------------------------------------
    def scale_loss(self, loss: jnp.ndarray,
                   state: FP16OptimizerState) -> jnp.ndarray:
        """The ``backward(loss)`` analogue: differentiate THIS value (ref
        scales the loss before .backward() so fp16 grads don't
        underflow)."""
        return self.loss_scaler.scale(loss, state.scaler)

    def step(self, grads: Any, model_params: Any,
             state: FP16OptimizerState, **step_kwargs
             ) -> Tuple[Any, FP16OptimizerState]:
        """grads are w.r.t. the SCALED loss in the model (fp16) dtype.
        Returns (new model params, new state); on overflow the inner step
        is skipped and the scale halves (dynamic), exactly the
        reference's ``step``-after-``update_master_grads`` sequence."""
        master_grads = model_grads_to_master_grads(grads)
        master_grads, found_inf = self.loss_scaler.unscale(
            master_grads, state.scaler)
        new_master, new_inner = self.optimizer.step(
            master_grads, state.master, state.inner,
            found_inf=found_inf, **step_kwargs)
        new_scaler = self.loss_scaler.update_scale(state.scaler, found_inf)
        new_model = master_params_to_model_params(model_params, new_master)
        return new_model, FP16OptimizerState(
            master=new_master, inner=new_inner, scaler=new_scaler)

    # -- checkpoint parity ----------------------------------------------
    def state_dict(self, state: FP16OptimizerState) -> dict:
        """Pytree-of-arrays dict (ref: ``state_dict`` incl. the loss
        scaler's dynamic state)."""
        return {"master": state.master, "inner": state.inner,
                "scaler": {"loss_scale": state.scaler.loss_scale,
                           "unskipped": state.scaler.unskipped,
                           "overflows": state.scaler.overflows}}

    def load_state_dict(self, d: dict) -> FP16OptimizerState:
        return FP16OptimizerState(
            master=d["master"], inner=d["inner"],
            scaler=LossScalerState(**d["scaler"]))

"""Functional analogues of ``apex/fp16_utils/fp16util.py``.

The reference mutates ``nn.Module``s in place (``network_to_half``,
``BN_convert_float``) and copies between ``.data`` buffers
(``master_params_to_model_params``). Params here are immutable pytrees, so
each helper is a pure tree transform built on the amp policy engine —
kept as a distinct API because a generation of training scripts speaks
it; new code should use :func:`apex_tpu.amp.initialize` (O2) instead,
exactly as the reference's docs point fp16_utils users at amp.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp import policy as _policy


def tofp16(params: Any, dtype=jnp.float16) -> Any:
    """Blanket cast of float leaves (ref: ``tofp16`` module wrapper).
    On TPU prefer bfloat16 — fp16 is supported but needs loss scaling."""
    return _policy.cast_params(params, dtype)


def network_to_half(params: Any, dtype=jnp.float16) -> Any:
    """Cast float params to half EXCEPT normalization params (ref:
    ``network_to_half`` = tofp16 + ``BN_convert_float``; the norm
    detection reuses amp's keep_batchnorm_fp32 path predicate)."""
    return _policy.cast_params(params, dtype, keep_batchnorm_fp32=True)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """(model_params, fp32 master copy) — ref: ``prep_param_lists``
    (which also flattens; flattening is the multi-tensor engine's job
    here and orthogonal to master-weight keeping)."""
    return params, _policy.master_params(params)


def master_params_to_model_params(model_params: Any, master: Any) -> Any:
    """Cast the fp32 master values into the model params' dtypes (ref:
    copies master ``.data`` into the fp16 model tensors)."""
    return jax.tree.map(
        lambda mp, ma: ma.astype(mp.dtype)
        if jnp.issubdtype(jnp.asarray(mp).dtype, jnp.floating) else mp,
        model_params, master)


def model_grads_to_master_grads(grads: Any) -> Any:
    """Upcast fp16 grads to fp32 for the master update (ref: copies
    ``.grad`` into fp32 buffers)."""
    return jax.tree.map(
        lambda g: g.astype(jnp.float32)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating) else g,
        grads)

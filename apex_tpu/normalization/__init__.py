"""Fused normalization layers (TPU-native).

Reference: ``apex/normalization/__init__.py`` exports ``FusedLayerNorm``,
``MixedFusedLayerNorm``, ``FusedRMSNorm``, ``MixedFusedRMSNorm`` backed by
the ``fused_layer_norm_cuda`` extension (``csrc/layer_norm_cuda_kernel.cu``).
Here the kernels are Pallas (row-tiled, fp32 accumulation) with
``jax.custom_vjp`` backward passes.
"""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)

"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with custom VJPs.

TPU-native equivalent of the reference's ``fused_layer_norm_cuda`` extension
(ref: ``csrc/layer_norm_cuda.cpp`` + ``csrc/layer_norm_cuda_kernel.cu``,
consumed by ``apex/normalization/fused_layer_norm.py :: FusedLayerNormAffineFunction``
/ ``FusedRMSNormAffineFunction`` / ``class FusedLayerNorm`` / ``class FusedRMSNorm``).

Design (vs. the CUDA reference):

- The CUDA kernels do a per-row Welford mean/var with warp reductions; on TPU
  a row tile of shape ``(TILE_R, H)`` sits in VMEM and the VPU reduces the
  hidden dim directly in fp32 — no Welford needed because the whole row is
  resident.
- The CUDA backward does a two-stage dgamma/dbeta reduction across threadblocks;
  here partial ``(1, H)`` sums are accumulated across sequential grid steps
  into a single fp32 output block (TPU grids execute sequentially, so the
  revisited output block is the accumulator).
- "Mixed" (fp16/bf16 activations with fp32 params and fp32 statistics) is the
  only behavior: statistics and all accumulation are always fp32; outputs take
  the input dtype, weight grads take the weight dtype.

Forward saves ``(x, weight[, bias-not-needed], mean, rstd)`` — the same
residual set the reference saves with ``ctx.save_for_backward``.
"""

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.math import round_up_to_multiple
from apex_tpu.utils.pallas import dimsem as _dimsem
from apex_tpu.utils.platform import pallas_interpret

Shape = Union[int, Sequence[int]]

_LANE = 128
_SUBLANE = 8

# VMEM working-set budget for choosing the row tile. A tile touches ~6 fp32
# row-blocks (x, y, dy, dx, xhat temp, wdy temp) at H columns each.
_VMEM_BUDGET = 8 * 1024 * 1024


def _normalized_size(normalized_shape: Shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    return int(np.prod(tuple(normalized_shape)))


def _row_tile(n_rows: int, h: int, n_bufs: int = 6) -> int:
    """Pick a row-tile size: multiple of the fp32 sublane count, bounded by
    the VMEM budget and the (padded) row count."""
    by_vmem = _VMEM_BUDGET // max(1, n_bufs * h * 4)
    tile = max(_SUBLANE, min(512, (by_vmem // _SUBLANE) * _SUBLANE))
    padded_rows = round_up_to_multiple(n_rows, _SUBLANE)
    return min(tile, max(_SUBLANE, padded_rows))


def _pad_rows(x2d: jax.Array, tile: int) -> Tuple[jax.Array, int]:
    rows = x2d.shape[0]
    padded = round_up_to_multiple(rows, tile)
    if padded != rows:
        x2d = jnp.pad(x2d, ((0, padded - rows), (0, 0)))
    return x2d, padded


# ---------------------------------------------------------------------------
# Kernels. ``mode`` is "ln" or "rms"; affine params are optional positionals.
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, mode: str, eps: float, has_w: bool, has_b: bool):
    it = iter(refs)
    x_ref = next(it)
    w_ref = next(it) if has_w else None
    b_ref = next(it) if has_b else None
    y_ref = next(it)
    mean_ref = next(it) if mode == "ln" else None
    rstd_ref = next(it)

    x = x_ref[:].astype(jnp.float32)
    if mode == "ln":
        mean = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = xc * rstd
        mean_ref[:] = mean
    else:
        ms = jnp.mean(x * x, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(ms + eps)
        xhat = x * rstd
    rstd_ref[:] = rstd

    y = xhat
    if has_w:
        y = y * w_ref[:].astype(jnp.float32)
    if has_b:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _bwd_kernel(*refs, mode: str, has_w: bool, has_b: bool,
                accum_parts: bool = False):
    it = iter(refs)
    dy_ref = next(it)
    x_ref = next(it)
    w_ref = next(it) if has_w else None
    mean_ref = next(it) if mode == "ln" else None
    rstd_ref = next(it)
    dx_ref = next(it)
    dw_ref = next(it) if has_w else None
    db_ref = next(it) if has_b else None

    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    if mode == "ln":
        xhat = (x - mean_ref[:]) * rstd
    else:
        xhat = x * rstd

    wdy = dy * w_ref[:].astype(jnp.float32) if has_w else dy
    c1 = jnp.mean(xhat * wdy, axis=1, keepdims=True)
    if mode == "ln":
        c2 = jnp.mean(wdy, axis=1, keepdims=True)
        dx = (wdy - xhat * c1 - c2) * rstd
    else:
        dx = (wdy - xhat * c1) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)

    # dgamma/dbeta — stage 2 of the CUDA kernel's two-stage threadblock
    # reduction, with a tile-size-dependent strategy (both measured on
    # v5e, 8192 rows):
    # - big tiles (h<=~2k): one (8, H) partial PER grid step (row 0 live,
    #   rows 1-7 zero for the sublane rule), summed by XLA outside —
    #   avoids the revisited output block that stalls the pipeline's
    #   output stage (h=1024: 90 -> 83 us/iter fwd+bwd);
    # - small tiles (big h): accumulate into one revisited (1, H) block —
    #   the per-step partial writes cost 8/tile of the stream bytes,
    #   a 10% regression at tile 80 (h=4096: 801 -> 841 us with partials).
    if accum_parts:
        if has_w:
            dw_ref[:] = jnp.concatenate(
                [jnp.sum(dy * xhat, axis=0, keepdims=True),
                 jnp.zeros((7, dy.shape[1]), jnp.float32)], axis=0)
        if has_b:
            db_ref[:] = jnp.concatenate(
                [jnp.sum(dy, axis=0, keepdims=True),
                 jnp.zeros((7, dy.shape[1]), jnp.float32)], axis=0)
        return
    step = pl.program_id(0)
    if has_w:
        @pl.when(step == 0)
        def _():
            dw_ref[:] = jnp.zeros_like(dw_ref)
        dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    if has_b:
        @pl.when(step == 0)
        def _():
            db_ref[:] = jnp.zeros_like(db_ref)
        db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


# -- column-split backward (large H) ----------------------------------------
#
# At big H the full-row tile is VMEM-starved (h=4096: 80 rows/step) and the
# measured bandwidth collapses (420 GB/s vs ~1040 at h=1024) — the single
# revisited (1, H) dgamma accumulator is the wrong structure, not the wrong
# tile size. Column-split restructuring: two passes over (TR, TC) blocks.
#
#   pass A (grid ri × ci, ci inner): accumulate the per-row sums that need
#     the whole row — c1s = Σ_h xhat·wdy and (LN) c2s = Σ_h wdy — into a
#     revisited (TR, 1) block, AND the per-column dgamma/dbeta partials
#     into a (1, H_p) accumulator that lives in VMEM for the whole grid
#     (16 KB at h=4096), written via a pl.ds column slice.
#   pass B (grid ci × ri, ri inner): dx = (wdy − xhat·c1 − c2)·rstd with
#     c1/c2 read back as (TR, 1) blocks — pure streaming, no reductions.
#
# Costs one extra read of (x, dy) vs the single-pass kernel, but every
# block is MXU/VPU-sized (512×512) regardless of H, which is the point.

_COL_TILE = 512
_ROW_TILE_CAP = 512  # colsplit row-block cap


def _bwd_colsum_kernel(*refs, mode, has_w, has_b):
    it = iter(refs)
    dy_ref = next(it)
    x_ref = next(it)
    w_ref = next(it) if has_w else None
    mean_ref = next(it) if mode == "ln" else None
    rstd_ref = next(it)
    c1_ref = next(it)
    c2_ref = next(it) if mode == "ln" else None
    dw_ref = next(it) if has_w else None
    db_ref = next(it) if has_b else None

    ri, ci = pl.program_id(0), pl.program_id(1)
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = (x - mean_ref[:]) * rstd if mode == "ln" else x * rstd
    wdy = dy * w_ref[:].astype(jnp.float32) if has_w else dy

    @pl.when(ci == 0)
    def _():
        c1_ref[:] = jnp.zeros_like(c1_ref)
        if mode == "ln":
            c2_ref[:] = jnp.zeros_like(c2_ref)
    c1_ref[:] += jnp.sum(xhat * wdy, axis=1, keepdims=True)
    if mode == "ln":
        c2_ref[:] += jnp.sum(wdy, axis=1, keepdims=True)

    first = jnp.logical_and(ri == 0, ci == 0)
    tc = dy.shape[1]
    if has_w:
        @pl.when(first)
        def _():
            dw_ref[:] = jnp.zeros_like(dw_ref)
        dw_ref[0:1, pl.ds(ci * tc, tc)] += jnp.sum(
            dy * xhat, axis=0, keepdims=True)
    if has_b:
        @pl.when(first)
        def _():
            db_ref[:] = jnp.zeros_like(db_ref)
        db_ref[0:1, pl.ds(ci * tc, tc)] += jnp.sum(
            dy, axis=0, keepdims=True)


def _bwd_dx_kernel(*refs, mode, has_w, inv_h):
    it = iter(refs)
    dy_ref = next(it)
    x_ref = next(it)
    w_ref = next(it) if has_w else None
    mean_ref = next(it) if mode == "ln" else None
    rstd_ref = next(it)
    c1_ref = next(it)
    c2_ref = next(it) if mode == "ln" else None
    dx_ref = next(it)

    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = (x - mean_ref[:]) * rstd if mode == "ln" else x * rstd
    wdy = dy * w_ref[:].astype(jnp.float32) if has_w else dy
    c1 = c1_ref[:] * inv_h
    if mode == "ln":
        dx = (wdy - xhat * c1 - c2_ref[:] * inv_h) * rstd
    else:
        dx = (wdy - xhat * c1) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _pad_cols(x2d, h_p):
    h = x2d.shape[1]
    if h_p != h:
        x2d = jnp.pad(x2d, ((0, 0), (0, h_p - h)))
    return x2d


def _bwd_call_colsplit(dy2d, x2d, w, mean, rstd, mode, has_b, interpret):
    rows, h = x2d.shape
    tc = _COL_TILE
    tr = min(_ROW_TILE_CAP, round_up_to_multiple(rows, _SUBLANE))
    has_w = w is not None
    h_p = round_up_to_multiple(h, tc)
    xp, padded = _pad_rows(_pad_cols(x2d, h_p), tr)
    dyp, _ = _pad_rows(_pad_cols(dy2d, h_p), tr)
    meanp = _pad_rows(mean, tr)[0] if mode == "ln" else None
    rstdp, _ = _pad_rows(rstd, tr)
    wp = _pad_cols(w.reshape(1, h), h_p) if has_w else None
    nri, nci = padded // tr, h_p // tc

    blk = pl.BlockSpec((tr, tc), lambda ri, ci: (ri, ci),
                       memory_space=pltpu.VMEM)
    wspec = pl.BlockSpec((1, tc), lambda ri, ci: (0, ci),
                         memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((tr, 1), lambda ri, ci: (ri, 0),
                        memory_space=pltpu.VMEM)
    grow = pl.BlockSpec((1, h_p), lambda ri, ci: (0, 0),
                        memory_space=pltpu.VMEM)

    in_specs = [blk, blk]
    args = [dyp, xp]
    if has_w:
        in_specs.append(wspec)
        args.append(wp)
    if mode == "ln":
        in_specs.append(stat)
        args.append(meanp)
    in_specs.append(stat)
    args.append(rstdp)

    out_specs = [stat]
    out_shape = [jax.ShapeDtypeStruct((padded, 1), jnp.float32)]
    if mode == "ln":
        out_specs.append(stat)
        out_shape.append(jax.ShapeDtypeStruct((padded, 1), jnp.float32))
    if has_w:
        out_specs.append(grow)
        out_shape.append(jax.ShapeDtypeStruct((1, h_p), jnp.float32))
    if has_b:
        out_specs.append(grow)
        out_shape.append(jax.ShapeDtypeStruct((1, h_p), jnp.float32))

    outs = pl.pallas_call(
        functools.partial(_bwd_colsum_kernel, mode=mode, has_w=has_w,
                          has_b=has_b),
        grid=(nri, nci),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_dimsem("arbitrary", "arbitrary"),
        interpret=pallas_interpret(interpret),
    )(*args)
    outs = list(outs)
    c1s = outs.pop(0)
    c2s = outs.pop(0) if mode == "ln" else None
    dw = outs.pop(0)[0, :h] if has_w else None
    db = outs.pop(0)[0, :h] if has_b else None

    # pass B: ri innermost so dx blocks stream; stats re-read per row tile
    blk2 = pl.BlockSpec((tr, tc), lambda ci, ri: (ri, ci),
                        memory_space=pltpu.VMEM)
    wspec2 = pl.BlockSpec((1, tc), lambda ci, ri: (0, ci),
                          memory_space=pltpu.VMEM)
    stat2 = pl.BlockSpec((tr, 1), lambda ci, ri: (ri, 0),
                         memory_space=pltpu.VMEM)
    in_specs2 = [blk2, blk2]
    args2 = [dyp, xp]
    if has_w:
        in_specs2.append(wspec2)
        args2.append(wp)
    if mode == "ln":
        in_specs2.append(stat2)
        args2.append(meanp)
    in_specs2.append(stat2)
    args2.append(rstdp)
    in_specs2.append(stat2)
    args2.append(c1s)
    if mode == "ln":
        in_specs2.append(stat2)
        args2.append(c2s)

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, mode=mode, has_w=has_w,
                          inv_h=1.0 / h),
        grid=(nci, nri),
        in_specs=in_specs2,
        out_specs=blk2,
        out_shape=jax.ShapeDtypeStruct((padded, h_p), x2d.dtype),
        compiler_params=_dimsem("parallel", "parallel"),
        interpret=pallas_interpret(interpret),
    )(*args2)
    return dx[:rows, :h], dw, db


def _row_spec(tile: int, h: int):
    return pl.BlockSpec((tile, h), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _stat_spec(tile: int):
    return pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _full_spec(h: int):
    return pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)


def _fwd_call(x2d, w, b, mode, eps, interpret):
    rows, h = x2d.shape
    tile = _row_tile(rows, h, n_bufs=4)
    xp, padded = _pad_rows(x2d, tile)
    grid = padded // tile

    in_specs = [_row_spec(tile, h)]
    args = [xp]
    if w is not None:
        in_specs.append(_full_spec(h))
        args.append(w.reshape(1, h))
    if b is not None:
        in_specs.append(_full_spec(h))
        args.append(b.reshape(1, h))

    out_shape = [jax.ShapeDtypeStruct((padded, h), x2d.dtype)]
    out_specs = [_row_spec(tile, h)]
    if mode == "ln":
        out_shape.append(jax.ShapeDtypeStruct((padded, 1), jnp.float32))
        out_specs.append(_stat_spec(tile))
    out_shape.append(jax.ShapeDtypeStruct((padded, 1), jnp.float32))
    out_specs.append(_stat_spec(tile))

    kernel = functools.partial(
        _fwd_kernel, mode=mode, eps=eps, has_w=w is not None, has_b=b is not None
    )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(*args)
    outs = [o[:rows] for o in outs]
    if mode == "ln":
        y, mean, rstd = outs
        return y, mean, rstd
    y, rstd = outs
    return y, None, rstd


def _bwd_call(dy2d, x2d, w, mean, rstd, mode, has_b, interpret):
    rows, h = x2d.shape
    tile = _row_tile(rows, h, n_bufs=6)
    # dispatch on the VMEM-derived tile (NOT the row-count-clamped one:
    # a short input at moderate H is not a reason to pay two passes)
    vmem_tile = (_VMEM_BUDGET // (6 * h * 4) // _SUBLANE) * _SUBLANE
    if vmem_tile < 128 and h >= _COL_TILE:
        # full-row tiles have shrunk below the pipelining sweet spot —
        # switch to the column-split structure (measured: h=4096 fwd+bwd
        # 420 GB/s single-pass vs the colsplit restructure; see above)
        return _bwd_call_colsplit(dy2d, x2d, w, mean, rstd, mode, has_b,
                                  interpret)
    xp, padded = _pad_rows(x2d, tile)
    dyp, _ = _pad_rows(dy2d, tile)
    meanp = _pad_rows(mean, tile)[0] if mode == "ln" else None
    rstdp, _ = _pad_rows(rstd, tile)
    grid = padded // tile
    has_w = w is not None

    in_specs = [_row_spec(tile, h), _row_spec(tile, h)]
    args = [dyp, xp]
    if has_w:
        in_specs.append(_full_spec(h))
        args.append(w.reshape(1, h))
    if mode == "ln":
        in_specs.append(_stat_spec(tile))
        args.append(meanp)
    in_specs.append(_stat_spec(tile))
    args.append(rstdp)

    # partial-per-step writes cost 8/tile of the row streams: worth it
    # only when tiles are big (see the kernel's strategy note)
    accum_parts = tile >= 128
    if accum_parts:
        gw_spec = pl.BlockSpec((8, h), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
        gw_shape = jax.ShapeDtypeStruct((grid * 8, h), jnp.float32)
    else:
        gw_spec = _full_spec(h)
        gw_shape = jax.ShapeDtypeStruct((1, h), jnp.float32)
    out_shape = [jax.ShapeDtypeStruct((padded, h), x2d.dtype)]
    out_specs = [_row_spec(tile, h)]
    if has_w:
        out_shape.append(gw_shape)
        out_specs.append(gw_spec)
    if has_b:
        out_shape.append(gw_shape)
        out_specs.append(gw_spec)

    kernel = functools.partial(
        _bwd_kernel, mode=mode, has_w=has_w, has_b=has_b,
        accum_parts=accum_parts,
    )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_dimsem("arbitrary"),
        interpret=pallas_interpret(interpret),
    )(*args)
    outs = list(outs)
    dx = outs.pop(0)[:rows]
    dw = outs.pop(0).sum(axis=0) if has_w else None
    db = outs.pop(0).sum(axis=0) if has_b else None
    return dx, dw, db


# ---------------------------------------------------------------------------
# custom_vjp cores. eps/interpret are non-diff leading args (hashable
# statics), mirroring the reference's autograd.Function ctx attributes.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ln_affine(eps, interpret, x2d, w, b):
    y, _, _ = _fwd_call(x2d, w, b, "ln", eps, interpret)
    return y

def _ln_affine_fwd(eps, interpret, x2d, w, b):
    y, mean, rstd = _fwd_call(x2d, w, b, "ln", eps, interpret)
    # b rides along only to carry its dtype for the cotangent (it is (H,),
    # negligible next to the x residual).
    return y, (x2d, w, b, mean, rstd)

def _ln_affine_bwd(eps, interpret, res, dy):
    x2d, w, b, mean, rstd = res
    dx, dw, db = _bwd_call(dy, x2d, w, mean, rstd, "ln", True, interpret)
    return dx, dw.astype(w.dtype), db.astype(b.dtype)

_ln_affine.defvjp(_ln_affine_fwd, _ln_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ln_plain(eps, interpret, x2d):
    y, _, _ = _fwd_call(x2d, None, None, "ln", eps, interpret)
    return y

def _ln_plain_fwd(eps, interpret, x2d):
    y, mean, rstd = _fwd_call(x2d, None, None, "ln", eps, interpret)
    return y, (x2d, mean, rstd)

def _ln_plain_bwd(eps, interpret, res, dy):
    x2d, mean, rstd = res
    dx, _, _ = _bwd_call(dy, x2d, None, mean, rstd, "ln", False, interpret)
    return (dx,)

_ln_plain.defvjp(_ln_plain_fwd, _ln_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _rms_affine(eps, interpret, x2d, w):
    y, _, _ = _fwd_call(x2d, w, None, "rms", eps, interpret)
    return y

def _rms_affine_fwd(eps, interpret, x2d, w):
    y, _, rstd = _fwd_call(x2d, w, None, "rms", eps, interpret)
    return y, (x2d, w, rstd)

def _rms_affine_bwd(eps, interpret, res, dy):
    x2d, w, rstd = res
    dx, dw, _ = _bwd_call(dy, x2d, w, None, rstd, "rms", False, interpret)
    return dx, dw.astype(w.dtype)

_rms_affine.defvjp(_rms_affine_fwd, _rms_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _rms_plain(eps, interpret, x2d):
    y, _, _ = _fwd_call(x2d, None, None, "rms", eps, interpret)
    return y

def _rms_plain_fwd(eps, interpret, x2d):
    y, _, rstd = _fwd_call(x2d, None, None, "rms", eps, interpret)
    return y, (x2d, rstd)

def _rms_plain_bwd(eps, interpret, res, dy):
    x2d, rstd = res
    dx, _, _ = _bwd_call(dy, x2d, None, None, rstd, "rms", False, interpret)
    return (dx,)

_rms_plain.defvjp(_rms_plain_fwd, _rms_plain_bwd)


# ---------------------------------------------------------------------------
# Public functional API (names mirror apex/normalization/fused_layer_norm.py).
# ---------------------------------------------------------------------------

def fused_layer_norm_affine(x, weight, bias, normalized_shape: Shape,
                            eps: float = 1e-5, *, interpret: Optional[bool] = None):
    """LayerNorm over the trailing ``normalized_shape`` dims with affine
    params (ref: ``fused_layer_norm_affine``)."""
    h = _normalized_size(normalized_shape)
    y = _ln_affine(float(eps), interpret, x.reshape(-1, h),
                   weight.reshape(h), bias.reshape(h))
    return y.reshape(x.shape)


def fused_layer_norm(x, normalized_shape: Shape, eps: float = 1e-5,
                     *, interpret: Optional[bool] = None):
    h = _normalized_size(normalized_shape)
    return _ln_plain(float(eps), interpret, x.reshape(-1, h)).reshape(x.shape)


def fused_rms_norm_affine(x, weight, normalized_shape: Shape,
                          eps: float = 1e-5, *, interpret: Optional[bool] = None):
    h = _normalized_size(normalized_shape)
    y = _rms_affine(float(eps), interpret, x.reshape(-1, h), weight.reshape(h))
    return y.reshape(x.shape)


def fused_rms_norm(x, normalized_shape: Shape, eps: float = 1e-5,
                   *, interpret: Optional[bool] = None):
    h = _normalized_size(normalized_shape)
    return _rms_plain(float(eps), interpret, x.reshape(-1, h)).reshape(x.shape)


# ---------------------------------------------------------------------------
# Module-shaped API. Functional modules: ``init()`` -> params dict,
# ``apply(params, x)`` -> output (ref: ``class FusedLayerNorm(torch.nn.Module)``).
# ---------------------------------------------------------------------------

class FusedLayerNorm:
    """LayerNorm module (ref: ``apex/normalization/fused_layer_norm.py ::
    class FusedLayerNorm``). Params live in a dict pytree; stats are fp32."""

    mode = "ln"

    def __init__(self, normalized_shape: Shape, eps: float = 1e-5,
                 elementwise_affine: bool = True, param_dtype=jnp.float32):
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = float(eps)
        self.elementwise_affine = bool(elementwise_affine)
        self.param_dtype = param_dtype

    def init(self, key: Optional[jax.Array] = None) -> dict:
        del key  # LN init is deterministic (weight=1, bias=0)
        if not self.elementwise_affine:
            return {}
        params = {"weight": jnp.ones(self.normalized_shape, self.param_dtype)}
        if self.mode == "ln":
            params["bias"] = jnp.zeros(self.normalized_shape, self.param_dtype)
        return params

    def apply(self, params: dict, x, *, interpret: Optional[bool] = None):
        if self.mode == "ln":
            if self.elementwise_affine:
                return fused_layer_norm_affine(
                    x, params["weight"], params["bias"],
                    self.normalized_shape, self.eps, interpret=interpret)
            return fused_layer_norm(x, self.normalized_shape, self.eps,
                                    interpret=interpret)
        if self.elementwise_affine:
            return fused_rms_norm_affine(x, params["weight"],
                                         self.normalized_shape, self.eps,
                                         interpret=interpret)
        return fused_rms_norm(x, self.normalized_shape, self.eps,
                              interpret=interpret)

    __call__ = apply


class FusedRMSNorm(FusedLayerNorm):
    """RMSNorm module (ref: ``class FusedRMSNorm``): no mean subtraction,
    no bias."""

    mode = "rms"


class MixedFusedLayerNorm(FusedLayerNorm):
    """fp16/bf16 activations with fp32 params & stats (ref:
    ``class MixedFusedLayerNorm``). Our kernels always keep stats fp32, so
    "mixed" only pins the param dtype."""

    def __init__(self, normalized_shape: Shape, eps: float = 1e-5, **kw):
        kw.pop("param_dtype", None)
        super().__init__(normalized_shape, eps, param_dtype=jnp.float32, **kw)


class MixedFusedRMSNorm(FusedRMSNorm):
    def __init__(self, normalized_shape: Shape, eps: float = 1e-5, **kw):
        kw.pop("param_dtype", None)
        super().__init__(normalized_shape, eps, param_dtype=jnp.float32, **kw)

"""Fused dense blocks (ref: ``apex/fused_dense``)."""

from apex_tpu.fused_dense.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
)

"""Fused dense blocks (ref: ``apex/fused_dense/fused_dense.py`` over
``fused_dense_cuda`` — linear+bias in one GEMM-epilogue launch, and
linear→GELU→linear with the GELU fused between the GEMMs).

On TPU both fusions are XLA's standard epilogue/elementwise fusion; the
modules exist for API parity and as the idiomatic spot to hang the O1
autocast policy. The GELU here is the exact (erf) form the reference
kernel implements."""

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from apex_tpu.amp.autocast import cast_args


def _init(key, fi, fo, dtype):
    bound = 1.0 / math.sqrt(fi)
    return jax.random.uniform(key, (fi, fo), dtype, -bound, bound)


def _dense(p, x):
    x, kernel = cast_args("dense", x, p["kernel"])
    y = jnp.dot(x, kernel.astype(x.dtype))
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


class FusedDense:
    """Linear + bias (ref: ``FusedDense``)."""

    def __init__(self, in_features: int, out_features: int, *,
                 bias: bool = True, params_dtype=jnp.float32):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.params_dtype = params_dtype

    def init(self, key: jax.Array) -> Dict[str, Any]:
        p = {"kernel": _init(key, self.in_features, self.out_features,
                             self.params_dtype)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.out_features,), self.params_dtype)
        return p

    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        return _dense(params, x)

    __call__ = apply


class FusedDenseGeluDense:
    """Linear → GELU (exact) → Linear (ref: ``FusedDenseGeluDense``)."""

    def __init__(self, in_features: int, intermediate_features: int,
                 out_features: int, *, bias: bool = True,
                 params_dtype=jnp.float32):
        self.in_features = in_features
        self.intermediate_features = intermediate_features
        self.out_features = out_features
        self.use_bias = bias
        self.params_dtype = params_dtype

    def init(self, key: jax.Array) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        p = {
            "fc1": {"kernel": _init(k1, self.in_features,
                                    self.intermediate_features,
                                    self.params_dtype)},
            "fc2": {"kernel": _init(k2, self.intermediate_features,
                                    self.out_features, self.params_dtype)},
        }
        if self.use_bias:
            p["fc1"]["bias"] = jnp.zeros((self.intermediate_features,),
                                         self.params_dtype)
            p["fc2"]["bias"] = jnp.zeros((self.out_features,),
                                         self.params_dtype)
        return p

    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        h = _dense(params["fc1"], x)
        h = jax.nn.gelu(h, approximate=False)
        return _dense(params["fc2"], h)

    __call__ = apply

"""MLP block (ref: ``apex/mlp/mlp.py :: class MLP`` over ``mlp_cuda``).

The CUDA extension exists to fuse the whole linear→bias→ReLU chain into
one kernel launch with a hand-written backward. On TPU that is XLA's
default behavior: the bias-add and activation fuse into the matmul's
epilogue, and the chain compiles to back-to-back MXU ops with no
intermediate HBM round-trips — so this module is the *API*, not a
kernel. The one knob fusion cannot give you is memory: ``remat=True``
wraps the chain in ``jax.checkpoint`` (recompute instead of storing the
per-layer activations), the TPU analogue of the CUDA kernel's fused
backward reusing forward intermediates.
"""

import math
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.amp.autocast import cast_args

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


class MLP:
    """``MLP([in, h1, ..., out])`` — a chain of ``len(sizes)-1`` linear
    layers with ``activation`` between them (and after the last layer,
    matching the reference, which applies it uniformly)."""

    def __init__(self, mlp_sizes: Sequence[int], *, bias: bool = True,
                 activation: str = "relu", relu: bool = True,
                 params_dtype=jnp.float32, remat: bool = False):
        if len(mlp_sizes) < 2:
            raise ValueError("MLP needs at least [in, out] sizes")
        if activation not in _ACTIVATIONS:
            raise ValueError(
                f"activation must be one of {sorted(_ACTIVATIONS)}")
        if not relu:  # reference back-compat flag
            activation = "none"
        self.sizes = list(mlp_sizes)
        self.use_bias = bias
        self.activation = activation
        self.params_dtype = params_dtype
        self.remat = remat

    def init(self, key: jax.Array) -> List[Dict[str, Any]]:
        layers = []
        for k, (fi, fo) in zip(jax.random.split(key, len(self.sizes) - 1),
                               zip(self.sizes[:-1], self.sizes[1:])):
            # reference init: uniform(-1/sqrt(fan_in), 1/sqrt(fan_in))
            bound = 1.0 / math.sqrt(fi)
            p = {"kernel": jax.random.uniform(
                k, (fi, fo), self.params_dtype, -bound, bound)}
            if self.use_bias:
                p["bias"] = jnp.zeros((fo,), self.params_dtype)
            layers.append(p)
        return layers

    def apply(self, params: List[Dict[str, Any]], x: jax.Array
              ) -> jax.Array:
        act = _ACTIVATIONS[self.activation]

        def chain(params, x):
            for p in params:
                xi, kernel = cast_args("dense", x, p["kernel"])
                x = jnp.dot(xi, kernel.astype(xi.dtype))
                if "bias" in p:
                    x = x + p["bias"].astype(x.dtype)
                x = act(x)
            return x

        if self.remat:
            chain = jax.checkpoint(chain)
        return chain(params, x)

    __call__ = apply

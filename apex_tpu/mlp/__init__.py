"""Fused MLP (ref: ``apex/mlp``)."""

from apex_tpu.mlp.mlp import MLP  # noqa: F401

"""Fused optimizers (ref: ``apex/optimizers``).

Functional API: ``state = opt.init(params)``;
``params, state = opt.step(grads, params, state, found_inf=...)``.
All state is fp32 (master-quality), updates computed in fp32 and cast back
to the param dtype — the master-weight property of the reference's
``master_weights``/``capturable`` variants is the default here.
"""

from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState  # noqa: F401
from apex_tpu.optimizers.fused_novograd import (  # noqa: F401
    FusedNovoGrad,
    NovoGradState,
)
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState  # noqa: F401

"""FusedLAMB — ref ``apex/optimizers/fused_lamb.py :: class FusedLAMB``
(kernels: ``csrc/multi_tensor_lamb.cu`` / ``_stage_1`` / ``_stage_2``).

The two CUDA stages map onto:
stage 1 — grad clipping by the GLOBAL grad norm, then Adam-style moments and
the raw update ``u = m̂/(√v̂+eps) + wd·p``;
stage 2 — per-TENSOR trust ratio ``||p|| / ||u||`` applied with the lr.

Per-tensor norms are per-leaf reductions here (each leaf IS a tensor);
under sharding the global norm must be psum-ed — pass ``grad_norm`` in if
you computed it with a collective.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    check_m_dtype, f32, finish_compute_params, global_grad_norm,
    select_finite, tree_unzip, tree_zeros, tree_zeros_f32,
)


class LambState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


class FusedLAMB:
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, amsgrad: bool = False,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, *,
                 use_flat_kernel: bool = False,
                 m_dtype=jnp.float32, emit_compute_params: bool = False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        self.m_dtype = check_m_dtype(m_dtype)
        self.emit_compute_params = emit_compute_params
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        # NVLAMB: apply the trust ratio even to tensors with no weight decay
        self.use_nvlamb = use_nvlamb
        self.use_flat_kernel = use_flat_kernel
        self._specs = {}

    def _layout(self, params):
        from apex_tpu.optimizers._common import flat_layout

        return flat_layout(self._specs, params)

    def init(self, params: Any) -> LambState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            from apex_tpu.multi_tensor_apply import flatten as _flatten

            leaves, _, spec, _ = self._layout(params)
            return LambState(step=step,
                             m=_flatten.zeros_buffer(spec, self.m_dtype),
                             v=_flatten.zeros_buffer(spec, jnp.float32))
        return LambState(step=step,
                         m=tree_zeros(params, self.m_dtype),
                         v=tree_zeros_f32(params))

    def step(self, grads: Any, params: Any, state: LambState, *,
             lr=None, weight_decay=None, grad_scale=1.0,
             grad_norm: Optional[jax.Array] = None,
             found_inf: Optional[jax.Array] = None,
             compute_params: Optional[Any] = None):
        """``grad_scale`` MULTIPLIES the gradients (combined inverse loss
        scale: pass ``1 / loss_scale``); the reference's ``scale`` arg
        DIVIDES — invert when porting. With ``emit_compute_params`` the
        return grows to ``(params, state, compute)``. See
        ``FusedAdam.step``."""
        lr = f32(self.lr if lr is None else lr)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        gs = f32(grad_scale)
        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        t = state.step + 1
        tf = t.astype(jnp.float32)
        beta3 = 1.0 - b1 if self.grad_averaging else jnp.float32(1.0)
        if self.bias_correction:
            c1 = 1.0 - b1 ** tf
            c2 = 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)

        if self.use_flat_kernel:
            from apex_tpu.multi_tensor_apply import flatten as _flatten
            from apex_tpu.multi_tensor_apply.kernels import flat_lamb

            leaves, treedef, spec, tile_ids = self._layout(params)
            gbuf, _ = _flatten.flatten_tensors(
                jax.tree_util.tree_leaves(grads), spec)
            pbuf, _ = _flatten.flatten_tensors(leaves, spec)
            emit_dt = jnp.bfloat16 if self.emit_compute_params else None
            outs = flat_lamb(
                gbuf, pbuf, state.m, state.v, tile_ids,
                lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                step=t, weight_decay=wd, num_tensors=spec.num_tensors,
                adam_w_mode=self.adam_w_mode,
                grad_averaging=self.grad_averaging,
                bias_correction=self.bias_correction,
                use_nvlamb=self.use_nvlamb,
                max_grad_norm=self.max_grad_norm, grad_scale=gs,
                grad_norm=grad_norm, emit_compute_dtype=emit_dt)
            p_new, m_new, v_new = outs[:3]
            new_params = jax.tree_util.tree_unflatten(
                treedef, _flatten.unflatten_tensors(p_new, spec))
            new_state = LambState(step=t, m=m_new, v=v_new)
            new_params = select_finite(found_inf, new_params, params)
            new_state = select_finite(found_inf, new_state, state)
            if not self.emit_compute_params:
                return new_params, new_state
            pc = jax.tree_util.tree_unflatten(
                treedef,
                _flatten.unflatten_tensors(outs[3], spec, cast_back=False))
            if compute_params is not None:
                pc = jax.tree.map(
                    lambda c, tmpl, p: c if c.dtype == tmpl.dtype
                    else p.astype(tmpl.dtype),
                    pc, compute_params, new_params)
            compute = finish_compute_params(
                new_params, params, compute_params, found_inf,
                precomputed=pc)
            return new_params, new_state, compute

        # stage 1 preamble: global-norm grad clipping
        if grad_norm is None:
            grad_norm = global_grad_norm(
                jax.tree.map(lambda g: g.astype(jnp.float32) * gs, grads))
        max_norm = f32(self.max_grad_norm)
        clip = jnp.where(
            (max_norm > 0) & (grad_norm > max_norm),
            grad_norm / max_norm, jnp.float32(1.0))

        def upd(g, p, m, v):
            g = g.astype(jnp.float32) * gs / clip
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode:
                g = g + wd * p32
            m = b1 * m.astype(jnp.float32) + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if self.adam_w_mode:
                u = u + wd * p32
            # stage 2: layer-wise trust ratio
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(u * u))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / u_norm, jnp.float32(1.0))
            if not self.use_nvlamb:
                # reference: without NVLAMB, params with no weight decay
                # skip the trust-ratio (decoupled_wd group split); wd is a
                # scalar here so the split reduces to this where().
                ratio = jnp.where(wd == 0.0, jnp.float32(1.0), ratio)
            return ((p32 - lr * ratio * u).astype(p.dtype),
                    m.astype(self.m_dtype), v)

        out = jax.tree.map(upd, grads, params, state.m, state.v)
        new_params, new_m, new_v = tree_unzip(out, 3)
        new_state = LambState(step=t, m=new_m, v=new_v)

        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        if not self.emit_compute_params:
            return new_params, new_state
        compute = finish_compute_params(new_params, params, compute_params,
                                        found_inf)
        return new_params, new_state, compute

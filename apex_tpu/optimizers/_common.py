"""Shared helpers for the fused optimizers.

The reference optimizers (``apex/optimizers``) mutate params in place and
read ``param.grad``; here every optimizer is functional:

    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    params, state = opt.step(grads, params, state [, found_inf=...])

``found_inf`` (a traced bool from the AMP scaler) turns the step into a
no-op, reproducing the reference's skip-on-overflow wiring without the
optimizer/scaler back-channel (``_amp_stash``).
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import apply_if_finite
from apex_tpu.multi_tensor_apply import multi_tensor_l2norm

# dtypes accepted for reduced-precision first moments (``m_dtype``): fp32
# is exact apex semantics; bf16 halves the moment's HBM bytes with fp32
# accumulate inside the kernel (v always stays fp32).
_STATE_DTYPES = (jnp.float32, jnp.bfloat16)


def check_m_dtype(m_dtype) -> Any:
    dt = jnp.dtype(m_dtype)
    if not any(dt == jnp.dtype(d) for d in _STATE_DTYPES):
        raise ValueError(
            f"m_dtype must be float32 or bfloat16, got {dt}")
    return dt


def tree_zeros_f32(params: Any) -> Any:
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def tree_zeros(params: Any, dtype) -> Any:
    return jax.tree.map(lambda p: jnp.zeros(p.shape, dtype), params)


def select_finite(found_inf: Optional[jax.Array], new: Any, old: Any) -> Any:
    """Keep ``old`` wherever the step must be skipped (None = never skip)."""
    if found_inf is None:
        return new
    return apply_if_finite(new, old, found_inf)


def f32(x) -> jax.Array:
    return jnp.asarray(x, jnp.float32)


def global_grad_norm(grads: Any) -> jax.Array:
    return multi_tensor_l2norm(jax.tree.leaves(grads))


def tree_unzip(out: Any, n: int) -> Tuple[Any, ...]:
    """Split a tree whose leaves are n-tuples into n trees (the common
    post-``tree.map`` unpacking in every optimizer's step)."""
    is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
    return tuple(
        jax.tree.map(lambda o, i=i: o[i], out, is_leaf=is_tup)
        for i in range(n))


def cast_like(tree: Any, template: Optional[Any],
              default_dtype=jnp.bfloat16) -> Any:
    """Cast each floating leaf of ``tree`` to the dtype of the matching
    ``template`` leaf (or ``default_dtype`` when ``template`` is None) —
    the tree-path compute-param emission. XLA fuses these casts into the
    kernel that produced ``tree``, so emission costs one extra low-
    precision write, not a separate read-the-master pass."""
    if template is None:
        return jax.tree.map(
            lambda x: x.astype(default_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)
    return jax.tree.map(
        lambda x, t: x.astype(t.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, tree, template)


def finish_compute_params(new_params: Any, params: Any,
                          compute_params: Optional[Any],
                          found_inf: Optional[jax.Array],
                          precomputed: Optional[Any] = None) -> Any:
    """Shared tail of every optimizer's ``emit_compute_params`` path.

    ``precomputed`` is the kernel-emitted compute tree (flat paths);
    the tree paths leave it None and cast ``new_params`` per-leaf.
    ``compute_params`` (the previous compute tree) supplies the target
    dtypes and the cheap old value for the overflow-skip select; without
    it the skip falls back to re-casting the old master (correct, but
    pays the cast the fused path exists to avoid — pass it when using
    dynamic loss scaling)."""
    new_c = precomputed if precomputed is not None else \
        cast_like(new_params, compute_params)
    if found_inf is None:
        return new_c
    old_c = compute_params if compute_params is not None else \
        cast_like(params, None)
    return apply_if_finite(new_c, old_c, found_inf)


def flat_layout(cache: dict, params: Any):
    """Cached flat-buffer layout for the ``use_flat_kernel`` paths.

    Returns ``(leaves, treedef, spec, tile_ids)``. Keyed by
    ``(treedef, shapes, dtypes)`` — one optimizer instance may serve
    several param trees, and same-structure trees with different leaf
    shapes must not share a FlatSpec. ``tile_ids`` is
    ``spec.tile_tensor_ids(8)``, computed once per layout (used by the
    per-tensor reductions of LAMB/NovoGrad; harmless elsewhere).
    """
    from apex_tpu.multi_tensor_apply import flatten as _flatten

    leaves, treedef = jax.tree_util.tree_flatten(params)
    key = (treedef, tuple((l.shape, jnp.dtype(l.dtype)) for l in leaves))
    ent = cache.get(key)
    if ent is None:
        spec = _flatten.make_spec(leaves)
        ent = cache[key] = (spec, spec.tile_tensor_ids(8))
    return leaves, treedef, ent[0], ent[1]

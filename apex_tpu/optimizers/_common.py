"""Shared helpers for the fused optimizers.

The reference optimizers (``apex/optimizers``) mutate params in place and
read ``param.grad``; here every optimizer is functional:

    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    params, state = opt.step(grads, params, state [, found_inf=...])

``found_inf`` (a traced bool from the AMP scaler) turns the step into a
no-op, reproducing the reference's skip-on-overflow wiring without the
optimizer/scaler back-channel (``_amp_stash``).
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import apply_if_finite
from apex_tpu.multi_tensor_apply import multi_tensor_l2norm


def tree_zeros_f32(params: Any) -> Any:
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def select_finite(found_inf: Optional[jax.Array], new: Any, old: Any) -> Any:
    """Keep ``old`` wherever the step must be skipped (None = never skip)."""
    if found_inf is None:
        return new
    return apply_if_finite(new, old, found_inf)


def f32(x) -> jax.Array:
    return jnp.asarray(x, jnp.float32)


def global_grad_norm(grads: Any) -> jax.Array:
    return multi_tensor_l2norm(jax.tree.leaves(grads))


def tree_unzip(out: Any, n: int) -> Tuple[Any, ...]:
    """Split a tree whose leaves are n-tuples into n trees (the common
    post-``tree.map`` unpacking in every optimizer's step)."""
    is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
    return tuple(
        jax.tree.map(lambda o, i=i: o[i], out, is_leaf=is_tup)
        for i in range(n))


def flat_layout(cache: dict, params: Any):
    """Cached flat-buffer layout for the ``use_flat_kernel`` paths.

    Returns ``(leaves, treedef, spec, tile_ids)``. Keyed by
    ``(treedef, shapes, dtypes)`` — one optimizer instance may serve
    several param trees, and same-structure trees with different leaf
    shapes must not share a FlatSpec. ``tile_ids`` is
    ``spec.tile_tensor_ids(8)``, computed once per layout (used by the
    per-tensor reductions of LAMB/NovoGrad; harmless elsewhere).
    """
    from apex_tpu.multi_tensor_apply import flatten as _flatten

    leaves, treedef = jax.tree_util.tree_flatten(params)
    key = (treedef, tuple((l.shape, jnp.dtype(l.dtype)) for l in leaves))
    ent = cache.get(key)
    if ent is None:
        spec = _flatten.make_spec(leaves)
        ent = cache[key] = (spec, spec.tile_tensor_ids(8))
    return leaves, treedef, ent[0], ent[1]

"""Shared helpers for the fused optimizers.

The reference optimizers (``apex/optimizers``) mutate params in place and
read ``param.grad``; here every optimizer is functional:

    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    params, state = opt.step(grads, params, state [, found_inf=...])

``found_inf`` (a traced bool from the AMP scaler) turns the step into a
no-op, reproducing the reference's skip-on-overflow wiring without the
optimizer/scaler back-channel (``_amp_stash``).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp


def tree_zeros_f32(params: Any) -> Any:
    return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def select_finite(found_inf: Optional[jax.Array], new: Any, old: Any) -> Any:
    """Keep ``old`` wherever the step must be skipped."""
    if found_inf is None:
        return new
    return jax.tree.map(
        lambda n, o: jnp.where(found_inf, o.astype(n.dtype), n), new, old)


def f32(x) -> jax.Array:
    return jnp.asarray(x, jnp.float32)


def global_grad_norm(grads: Any) -> jax.Array:
    sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
          for g in jax.tree.leaves(grads)]
    return jnp.sqrt(jnp.stack(sq).sum()) if sq else jnp.float32(0)

"""FusedSGD — ref ``apex/optimizers/fused_sgd.py :: class FusedSGD``
(kernel: ``csrc/multi_tensor_sgd_kernel.cu``).

Momentum/nesterov/dampening/weight-decay semantics follow torch.optim.SGD
as the reference does; the first momentum step seeds the buffer with the
gradient (reference's ``first_run`` flag)."""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    check_m_dtype, f32, finish_compute_params, select_finite, tree_unzip,
    tree_zeros,
)


class SGDState(NamedTuple):
    step: jax.Array
    momentum_buf: Any


class FusedSGD:
    def __init__(self, lr: float, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, *,
                 wd_after_momentum: bool = False,
                 use_flat_kernel: bool = False,
                 m_dtype=jnp.float32, emit_compute_params: bool = False):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        # ``m`` here is the momentum buffer (SGD's only moment)
        self.m_dtype = check_m_dtype(m_dtype)
        self.emit_compute_params = emit_compute_params
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.use_flat_kernel = use_flat_kernel
        self._specs = {}

    def _layout(self, params):
        from apex_tpu.optimizers._common import flat_layout

        leaves, treedef, spec, _ = flat_layout(self._specs, params)
        return leaves, treedef, spec

    def init(self, params: Any) -> SGDState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            from apex_tpu.multi_tensor_apply import flatten as _flatten

            leaves, _, spec = self._layout(params)
            return SGDState(
                step=step,
                momentum_buf=_flatten.zeros_buffer(spec, self.m_dtype))
        return SGDState(step=step,
                        momentum_buf=tree_zeros(params, self.m_dtype))

    def step(self, grads: Any, params: Any, state: SGDState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None,
             compute_params: Optional[Any] = None):
        """``grad_scale`` MULTIPLIES the gradients (combined inverse loss
        scale: pass ``1 / loss_scale``); the reference's ``scale`` arg
        DIVIDES — invert when porting. With ``emit_compute_params`` the
        return grows to ``(params, state, compute)``. See
        ``FusedAdam.step``."""
        lr = f32(self.lr if lr is None else lr)
        gs = f32(grad_scale)
        mom, damp = f32(self.momentum), f32(self.dampening)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        t = state.step + 1
        first = (state.step == 0)

        if self.use_flat_kernel:
            from apex_tpu.multi_tensor_apply import flatten as _flatten
            from apex_tpu.multi_tensor_apply.kernels import flat_sgd

            leaves, treedef, spec = self._layout(params)
            gbuf, _ = _flatten.flatten_tensors(
                jax.tree_util.tree_leaves(grads), spec)
            pbuf, _ = _flatten.flatten_tensors(leaves, spec)
            emit_dt = jnp.bfloat16 if self.emit_compute_params else None
            outs = flat_sgd(
                gbuf, pbuf, state.momentum_buf, lr=lr,
                momentum=self.momentum, dampening=self.dampening,
                weight_decay=wd, nesterov=self.nesterov,
                wd_after_momentum=self.wd_after_momentum,
                first_run=first, grad_scale=gs, emit_compute_dtype=emit_dt)
            p_new, b_new = outs[:2]
            new_params = jax.tree_util.tree_unflatten(
                treedef, _flatten.unflatten_tensors(p_new, spec))
            new_state = SGDState(step=t, momentum_buf=b_new)
            new_params = select_finite(found_inf, new_params, params)
            new_state = select_finite(found_inf, new_state, state)
            if not self.emit_compute_params:
                return new_params, new_state
            pc = jax.tree_util.tree_unflatten(
                treedef,
                _flatten.unflatten_tensors(outs[2], spec, cast_back=False))
            if compute_params is not None:
                pc = jax.tree.map(
                    lambda c, tmpl, p: c if c.dtype == tmpl.dtype
                    else p.astype(tmpl.dtype),
                    pc, compute_params, new_params)
            compute = finish_compute_params(
                new_params, params, compute_params, found_inf,
                precomputed=pc)
            return new_params, new_state, compute

        def upd(g, p, buf):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            if not self.wd_after_momentum:
                g = g + wd * p32
            if self.momentum > 0:
                seeded = jnp.where(first, g,
                                   mom * buf.astype(jnp.float32)
                                   + (1.0 - damp) * g)
                d = g + mom * seeded if self.nesterov else seeded
                buf = seeded.astype(self.m_dtype)
            else:
                d = g
            if self.wd_after_momentum:
                d = d + wd * p32
            return (p32 - lr * d).astype(p.dtype), buf

        out = jax.tree.map(upd, grads, params, state.momentum_buf)
        new_params, new_buf = tree_unzip(out, 2)
        new_state = SGDState(step=t, momentum_buf=new_buf)

        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        if not self.emit_compute_params:
            return new_params, new_state
        compute = finish_compute_params(new_params, params, compute_params,
                                        found_inf)
        return new_params, new_state, compute

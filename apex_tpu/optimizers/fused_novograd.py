"""FusedNovoGrad — ref ``apex/optimizers/fused_novograd.py``
(kernel: ``csrc/multi_tensor_novograd.cu``).

NovoGrad keeps the second moment as ONE scalar per tensor (the layer-wise
EMA of ||g||²), so ``v`` here is a pytree of fp32 scalars. First step seeds
``v`` with ||g||² unless ``init_zero``.

``use_flat_kernel=True`` runs the step on packed ``(rows, 128)`` flat
fp32 buffers (``kernels.flat_novograd``): one l2 pre-pass for the
per-tensor ||g||² (the LAMB-style two-stage reduction over
``tile_tensor_ids``), then ONE in-place Pallas pass for the
moment/param update — the one-fused-pass-per-step property of
``multi_tensor_novograd.cu``. ``v`` is then a ``(num_tensors,)``
vector."""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.multi_tensor_apply import kernels as _kernels
from apex_tpu.optimizers._common import (
    check_m_dtype, finish_compute_params, flat_layout,
    f32, select_finite, tree_unzip, tree_zeros,
)


class NovoGradState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any  # per-tensor scalars


class FusedNovoGrad:
    def __init__(self, lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.95, 0.98), eps: float = 1e-8,
                 weight_decay: float = 0.0, amsgrad: bool = False,
                 reg_inside_moment: bool = False, grad_averaging: bool = True,
                 norm_type: int = 2, init_zero: bool = False,
                 bias_correction: bool = True, *,
                 use_flat_kernel: bool = False,
                 m_dtype=jnp.float32, emit_compute_params: bool = False):
        self.m_dtype = check_m_dtype(m_dtype)
        self.emit_compute_params = emit_compute_params
        if amsgrad:
            raise RuntimeError(
                "FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type != 2:
            raise ValueError("FusedNovoGrad only supports norm_type=2")
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.reg_inside_moment = reg_inside_moment
        self.grad_averaging = grad_averaging
        self.init_zero = init_zero
        self.bias_correction = bias_correction
        self.use_flat_kernel = use_flat_kernel
        self._specs = {}

    def init(self, params: Any) -> NovoGradState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            leaves, _, spec, _ = flat_layout(self._specs, params)
            return NovoGradState(
                step=step, m=_flatten.zeros_buffer(spec, self.m_dtype),
                v=jnp.zeros((spec.num_tensors,), jnp.float32))
        return NovoGradState(
            step=step,
            m=tree_zeros(params, self.m_dtype),
            v=jax.tree.map(lambda p: jnp.zeros((), jnp.float32), params))

    def step(self, grads: Any, params: Any, state: NovoGradState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None,
             compute_params: Optional[Any] = None):
        """``grad_scale`` MULTIPLIES the gradients (combined inverse loss
        scale: pass ``1 / loss_scale``); the reference's ``scale`` arg
        DIVIDES — invert when porting. With ``emit_compute_params`` the
        return grows to ``(params, state, compute)``. See
        ``FusedAdam.step``."""
        lr = f32(self.lr if lr is None else lr)
        gs = f32(grad_scale)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        t = state.step + 1

        if self.use_flat_kernel:
            leaves, treedef, spec, tile_ids = flat_layout(self._specs,
                                                          params)
            gbuf, _ = _flatten.flatten_tensors(
                jax.tree_util.tree_leaves(grads), spec)
            pbuf, _ = _flatten.flatten_tensors(leaves, spec)
            emit_dt = jnp.bfloat16 if self.emit_compute_params else None
            outs = _kernels.flat_novograd(
                gbuf, pbuf, state.m, state.v,
                tile_ids, lr=lr, beta1=self.beta1,
                beta2=self.beta2, eps=self.eps, step=t, weight_decay=wd,
                num_tensors=spec.num_tensors,
                grad_averaging=self.grad_averaging,
                bias_correction=self.bias_correction,
                reg_inside_moment=self.reg_inside_moment,
                init_zero=self.init_zero, grad_scale=gs,
                emit_compute_dtype=emit_dt)
            p_new, m_new, v_new = outs[:3]
            new_params = jax.tree_util.tree_unflatten(
                treedef, _flatten.unflatten_tensors(p_new, spec))
            new_state = NovoGradState(step=t, m=m_new, v=v_new)
            new_params = select_finite(found_inf, new_params, params)
            new_state = select_finite(found_inf, new_state, state)
            if not self.emit_compute_params:
                return new_params, new_state
            pc = jax.tree_util.tree_unflatten(
                treedef,
                _flatten.unflatten_tensors(outs[3], spec, cast_back=False))
            if compute_params is not None:
                pc = jax.tree.map(
                    lambda c, tmpl, p: c if c.dtype == tmpl.dtype
                    else p.astype(tmpl.dtype),
                    pc, compute_params, new_params)
            compute = finish_compute_params(
                new_params, params, compute_params, found_inf,
                precomputed=pc)
            return new_params, new_state, compute

        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        tf = t.astype(jnp.float32)
        first = (state.step == 0)
        beta3 = 1.0 - b1 if self.grad_averaging else jnp.float32(1.0)
        if self.bias_correction:
            c1 = 1.0 - b1 ** tf
            c2 = 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)

        def upd(g, p, m, v):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            gsq = jnp.sum(g * g)
            if self.init_zero:
                v = b2 * v + (1.0 - b2) * gsq
            else:
                v = jnp.where(first, gsq, b2 * v + (1.0 - b2) * gsq)
            denom = jnp.sqrt(v / c2) + eps
            gn = g / denom
            if self.reg_inside_moment:
                gn = gn + wd * p32
            m = b1 * m.astype(jnp.float32) + beta3 * gn
            u = m / c1
            if not self.reg_inside_moment:
                u = u + wd * p32
            return (p32 - lr * u).astype(p.dtype), m.astype(self.m_dtype), v

        out = jax.tree.map(upd, grads, params, state.m, state.v)
        new_params, new_m, new_v = tree_unzip(out, 3)
        new_state = NovoGradState(step=t, m=new_m, v=new_v)

        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        if not self.emit_compute_params:
            return new_params, new_state
        compute = finish_compute_params(new_params, params, compute_params,
                                        found_inf)
        return new_params, new_state, compute

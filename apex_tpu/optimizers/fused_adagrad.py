"""FusedAdagrad — ref ``apex/optimizers/fused_adagrad.py``
(kernel: ``csrc/multi_tensor_adagrad.cu``)."""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    f32, select_finite, tree_unzip, tree_zeros_f32,
)


class AdagradState(NamedTuple):
    step: jax.Array
    sum: Any


class FusedAdagrad:
    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode

    def init(self, params: Any) -> AdagradState:
        return AdagradState(step=jnp.zeros((), jnp.int32),
                            sum=tree_zeros_f32(params))

    def step(self, grads: Any, params: Any, state: AdagradState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None
             ) -> Tuple[Any, AdagradState]:
        """``grad_scale`` MULTIPLIES the gradients (combined inverse loss
        scale: pass ``1 / loss_scale``); the reference's ``scale`` arg
        DIVIDES — invert when porting. See ``FusedAdam.step``."""
        lr = f32(self.lr if lr is None else lr)
        gs = f32(grad_scale)
        eps = f32(self.eps)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)

        def upd(g, p, s):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            if not self.adagrad_w_mode:
                g = g + wd * p32
            s = s + g * g
            u = g / (jnp.sqrt(s) + eps)
            if self.adagrad_w_mode:
                u = u + wd * p32
            return (p32 - lr * u).astype(p.dtype), s

        out = jax.tree.map(upd, grads, params, state.sum)
        new_params, new_sum = tree_unzip(out, 2)
        new_state = AdagradState(step=state.step + 1, sum=new_sum)

        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        return new_params, new_state

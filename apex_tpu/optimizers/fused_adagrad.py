"""FusedAdagrad — ref ``apex/optimizers/fused_adagrad.py``
(kernel: ``csrc/multi_tensor_adagrad.cu``).

``use_flat_kernel=True`` packs params/state into ``(rows, 128)`` flat
fp32 buffers and updates them with ONE in-place Pallas pass
(``kernels.flat_adagrad``) — the one-fused-pass-per-step property of the
CUDA multi-tensor kernel; see ``FusedAdam`` for when the flat path pays
off (many small tensors)."""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.multi_tensor_apply import kernels as _kernels
from apex_tpu.optimizers._common import (
    finish_compute_params, flat_layout,
    f32, select_finite, tree_unzip, tree_zeros_f32,
)


class AdagradState(NamedTuple):
    step: jax.Array
    sum: Any


class FusedAdagrad:
    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False,
                 *, use_flat_kernel: bool = False,
                 emit_compute_params: bool = False):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.use_flat_kernel = use_flat_kernel
        # Adagrad's only state is the second-moment sum — it has no first
        # moment, so there is no m_dtype knob (``sum`` must stay fp32);
        # the fused cast-out is supported like the other optimizers.
        self.emit_compute_params = emit_compute_params
        self._specs = {}

    def init(self, params: Any) -> AdagradState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            leaves, _, spec, _ = flat_layout(self._specs, params)
            buf, _ = _flatten.flatten_tensors(leaves, spec)
            return AdagradState(step=step, sum=jnp.zeros_like(buf))
        return AdagradState(step=step, sum=tree_zeros_f32(params))

    def step(self, grads: Any, params: Any, state: AdagradState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None,
             compute_params: Optional[Any] = None):
        """``grad_scale`` MULTIPLIES the gradients (combined inverse loss
        scale: pass ``1 / loss_scale``); the reference's ``scale`` arg
        DIVIDES — invert when porting. With ``emit_compute_params`` the
        return grows to ``(params, state, compute)``. See
        ``FusedAdam.step``."""
        lr = f32(self.lr if lr is None else lr)
        gs = f32(grad_scale)
        eps = f32(self.eps)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)

        if self.use_flat_kernel:
            leaves, treedef, spec, _ = flat_layout(self._specs, params)
            gbuf, _ = _flatten.flatten_tensors(
                jax.tree_util.tree_leaves(grads), spec)
            pbuf, _ = _flatten.flatten_tensors(leaves, spec)
            emit_dt = jnp.bfloat16 if self.emit_compute_params else None
            outs = _kernels.flat_adagrad(
                gbuf, pbuf, state.sum, lr=lr, eps=self.eps,
                weight_decay=wd, adagrad_w_mode=self.adagrad_w_mode,
                grad_scale=gs, emit_compute_dtype=emit_dt)
            p_new, s_new = outs[:2]
            new_params = jax.tree_util.tree_unflatten(
                treedef, _flatten.unflatten_tensors(p_new, spec))
            new_state = AdagradState(step=state.step + 1, sum=s_new)
            new_params = select_finite(found_inf, new_params, params)
            new_state = select_finite(found_inf, new_state, state)
            if not self.emit_compute_params:
                return new_params, new_state
            pc = jax.tree_util.tree_unflatten(
                treedef,
                _flatten.unflatten_tensors(outs[2], spec, cast_back=False))
            if compute_params is not None:
                pc = jax.tree.map(
                    lambda c, tmpl, p: c if c.dtype == tmpl.dtype
                    else p.astype(tmpl.dtype),
                    pc, compute_params, new_params)
            compute = finish_compute_params(
                new_params, params, compute_params, found_inf,
                precomputed=pc)
            return new_params, new_state, compute

        def upd(g, p, s):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            if not self.adagrad_w_mode:
                g = g + wd * p32
            s = s + g * g
            u = g / (jnp.sqrt(s) + eps)
            if self.adagrad_w_mode:
                u = u + wd * p32
            return (p32 - lr * u).astype(p.dtype), s

        out = jax.tree.map(upd, grads, params, state.sum)
        new_params, new_sum = tree_unzip(out, 2)
        new_state = AdagradState(step=state.step + 1, sum=new_sum)

        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        if not self.emit_compute_params:
            return new_params, new_state
        compute = finish_compute_params(new_params, params, compute_params,
                                        found_inf)
        return new_params, new_state, compute

"""FusedAdam — ref ``apex/optimizers/fused_adam.py :: class FusedAdam``
(kernel: ``csrc/multi_tensor_adam.cu``).

Two execution paths:

- default: per-leaf jnp updates inside the caller's jit — XLA fuses the
  whole step into a few elementwise kernels (the TPU analogue of the
  single multi-tensor launch);
- ``use_flat_kernel=True``: m/v live as packed ``(rows, 128)`` fp32 buffers
  updated in place by ONE Pallas pass (``kernels.flat_adam``; buffers are
  BLOCK_ROWS-aligned so aliasing is copy-free). Grads and params still
  round-trip through flatten/unflatten each step (~3 extra HBM passes), so
  this path pays off only when per-leaf launch overhead dominates (very
  many small tensors); the tree path is the default for good reason.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.multi_tensor_apply import kernels as _kernels
from apex_tpu.optimizers._common import (
    check_m_dtype, finish_compute_params, flat_layout,
    f32, select_finite, tree_unzip, tree_zeros,
)


class AdamState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


class FusedAdam:
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 amsgrad: bool = False, *, use_flat_kernel: bool = False,
                 m_dtype=jnp.float32, emit_compute_params: bool = False):
        if amsgrad:
            # matches the reference: FusedAdam raises on amsgrad
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.use_flat_kernel = use_flat_kernel
        # reduced-precision first moment (fp32 accumulate, v stays fp32)
        self.m_dtype = check_m_dtype(m_dtype)
        # fused cast-out: step additionally returns the updated params
        # pre-cast to the compute dtypes (amp-O2 skips model_params_
        # from_master); see _common.finish_compute_params
        self.emit_compute_params = emit_compute_params
        # layout cache keyed by treedef: one optimizer instance may serve
        # several param trees (init called more than once)
        self._specs = {}

    def init(self, params: Any) -> AdamState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            leaves, _, spec, _ = flat_layout(self._specs, params)
            return AdamState(step=step,
                             m=_flatten.zeros_buffer(spec, self.m_dtype),
                             v=_flatten.zeros_buffer(spec, jnp.float32))
        return AdamState(step=step, m=tree_zeros(params, self.m_dtype),
                         v=tree_zeros(params, jnp.float32))

    def state_partition_specs(self, param_specs: Any) -> AdamState:
        """PartitionSpecs for the (tree-layout) state, given the params'
        spec tree: moments shard exactly like their params, the step
        counter replicates. The APX702 sharding check verifies the
        partition-rule tables reproduce this tensor-by-tensor. Not valid
        with ``use_flat_kernel`` (the flat buffer has its own layout)."""
        if self.use_flat_kernel:
            raise ValueError(
                "state_partition_specs describes the tree layout; the flat "
                "kernel's packed buffer is sharded by its caller")
        from jax.sharding import PartitionSpec as P

        return AdamState(step=P(), m=param_specs, v=param_specs)

    def step(self, grads: Any, params: Any, state: AdamState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None,
             compute_params: Optional[Any] = None):
        """One optimizer step.

        ``grad_scale`` MULTIPLIES the gradients (it is the combined
        inverse loss scale: pass ``1 / loss_scale`` to unscale). Note the
        reference's ``FusedAdam.step(scale=...)`` takes the factor to
        DIVIDE by; callers porting from apex must invert. This convention
        is uniform across every ``apex_tpu.optimizers`` step and the flat
        Pallas kernel (``kernels.flat_adam``), chosen so the unscale
        fuses into the update as a multiply without a reciprocal op.

        With ``emit_compute_params`` the return grows to ``(params,
        state, compute)`` where ``compute`` is the updated params cast to
        the dtypes of ``compute_params`` (the previous compute tree —
        pass it; it also provides the cheap overflow-skip fallback) or
        uniformly bf16 when ``compute_params`` is None.
        """
        lr = f32(self.lr if lr is None else lr)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        t = state.step + 1

        with jax.named_scope("FusedAdam.step"):
            if self.use_flat_kernel:
                new_params, new_state, pc = self._flat_step(
                    grads, params, state, lr, wd, t, grad_scale)
            else:
                new_params, new_state = self._tree_step(
                    grads, params, state, lr, wd, t, grad_scale)
                pc = None

        # On overflow the reference skips optimizer.step() entirely, so
        # params AND optimizer state (including the step count) stay put.
        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        if not self.emit_compute_params:
            return new_params, new_state
        if pc is not None and compute_params is not None:
            # kernel emits uniform bf16; leaves whose compute dtype
            # differs (e.g. keep-fp32 norms) re-cast from the (selected)
            # master — those leaves are the small minority by bytes
            pc = jax.tree.map(
                lambda c, tmpl, p: c if c.dtype == tmpl.dtype
                else p.astype(tmpl.dtype),
                pc, compute_params, new_params)
        compute = finish_compute_params(new_params, params, compute_params,
                                        found_inf, precomputed=pc)
        return new_params, new_state, compute

    # -- paths ----------------------------------------------------------
    def _tree_step(self, grads, params, state, lr, wd, t, grad_scale):
        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        gs = f32(grad_scale)
        tf = t.astype(jnp.float32)
        if self.bias_correction:
            c1 = 1.0 - b1 ** tf
            c2 = 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)
        aw = self.adam_w_mode

        md = self.m_dtype

        def upd(g, p, m, v):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            if not aw:
                g = g + wd * p32
            m = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if aw:
                u = u + wd * p32
            return (p32 - lr * u).astype(p.dtype), m.astype(md), v

        out = jax.tree.map(upd, grads, params, state.m, state.v)
        new_params, new_m, new_v = tree_unzip(out, 3)
        return new_params, AdamState(step=t, m=new_m, v=new_v)

    def _flat_step(self, grads, params, state, lr, wd, t, grad_scale):
        leaves, treedef, spec, _ = flat_layout(self._specs, params)
        gbuf, _ = _flatten.flatten_tensors(
            jax.tree_util.tree_leaves(grads), spec)
        pbuf, _ = _flatten.flatten_tensors(leaves, spec)
        emit_dt = jnp.bfloat16 if self.emit_compute_params else None
        outs = _kernels.flat_adam(
            gbuf, pbuf, state.m, state.v,
            lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            step=t, weight_decay=wd, adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, grad_scale=grad_scale,
            emit_compute_dtype=emit_dt)
        p_new, m_new, v_new = outs[:3]
        new_params = jax.tree_util.tree_unflatten(
            treedef, _flatten.unflatten_tensors(p_new, spec))
        pc = None
        if emit_dt is not None:
            pc = jax.tree_util.tree_unflatten(
                treedef,
                _flatten.unflatten_tensors(outs[3], spec, cast_back=False))
        return new_params, AdamState(step=t, m=m_new, v=v_new), pc

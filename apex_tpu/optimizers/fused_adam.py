"""FusedAdam — ref ``apex/optimizers/fused_adam.py :: class FusedAdam``
(kernel: ``csrc/multi_tensor_adam.cu``).

Two execution paths:

- default: per-leaf jnp updates inside the caller's jit — XLA fuses the
  whole step into a few elementwise kernels (the TPU analogue of the
  single multi-tensor launch);
- ``use_flat_kernel=True``: m/v live as packed ``(rows, 128)`` fp32 buffers
  updated in place by ONE Pallas pass (``kernels.flat_adam``; buffers are
  BLOCK_ROWS-aligned so aliasing is copy-free). Grads and params still
  round-trip through flatten/unflatten each step (~3 extra HBM passes), so
  this path pays off only when per-leaf launch overhead dominates (very
  many small tensors); the tree path is the default for good reason.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.multi_tensor_apply import kernels as _kernels
from apex_tpu.optimizers._common import (
    flat_layout,
    f32, select_finite, tree_unzip, tree_zeros_f32,
)


class AdamState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


class FusedAdam:
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 amsgrad: bool = False, *, use_flat_kernel: bool = False):
        if amsgrad:
            # matches the reference: FusedAdam raises on amsgrad
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.use_flat_kernel = use_flat_kernel
        # layout cache keyed by treedef: one optimizer instance may serve
        # several param trees (init called more than once)
        self._specs = {}

    def init(self, params: Any) -> AdamState:
        step = jnp.zeros((), jnp.int32)
        if self.use_flat_kernel:
            leaves, _, spec, _ = flat_layout(self._specs, params)
            buf, _ = _flatten.flatten_tensors(leaves, spec)
            return AdamState(step=step, m=jnp.zeros_like(buf),
                             v=jnp.zeros_like(buf))
        return AdamState(step=step, m=tree_zeros_f32(params),
                         v=tree_zeros_f32(params))

    def step(self, grads: Any, params: Any, state: AdamState, *,
             lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None
             ) -> Tuple[Any, AdamState]:
        """One optimizer step.

        ``grad_scale`` MULTIPLIES the gradients (it is the combined
        inverse loss scale: pass ``1 / loss_scale`` to unscale). Note the
        reference's ``FusedAdam.step(scale=...)`` takes the factor to
        DIVIDE by; callers porting from apex must invert. This convention
        is uniform across every ``apex_tpu.optimizers`` step and the flat
        Pallas kernel (``kernels.flat_adam``), chosen so the unscale
        fuses into the update as a multiply without a reciprocal op.
        """
        lr = f32(self.lr if lr is None else lr)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        t = state.step + 1

        with jax.named_scope("FusedAdam.step"):
            if self.use_flat_kernel:
                new_params, new_state = self._flat_step(
                    grads, params, state, lr, wd, t, grad_scale)
            else:
                new_params, new_state = self._tree_step(
                    grads, params, state, lr, wd, t, grad_scale)

        # On overflow the reference skips optimizer.step() entirely, so
        # params AND optimizer state (including the step count) stay put.
        new_params = select_finite(found_inf, new_params, params)
        new_state = select_finite(found_inf, new_state, state)
        return new_params, new_state

    # -- paths ----------------------------------------------------------
    def _tree_step(self, grads, params, state, lr, wd, t, grad_scale):
        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        gs = f32(grad_scale)
        tf = t.astype(jnp.float32)
        if self.bias_correction:
            c1 = 1.0 - b1 ** tf
            c2 = 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)
        aw = self.adam_w_mode

        def upd(g, p, m, v):
            g = g.astype(jnp.float32) * gs
            p32 = p.astype(jnp.float32)
            if not aw:
                g = g + wd * p32
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if aw:
                u = u + wd * p32
            return (p32 - lr * u).astype(p.dtype), m, v

        out = jax.tree.map(upd, grads, params, state.m, state.v)
        new_params, new_m, new_v = tree_unzip(out, 3)
        return new_params, AdamState(step=t, m=new_m, v=new_v)

    def _flat_step(self, grads, params, state, lr, wd, t, grad_scale):
        leaves, treedef, spec, _ = flat_layout(self._specs, params)
        gbuf, _ = _flatten.flatten_tensors(
            jax.tree_util.tree_leaves(grads), spec)
        pbuf, _ = _flatten.flatten_tensors(leaves, spec)
        p_new, m_new, v_new = _kernels.flat_adam(
            gbuf, pbuf, state.m, state.v,
            lr=lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            step=t, weight_decay=wd, adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, grad_scale=grad_scale)
        new_params = jax.tree_util.tree_unflatten(
            treedef, _flatten.unflatten_tensors(p_new, spec))
        return new_params, AdamState(step=t, m=m_new, v=v_new)

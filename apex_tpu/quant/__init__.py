"""Quantized inference tier: int8 weight-only matmuls + int8 paged KV.

``quantize_params`` builds the weight-only int8 tree (per-output-channel
symmetric fp32 scales, sharding derived from the partition rule tables);
``w8_matmul``/``w8_matmul_nk`` are the Pallas dequant-fused matmuls the
serving cores plug in as ``dense_fns``/``logits_fn``; ``kv_quantize``/
``kv_dequantize`` are the per-page-per-head KV codecs the paged cores
use when the cache carries ``k_scale``/``v_scale``. See
``docs/source/quantization.rst`` for the scale layout, the accuracy
gates, and the budgets workflow.
"""

from apex_tpu.quant.kernels import (
    kernel_variant,
    kv_dequantize,
    kv_quantize,
    w8_matmul,
    w8_matmul_nk,
)
from apex_tpu.quant.params import (
    dequantize_tensor,
    is_quantized_tree,
    quant_partition_specs,
    quantize_params,
    quantize_tensor,
)

__all__ = [
    "dequantize_tensor",
    "is_quantized_tree",
    "kernel_variant",
    "kv_dequantize",
    "kv_quantize",
    "quant_partition_specs",
    "quantize_params",
    "quantize_tensor",
    "w8_matmul",
    "w8_matmul_nk",
]

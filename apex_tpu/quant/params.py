"""Weight-only int8 parameter trees: quantize once, shard like bf16.

``quantize_params`` rewrites a GPT parameter tree in place of layout:
every matmul kernel (the four per-layer linears plus the tied word
table) becomes an int8 leaf AT THE SAME PATH with a sibling ``scale``
leaf — per-output-channel symmetric fp32 scales, contraction axis
reduced away. Keeping the kernel paths unchanged is what makes the
partition rule tables carry over: ``layers/qkv/kernel`` still matches
``layers/qkv/kernel``, and the scale specs are DERIVED from the same
table by dropping the contracted-axis entry
(:func:`apex_tpu.partition.tables.gpt_quant_rules`), so a quantized
tree shards identically to its bf16 twin — APX701 verifies the
quantized table against registered quantized trees, APX703 the
shard_map agreement.

Scale layout (the contraction axis is what the dot reduces over, so the
per-OUTPUT-channel scale survives as one fp32 per column):

====================  ==============  ===========  ==============
leaf                  kernel shape    contraction  scale shape
====================  ==============  ===========  ==============
layers/*/kernel       (L, K, N)       axis -2      (L, N)
embedding/word        (V, h)          axis -1      (V,)
====================  ==============  ===========  ==============

Biases, layer norms and the learned position table stay untouched —
they are O(h) reads, and the O2 lesson applies: keep the cheap
high-precision master where it costs nothing.
"""

import re
from typing import Any, Dict

import jax
import jax.numpy as jnp

# path-regex -> contraction axis of the dot that consumes the leaf.
# layers/* kernels carry the leading stacked-L dim, hence -2 (the K of
# (L, K, N)); the tied word table contracts its hidden dim both as the
# logits head (hidden @ table.T) and, symmetrically, row-dequants on
# embed lookup.
_QUANT_AXES = (
    (r"(^|/)embedding/word/embedding$", -1),
    (r"(^|/)layers/(qkv|out|fc1|fc2)/kernel$", -2),
)


def quantize_tensor(w, axis: int):
    """Per-output-channel symmetric int8: amax over the contraction
    ``axis``, round-to-nearest, fp32 scales. Returns ``(q int8, scale
    fp32)`` with ``scale.shape = w.shape`` minus ``axis``. Zero
    channels keep scale 0 and quantize to exact zeros."""
    fw = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(fw), axis=axis)
    scale = (amax / 127.0).astype(jnp.float32)
    safe = jnp.expand_dims(jnp.where(scale > 0, scale, 1.0), axis)
    q = jnp.clip(jnp.round(fw / safe), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_tensor(q, scale, axis: int, dtype=jnp.float32):
    """Inverse of :func:`quantize_tensor` (up to the rounding step)."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scale.astype(jnp.float32), axis)).astype(
        dtype)


def _quant_axis(path: str):
    for pat, axis in _QUANT_AXES:
        if re.search(pat, path):
            return axis
    return None


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """GPT param tree -> weight-only int8 tree (kernel leaves int8 at
    their original paths + sibling fp32 ``scale`` leaves; everything
    else passed through untouched). Works on concrete arrays and on
    ``ShapeDtypeStruct`` trees alike (abstract trees take the
    eval_shape path, for the lint registries)."""

    def rewrite(subtree, prefix):
        if not isinstance(subtree, dict):
            return subtree
        out = {}
        for name, child in subtree.items():
            path = f"{prefix}/{name}" if prefix else name
            axis = _quant_axis(path) if not isinstance(child, dict) \
                else None
            if axis is not None:
                if isinstance(child, jax.ShapeDtypeStruct):
                    q, scale = jax.eval_shape(
                        lambda w, a=axis: quantize_tensor(w, a), child)
                else:
                    q, scale = quantize_tensor(child, axis)
                out[name] = q
                out["scale"] = scale
            else:
                out[name] = rewrite(child, path)
        return out

    return rewrite(params, "")


def is_quantized_tree(params: Dict[str, Any]) -> bool:
    """True when ``params`` carries the weight-only int8 layout (the
    engines auto-detect which dense/logits impls to build)."""
    word = params.get("embedding", {}).get("word", {})
    return "scale" in word


def quant_partition_specs(cfg) -> Dict[str, Any]:
    """PartitionSpecs for a quantized tree: the bf16 specs with each
    scale's spec derived by dropping the contracted-axis entry —
    Column (qkv/fc1) scales shard like their bias ``P(None, t)``, Row
    (out/fc2) scales replicate (their output dim is unsharded), the
    word-table scale rides the vocab shard ``P(t)``."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import gpt_partition_specs
    from apex_tpu.transformer import parallel_state as ps

    t = ps.TENSOR_AXIS
    specs = gpt_partition_specs(cfg)
    specs["embedding"]["word"]["scale"] = P(t)
    for name, spec in (("qkv", P(None, t)), ("fc1", P(None, t)),
                       ("out", P(None)), ("fc2", P(None))):
        specs["layers"][name] = dict(specs["layers"][name], scale=spec)
    return specs

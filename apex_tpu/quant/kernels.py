"""Dequant-fused int8 weight-only matmul (Pallas).

The APX6xx cost tier proves decode is pure bandwidth: at the r10 ragged
medium shape, ~0.71 GB of the 1.68 GB step is the bf16 parameter read.
Per-output-channel symmetric int8 weights halve that term; this module
is the compute side of the trade — the int8 tiles are dequantized IN
REGISTERS (``wq.astype(f32) * scale``) straight into an fp32-accumulated
MXU dot, so HBM only ever sees the int8 copy plus a tiny fp32 scale
vector. The apex O2 discipline transplanted to inference: high-precision
master (fp32 scales, >= fp32 accumulators), low-precision streaming copy.

Quantization contracts (pinned by the APX106 AST check and the APX5xx
trace tier):

- scale tensors are fp32 — never the compute dtype;
- the dequant accumulator is fp32 (``preferred_element_type``), whatever
  dtype the activations arrive in;
- int8 stores round to nearest via an explicit ``jnp.round`` — a bare
  ``astype(int8)`` truncates toward zero and doubles the mean error.

Two weight layouts, one contract:

- ``w8_matmul``: activations ``(..., K)`` against ``wq (K, N)`` with
  ``scale (N,)`` — the Column/RowParallel kernel layout;
- ``w8_matmul_nk``: ``wq (N, K)`` row-major over output channels — the
  tied-embedding logits head ``hidden @ table.T`` without ever
  materializing a transposed int8 table.

The grid runs over N tiles only (whole-M, whole-K blocks): decode M is
the slot count and K the hidden size, both comfortably VMEM-resident,
while N (ffn width, vocab) is what scales. ``kernel_variant(...)``
(same machinery as the flash-attention toggles) flips ``w8_fused`` to
the plain-jnp reference for same-process A/B pricing and parity tests.
"""

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.utils.platform import pallas_interpret

# Trace-time toggle (the flash_attention kernel_variant contract): True
# runs the Pallas dequant-fused kernel, False the jnp reference — the
# cost tier charges the same int8 invar bytes either way (reads are
# priced at the jit boundary), so the budgets.json byte claims survive
# the toggle; only the fusion (no dequantized HBM round-trip) differs.
_W8_FUSED = True

# N-tile candidates, largest first. 384 = 3 x 128 keeps the lane dim a
# multiple of the int8 min tile (32, 128) and divides the GPT-2 padded
# vocab (50304 = 131 x 384); a non-dividing N falls back to one whole
# tile (tiny configs — their widths are VMEM-trivial).
_BLOCK_N = (512, 384, 256, 128)


@contextlib.contextmanager
def kernel_variant(**toggles):
    """Temporarily override module toggles (``w8_fused``). Trace-time
    only — jit inside the context; already-compiled programs are
    unaffected. Same contract as
    :func:`apex_tpu.transformer.functional.flash_attention.kernel_variant`."""
    mapping = {k: f"_{k.upper()}" for k in toggles}
    saved = {}
    for k, attr in mapping.items():
        if attr not in globals():
            raise ValueError(f"unknown kernel_variant toggle {k!r}")
        saved[attr] = globals()[attr]
        globals()[attr] = toggles[k]
    try:
        yield
    finally:
        globals().update(saved)


def _block_n(n: int) -> int:
    for cand in _BLOCK_N:
        if n % cand == 0:
            return cand
    return n


def _w8_matmul_kernel(x_ref, wq_ref, scale_ref, bias_ref, out_ref):
    # dequant in registers: int8 tile * fp32 per-output-channel scale,
    # accumulated fp32 regardless of the activation dtype
    w = wq_ref[...].astype(jnp.float32) * scale_ref[...].astype(
        jnp.float32)
    acc = jnp.dot(x_ref[...].astype(jnp.float32), w,
                  preferred_element_type=jnp.float32)
    acc = acc + bias_ref[...].astype(jnp.float32)
    out_ref[...] = acc.astype(out_ref.dtype)


def _w8_matmul_nobias_kernel(x_ref, wq_ref, scale_ref, out_ref):
    w = wq_ref[...].astype(jnp.float32) * scale_ref[...].astype(
        jnp.float32)
    out_ref[...] = jnp.dot(
        x_ref[...].astype(jnp.float32), w,
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def _w8_matmul_nk_kernel(x_ref, wq_ref, scale_ref, out_ref):
    # wq block is (bn, K) output-channel-major: dequant rows, contract
    # both operands on their last dim — the logits head never transposes
    # the int8 table
    w = wq_ref[...].astype(jnp.float32) * scale_ref[...].astype(
        jnp.float32).T
    out_ref[...] = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def _w8_ref(x2, wq, scale, bias, out_dtype, nk):
    """jnp reference path (``w8_fused=False``): same fp32 dequant +
    fp32 accumulator, no fusion — the A/B baseline and the CPU-cheap
    variant for golden tests."""
    w = wq.astype(jnp.float32) * (scale[:, None] if nk else scale[None, :])
    if nk:
        y = jax.lax.dot_general(x2.astype(jnp.float32), w,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    else:
        y = jnp.dot(x2.astype(jnp.float32), w,
                    preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(out_dtype)


def _check_operands(x, wq, scale, k, n):
    if wq.dtype != jnp.int8:
        raise ValueError(f"wq must be int8, got {wq.dtype}")
    if scale.dtype != jnp.float32:
        raise ValueError(f"scale must be fp32, got {scale.dtype}")
    if scale.shape != (n,):
        raise ValueError(f"scale {scale.shape} != per-output-channel "
                         f"({n},)")
    if x.shape[-1] != k:
        raise ValueError(f"x last dim {x.shape[-1]} != contraction {k}")


def w8_matmul(x, wq, scale, bias=None, out_dtype=None, interpret=None):
    """``x (..., K) @ dequant(wq (K, N), scale (N,)) [+ bias (N,)]``.

    fp32 accumulation, output in ``out_dtype`` (default: ``x.dtype``).
    """
    k, n = wq.shape
    _check_operands(x, wq, scale, k, n)
    out_dtype = jnp.dtype(x.dtype if out_dtype is None else out_dtype)
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    if not _W8_FUSED:
        return _w8_ref(x2, wq, scale, bias, out_dtype, False).reshape(
            lead + (n,))
    bn = _block_n(n)
    scale2 = scale.reshape(1, n)
    if bias is None:
        out = pl.pallas_call(
            _w8_matmul_nobias_kernel,
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((m, k), lambda i: (0, 0)),
                pl.BlockSpec((k, bn), lambda i: (0, i)),
                pl.BlockSpec((1, bn), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
            interpret=pallas_interpret(interpret),
        )(x2, wq, scale2)
    else:
        bias2 = bias.reshape(1, n)
        out = pl.pallas_call(
            _w8_matmul_kernel,
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((m, k), lambda i: (0, 0)),
                pl.BlockSpec((k, bn), lambda i: (0, i)),
                pl.BlockSpec((1, bn), lambda i: (0, i)),
                pl.BlockSpec((1, bn), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
            interpret=pallas_interpret(interpret),
        )(x2, wq, scale2, bias2)
    return out.reshape(lead + (n,))


def w8_matmul_nk(x, wq, scale, out_dtype=jnp.float32, interpret=None):
    """``x (..., K) @ dequant(wq (N, K), scale (N,)).T`` — the logits
    head against the output-channel-major int8 word table. fp32 out by
    default (the logits contract)."""
    n, k = wq.shape
    _check_operands(x, wq, scale, k, n)
    out_dtype = jnp.dtype(out_dtype)
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    if not _W8_FUSED:
        return _w8_ref(x2, wq, scale, None, out_dtype, True).reshape(
            lead + (n,))
    bn = _block_n(n)
    out = pl.pallas_call(
        _w8_matmul_nk_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0)),
            pl.BlockSpec((bn, k), lambda i: (i, 0)),
            pl.BlockSpec((1, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=pallas_interpret(interpret),
    )(x2, wq, scale.reshape(1, n))
    return out.reshape(lead + (n,))


# ---------------------------------------------------------------------------
# int8 KV page quantization (plain jnp: the attention gather stays an
# XLA einsum — the byte win is the int8 pool invar, priced at the jit
# boundary by the cost tier, not a fused kernel)
# ---------------------------------------------------------------------------

def kv_quantize(t):
    """Quantize KV page tiles per page per head: ``t (..., nh, page,
    hd)`` -> ``(int8 tiles, fp32 scales (..., nh))``. Symmetric amax
    over each head's page; all-zero pages keep scale 0 and quantize to
    exact zeros (the dequant of a 0-scale page is exactly zero, so the
    NULL page stays pristine under any gather)."""
    ft = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(ft), axis=(-2, -1))
    scale = (amax / 127.0).astype(jnp.float32)
    safe = jnp.where(scale > 0, scale, 1.0)[..., None, None]
    q = jnp.clip(jnp.round(ft / safe), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale):
    """``q (..., nh, page, hd)`` int8 * ``scale (..., nh)`` fp32 ->
    fp32 tiles."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None,
                                                             None]

"""Serving: KV-cached incremental decode for the in-tree GPT.

Reference anchor: the apex-fed Megatron stacks are served with
KV-cached autoregressive generation (``megatron/text_generation``);
this package is that path for ``apex_tpu.models.gpt``, TPU-first:

- ``cache``     — two cache layouts updated in place via donated
  buffers (apxlint APX512 pins the donation in the trace tier): the
  dense per-slot ``KVCache`` and the paged ``PagedKVCache`` (fixed page
  pool + per-slot block tables, K/V HBM proportional to allocated
  pages instead of ``slots x S_max``);
- ``paging``    — host-side page allocator: free list, refcounts,
  prefix-hash cache with LRU eviction, copy-on-write bookkeeping, and
  the hierarchical KV-cache's host tier: a byte-budgeted
  content-addressed ``PrefixRegistry`` that LRU-evicted sole-owned
  prefix pages spill to (versioned checksum-bound ``SpillRecord``
  wire format) and admission-time registry hits promote from —
  shareable across engines and replicas so any replica's prefill
  seeds everyone's cache;
- ``decode``    — bucketed prefill + single-token decode + k+1-position
  speculative *verify* steps over either layout, an unsharded path and
  a TP-sharded path (heads over the ``model`` axis);
- ``draft``     — host-side n-gram / prompt-lookup drafting for
  self-speculative decode (pure function of the token history — no
  draft model, no device work), plus the ``tree_arrays`` grid packer
  for tree speculation;
- ``draft_model`` — model-based drafting: a tiny (optionally
  TP-sharded) draft GPT advanced in lockstep with the target's slots,
  re-synced by common prefix after rejections;
- ``sampling``  — greedy / temperature / top-k / top-p under explicit
  PRNG keys, including the speculative accept/resample grid whose
  committed stream is bit-identical to plain decode;
- ``scheduler`` — fixed-slot continuous batching (admit/evict on EOS or
  max-len; jit recompiles only per prompt bucket, never per request),
  over either engine; the paged engine adds prefix sharing at admission
  and preemption-by-requeue when the pool runs dry; ``spec_k > 0``
  turns ticks into draft → verify → accept steps committing 1..k+1
  tokens per slot, with optional model drafting (``draft_model=``),
  tree speculation (``tree_spec=True``) and per-stream adaptive depth
  (``adaptive_spec=True``); ``chunk_tokens=`` switches admission to
  chunked prefill — page-aligned prompt chunks run between decode
  ticks under a ``tick_token_budget``, bounding p99 inter-token
  latency under mixed load while keeping committed streams
  bit-identical;
- ``health``    — typed failure taxonomy (``PoolExhausted``,
  ``NonFiniteLogits``, ``RetryBudgetExhausted``, ...), per-engine
  ``ServingStats`` counters, and typed ``RequestOutcome`` records;
- ``faults``    — deterministic fault injection: a seedable
  ``FaultInjector`` consulted at named host-side sites, schedules a
  pure function of (seed, site, call index) so chaos runs replay
  bit-for-bit (``tests/L0/run_serving/test_faults.py``);
- ``observe``   — host-side observability hooked the same way: a
  span/event ``Tracer`` on the deterministic tick clock (replay-exact
  streams, Perfetto JSONL dumps), a ``MetricsRegistry`` of counters/
  gauges/latency histograms (``ServingStats`` is a view over it), and
  a ``FlightRecorder`` ring that typed ``ServingError``\\ s attach to
  their payloads;
- ``transfer``  — fault-tolerant cross-replica page handoff: page
  tiles gathered from a prefill replica's pool and scattered into a
  decode replica's, content-addressed by the chained prefix keys,
  checksum-verified (corrupt payloads quarantined, never attended),
  retried under a per-handoff budget with every outcome typed — in
  two tiers sharing that contract: the host-staged ``PageTransfer``
  and the device-to-device spec-to-spec ``PageReshard`` (typed
  ``ReshardFailed`` on exhaustion, degrading back to host staging);
- ``router``    — the disaggregated serving tier: a
  ``DisaggregatedRouter`` (a ``ContinuousBatchingScheduler`` over a
  two-replica composite engine) admitting prompts on a prefill
  replica, shipping their pages across, decoding on a decode replica
  — with per-replica ``ReplicaHealth`` ladders driven by probe faults,
  graceful colocated fallback, and mid-stream failover whose committed
  streams stay bit-identical to colocated serving; and its pool-scale
  generalization ``PoolRouter``: N prefill x M decode replicas behind
  one admission queue, load-based prefill routing, headroom-chosen
  decode placement with N-way failover, per-link-priced reshard
  handoffs, and the same bit-identical stream contract.
- ``tenancy``   — the multi-tenant front-end policy: ``Tenant``
  configs (weight, page quota, priority rung, TTFT/ITL SLO bounds)
  behind a ``TenancyPolicy`` the scheduler consults for stride-clock
  weighted fair share over the tick token budget, page-quota
  reservations charged against the pool's ``QuotaLedger``, and
  priority preemption-by-requeue — reordering WHEN work runs, never
  WHAT commits (streams stay integer-identical to the untenanted
  scheduler);
- ``streaming`` — per-token delivery: a ``TokenStream`` per request
  fed by a ``StreamMux`` the scheduler flushes once per tick (1..k+1
  tokens per speculative commit), with a ``stream_emit`` fault site
  and a strict-prefix contract on failure — delivery is host-side
  fan-out, never part of the committed stream.
"""

from apex_tpu.serving.cache import (  # noqa: F401
    KVCache, PagedKVCache, audit_block_tables, cache_partition_specs,
    init_cache, init_paged_cache, paged_cache_partition_specs,
)
from apex_tpu.serving.decode import (  # noqa: F401
    make_chunk_prefill_fn, make_copy_page_fn, make_decode_fn,
    make_paged_chunk_prefill_fn, make_paged_decode_fn,
    make_paged_prefill_fn, make_paged_tree_verify_fn,
    make_paged_verify_fn, make_prefill_fn, make_tp_chunk_prefill_fn,
    make_tp_decode_fn, make_tp_paged_chunk_prefill_fn,
    make_tp_paged_decode_fn, make_tp_paged_prefill_fn,
    make_tp_paged_tree_verify_fn, make_tp_paged_verify_fn,
    make_tp_prefill_fn, make_tp_tree_verify_fn, make_tp_verify_fn,
    make_tree_verify_fn, make_verify_fn,
)
from apex_tpu.serving.draft import ngram_draft, tree_arrays  # noqa: F401
from apex_tpu.serving.draft_model import DraftModel  # noqa: F401
from apex_tpu.serving.faults import (  # noqa: F401
    SITES, FaultInjector, InjectedFault, fault_draw,
)
from apex_tpu.serving.health import (  # noqa: F401
    FINISH_REASONS, HEALTH_STATES, AdmissionRejected, DeadlineExceeded,
    LivelockError, NonFiniteLogits, PoolExhausted, PoolInvariantError,
    PromoteFailed, QuotaExhausted, ReplicaHealth, ReplicaUnavailable,
    RequestOutcome, ReshardFailed, RetryBudgetExhausted, ServingError,
    ServingStats, SloViolation, SpillFailed, StreamFailed,
    TransferCorrupt, TransferFailed,
)
from apex_tpu.serving.observe import (  # noqa: F401
    FlightRecorder, MetricsRegistry, TraceEvent, Tracer,
)
from apex_tpu.serving.paging import (  # noqa: F401
    PAGE_KEY_VERSION, SPILL_DTYPE_TAGS, PagePool, PrefixRegistry,
    QuotaLedger, SpillRecord, decode_spill_header, encode_spill_header,
    prefix_page_keys, spill_checksum,
)
from apex_tpu.serving.router import (  # noqa: F401
    DisaggregatedRouter, PoolRouter,
)
from apex_tpu.serving.sampling import (  # noqa: F401
    finite_rows, sample_token_grid, sample_tokens, speculative_accept,
    tree_speculative_accept,
)
from apex_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler, DecodeEngine, PagedDecodeEngine, Request,
)
from apex_tpu.serving.streaming import (  # noqa: F401
    StreamMux, TokenStream,
)
from apex_tpu.serving.tenancy import (  # noqa: F401
    DEFAULT_TENANT, Tenant, TenancyPolicy,
)
from apex_tpu.serving.transfer import (  # noqa: F401
    PageReshard, PageTransfer, make_extract_pages_fn,
    make_extract_pages_quant_fn, make_insert_pages_fn,
    make_insert_pages_quant_fn, make_reshard_extract_fn,
    make_tile_transfer_fns, transfer_checksum,
)

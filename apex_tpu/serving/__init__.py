"""Serving: KV-cached incremental decode for the in-tree GPT.

Reference anchor: the apex-fed Megatron stacks are served with
KV-cached autoregressive generation (``megatron/text_generation``);
this package is that path for ``apex_tpu.models.gpt``, TPU-first:

- ``cache``     — preallocated per-layer K/V buffers + per-slot length
  tracking, updated in place via ``lax.dynamic_update_slice`` with
  buffer donation (apxlint APX512 pins the donation in the trace tier);
- ``decode``    — bucketed prefill + single-token decode steps, an
  unsharded path and a TP-sharded path (heads over the ``model`` axis);
- ``sampling``  — greedy / temperature / top-k under explicit PRNG keys;
- ``scheduler`` — fixed-slot continuous batching (admit/evict on EOS or
  max-len; jit recompiles only per prompt bucket, never per request).
"""

from apex_tpu.serving.cache import (  # noqa: F401
    KVCache, cache_partition_specs, init_cache,
)
from apex_tpu.serving.decode import (  # noqa: F401
    make_decode_fn, make_prefill_fn, make_tp_decode_fn, make_tp_prefill_fn,
)
from apex_tpu.serving.sampling import sample_tokens  # noqa: F401
from apex_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler, DecodeEngine, Request,
)

"""Token sampling under explicit PRNG keys.

Serving needs reproducible sampling: every stochastic draw threads an
explicit ``jax.random`` key (the scheduler derives per-slot keys as
``fold_in(PRNGKey(request.seed), step)``), so a replayed request stream
regenerates byte-identical outputs — the determinism contract the
training side already holds (see ``tests/L0/run_serving``).

One fused entry point handles the whole batch: per-slot temperature
(``<= 0`` selects greedy) so mixed greedy/sampled slots decode in one
jitted step instead of recompiling per request mix. ``top_k`` / ``top_p``
are static (part of the compiled program) — engine-level settings, not
per-request ones.

Speculative decoding shares this surface. ``sample_token_grid`` runs
the SAME sampler over the verify step's (B, k+1, V) logits, one key
per (slot, position) — position j uses ``fold_in(seed, n_generated +
j)``, i.e. exactly the key the plain decode stream would use for its
(n_generated + j)-th token. The host accept walk then commits the
longest prefix where the sampled token reproduces the draft, plus the
first non-matching sample. Because the n-gram draft is deterministic
(a point mass q = δ_d), this IS standard speculative sampling
(Leviathan et al.): the accept probability min(1, p(d)/q(d)) at the
drafted token is just p(d) — the chance the plain-key categorical
draw lands on d — and the residual distribution on first rejection
norm(max(p − q, 0)) is p restricted to tokens ≠ d, which is what the
non-matching draw realizes. Greedy rows degenerate to
longest-matching-argmax-prefix. Acceptance therefore changes only how
many STEPS a stream takes, never which tokens it emits: speculative
output is bit-identical to plain decode.
"""

import jax
import jax.numpy as jnp
from jax import lax


def _restrict(logits: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """Mask ``logits`` (…, V) to the top-k / nucleus support with
    ``-inf`` (applied to RAW logits, before temperature, so the support
    is temperature-independent — matching greedy's argmax view)."""
    if top_k:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p and top_p < 1.0:
        srt = jnp.sort(logits, axis=-1)[..., ::-1]        # descending
        probs = jax.nn.softmax(srt, axis=-1)
        # keep a sorted token while the mass BEFORE it is < top_p: the
        # smallest prefix whose mass reaches top_p (the argmax always
        # survives — its "before" mass is 0)
        keep = jnp.cumsum(probs, axis=-1) - probs < top_p
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits >= thresh, logits, -jnp.inf)
    return logits


def sample_tokens(logits: jax.Array, keys: jax.Array,
                  temperature: jax.Array, top_k: int = 0,
                  top_p: float = 0.0) -> jax.Array:
    """logits (B, V) fp32; keys (B, 2) uint32 (stacked jax.random keys);
    temperature (B,) float — ``t <= 0`` means greedy for that slot, the
    scheduler's encoding for deterministic requests. ``top_k`` (static;
    0 = full vocab) restricts sampling to each row's k largest logits;
    ``top_p`` (static; 0 or 1 = off) to the smallest set whose softmax
    mass reaches p (nucleus sampling). Returns (B,) int32 token ids."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _restrict(logits, top_k, top_p)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(
        jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def sample_token_grid(logits: jax.Array, keys: jax.Array,
                      temperature: jax.Array, top_k: int = 0,
                      top_p: float = 0.0) -> jax.Array:
    """:func:`sample_tokens` over a verify step's (B, k1, V) logits with
    per-position keys (B, k1, 2): flattens to (B*k1, V), repeats each
    slot's temperature over its k1 positions, and reshapes back to
    (B, k1) int32. Position (b, j) draws with key[b, j] — the key the
    plain stream uses for that slot's (n_generated + j)-th token — so
    the committed prefix is bit-identical to plain decode."""
    b, k1, v = logits.shape
    toks = sample_tokens(logits.reshape(b * k1, v),
                         keys.reshape(b * k1, 2),
                         jnp.repeat(temperature, k1), top_k, top_p)
    return toks.reshape(b, k1)


def speculative_accept(tokens: jax.Array, drafts: jax.Array,
                       draft_lens: jax.Array) -> jax.Array:
    """Vectorized accept rule: ``tokens`` (B, k1) are the grid-sampled
    tokens, ``drafts`` (B, k) the (0-padded) drafted candidates,
    ``draft_lens`` (B,) the true draft lengths. Draft j is accepted iff
    every draft before it matched its sampled token and ``tokens[:, j]
    == drafts[:, j]`` with ``j < draft_len`` (pad positions never
    match). Returns (B,) int32 accepted counts in [0, k]; the commit is
    ``accepted + 1`` tokens — the accepted drafts plus the first
    non-matching (or bonus k-th) sample, ``tokens[:, :accepted + 1]``.
    Pure structure — no probabilities: the sampled grid already IS the
    plain stream (see the module docstring), so acceptance is just
    "did the plain stream reproduce the draft".
    """
    k = drafts.shape[1]
    match = (tokens[:, :k] == drafts) & \
        (jnp.arange(k)[None, :] < draft_lens[:, None])
    return jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                   axis=1).astype(jnp.int32)


def tree_speculative_accept(samples: jax.Array, tokens: jax.Array,
                            parents: jax.Array, valid: jax.Array,
                            start=None):
    """:func:`speculative_accept` generalized to a draft TREE: walk the
    accepted root-to-leaf path. ``samples`` (B, k1) are the tree-verify
    grid's sampled tokens (node j drawn with the plain stream's key for
    depth ``depth[j]``); ``tokens`` (B, k1) the grid's INPUT tokens;
    ``parents`` (B, k1) int32 each node's parent grid index; ``valid``
    (B, k1) bool marks candidate draft nodes (forced/pad columns
    False); ``start`` (B,) is the walk root — the last forced column,
    whose sample is the stream's first new token.

    From ``cur = start``: commit ``samples[cur]``; descend to the valid
    child whose INPUT token equals the committed sample (drafter
    contract: children of one node carry distinct tokens, so the draw
    lands on at most one branch — the point-mass Leviathan accept per
    branch); stop when no child matches. Returns (counts (B,) int32 —
    committed tokens, in [1, k1]; path (B, k1) int32 — visited node
    indices, -1 beyond the path). The committed tokens are
    ``samples[b, path[b, i]]`` in path order: each visited node's
    sample is drawn with exactly the key and (teacher-forced)
    distribution the plain stream would use, so the committed stream
    stays bit-identical to plain decode — acceptance only changes how
    many steps it takes."""
    b, k1 = samples.shape
    if start is None:
        start = jnp.zeros((b,), jnp.int32)
    idx = jnp.arange(k1)[None, :]

    def step(carry, _):
        cur, alive = carry
        s = jnp.take_along_axis(samples, cur[:, None], 1)[:, 0]
        cand = valid & (parents == cur[:, None]) & \
            (tokens == s[:, None]) & (idx > cur[:, None])
        has = jnp.any(cand, axis=1)
        nxt = jnp.argmax(cand, axis=1).astype(jnp.int32)
        out = jnp.where(alive, cur, -1)
        alive = alive & has
        cur = jnp.where(alive, nxt, cur)
        return (cur, alive), out

    init = (start.astype(jnp.int32), jnp.ones((b,), bool))
    _, path = lax.scan(step, init, None, length=k1)
    path = path.T                                        # (B, k1)
    counts = jnp.sum(path >= 0, axis=1).astype(jnp.int32)
    return counts, path


def finite_rows(logits: jax.Array) -> jax.Array:
    """(…, V) -> (…,) bool — True where a row of ``logits`` is entirely
    finite. The scheduler's always-on NaN/Inf quarantine gate: a
    device-side reduction so each tick ships B (or B×k1) bools to the
    host instead of the logits matrix. A False row is never sampled
    into a stream — the slot is quarantined and the request retried
    (``serving.health.NonFiniteLogits``)."""
    return jnp.all(jnp.isfinite(logits), axis=-1)

"""Token sampling under explicit PRNG keys.

Serving needs reproducible sampling: every stochastic draw threads an
explicit ``jax.random`` key (the scheduler derives per-slot keys as
``fold_in(PRNGKey(request.seed), step)``), so a replayed request stream
regenerates byte-identical outputs — the determinism contract the
training side already holds (see ``tests/L0/run_serving``).

One fused entry point handles the whole batch: per-slot temperature
(``<= 0`` selects greedy) so mixed greedy/sampled slots decode in one
jitted step instead of recompiling per request mix. ``top_k`` is static
(part of the compiled program) — it is an engine-level setting, not a
per-request one.
"""

import jax
import jax.numpy as jnp


def sample_tokens(logits: jax.Array, keys: jax.Array,
                  temperature: jax.Array, top_k: int = 0) -> jax.Array:
    """logits (B, V) fp32; keys (B, 2) uint32 (stacked jax.random keys);
    temperature (B,) float — ``t <= 0`` means greedy for that slot, the
    scheduler's encoding for deterministic requests. ``top_k`` (static;
    0 = full vocab) restricts sampling to each row's k largest logits.
    Returns (B,) int32 token ids."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(
        jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def finite_rows(logits: jax.Array) -> jax.Array:
    """(B,) bool — True where a row of ``logits`` is entirely finite.
    The scheduler's always-on NaN/Inf quarantine gate: a device-side
    reduction so each tick ships B bools to the host instead of the
    (B, V) logits matrix. A False row is never sampled into a stream —
    the slot is quarantined and the request retried
    (``serving.health.NonFiniteLogits``)."""
    return jnp.all(jnp.isfinite(logits), axis=-1)

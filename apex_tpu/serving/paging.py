"""Host-side page allocator: free list, refcounts, prefix cache.

The device half of paging (``serving.cache.PagedKVCache``) is dumb
storage — a fixed pool of ``(heads, page_size, head_dim)`` pages per
layer plus per-slot block tables. Everything that decides WHICH page a
logical position lives in happens here, on the host, in plain Python:

- **free list + refcounts** — ``alloc()`` hands out exclusively-owned
  pages (refcount 1); ``retain``/``release`` move shared pages between
  owners; a page returns to the free list when its last reference
  drops. Page ids below ``RESERVED_PAGES`` (the null and scratch pages)
  are never allocated.
- **prefix cache** — completed prompt pages register under a CHAINED
  content hash (``prefix_page_keys``): page ``i``'s key commits to
  every token of pages ``0..i``, so a registry hit at key ``i`` means
  the whole prefix matches, not just one page. ``match_prefix`` walks
  the longest registered chain and retains each hit for the caller —
  two requests sharing a system prompt then hold the SAME physical
  pages (stored once, refcounted). The registry holds its own +1 ref
  per page so cached prefixes survive the submitting request.
- **copy-on-write** — appending a row into a page some other owner
  (another slot or the registry) can still read MUST NOT mutate it.
  ``needs_copy`` is exactly ``refcount > 1``; the engine copies the
  page device-side, releases the shared original, and repoints its
  block table. The cached/shared copy is never perturbed — the
  acceptance contract ``tests/L0/run_serving/test_paging.py`` pins.
- **eviction** — when the free list runs dry, ``alloc()`` drops
  least-recently-used prefix-cache entries (releasing the registry's
  refs) until a page frees or the registry is empty; only then does it
  return ``None`` and the engine preempts.

- **audit** — ``check_invariants()`` cross-checks refcounts against
  the free list, the prefix registry, and (given the engine's per-slot
  page lists) the slots' references; the chaos tier runs it after
  every scheduler tick. The ``pool_alloc`` fault site
  (``serving.faults``) hooks ``alloc()`` to simulate transient
  exhaustion deterministically.

Determinism: nothing here touches device state or RNG — identical
request streams replay identical page decisions, and the decode math
is placement-invariant anyway (see ``_paged_decode_attention``).
"""

import hashlib
import struct
from collections import Counter, OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.serving.cache import RESERVED_PAGES
from apex_tpu.serving.faults import FaultInjector
from apex_tpu.serving.health import PoolInvariantError

#: Version tag baked into every hashed page record. The chained key is
#: a CROSS-REPLICA content address (prefix cache, transfer dedup, and
#: transfer integrity all compare raw digests), so the byte layout
#: under the hash is a wire format: bump this when it changes and the
#: old generation's keys simply never match — no silent aliasing.
PAGE_KEY_VERSION = 1


def _encode_page(page: Sequence[int]) -> bytes:
    """Canonical byte record for one page of token ids: a
    ``struct.pack``'d little-endian layout — ``<II`` header (version,
    token count) followed by one ``<i`` int32 per token. Replaces the
    original ``repr(page).encode()``, whose text form depended on the
    Python int formatting of the host that hashed it — too fragile to
    serve as a content address two replicas must agree on. int32 is
    deliberate: token ids are vocabulary indices, and ``struct.pack``
    raises on anything outside int32 range rather than truncating."""
    return struct.pack(f"<II{len(page)}i", PAGE_KEY_VERSION,
                       len(page), *page)


def prefix_page_keys(tokens: Sequence[int],
                     page_size: int) -> List[bytes]:
    """One chained content key per page of ``tokens`` (the last page
    may be partial — its key commits to the partial contents, so only
    an EXACT partial match shares it). Key ``i`` is
    ``sha256(key[i-1] + encode(page_i))`` over the canonical
    :func:`_encode_page` layout, so it commits to every token of pages
    ``0..i`` and the same prompt hashes identically on every replica
    (the encoding-stability test pins exact digests)."""
    if page_size < 1:
        raise ValueError(f"page_size must be positive, got {page_size}")
    keys: List[bytes] = []
    h = b""
    for start in range(0, len(tokens), page_size):
        page = tuple(int(t) for t in tokens[start:start + page_size])
        h = hashlib.sha256(h + _encode_page(page)).digest()
        keys.append(h)
    return keys


class PagePool:
    """Free list + per-page refcounts + LRU prefix registry (see
    module doc). ``free_order`` overrides the initial free-list order —
    the placement bit-identity tests admit the same requests through
    permuted orders and require identical logits."""

    def __init__(self, num_pages: int, page_size: int,
                 free_order: Optional[Sequence[int]] = None,
                 injector: Optional[FaultInjector] = None):
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if num_pages <= RESERVED_PAGES:
            raise ValueError(
                f"num_pages {num_pages} must exceed the "
                f"{RESERVED_PAGES} reserved pages")
        self.num_pages = num_pages
        self.page_size = page_size
        usable = range(RESERVED_PAGES, num_pages)
        if free_order is None:
            free_order = list(usable)
        if sorted(free_order) != list(usable):
            raise ValueError(
                f"free_order must be a permutation of {usable}")
        self._free = deque(free_order)
        # fault hook: the ``pool_alloc`` site makes alloc() report a
        # transient exhaustion (no LRU sweep, nothing evicted)
        self.injector = injector or FaultInjector()
        self._ref: Dict[int, int] = {}  # page -> refcount; absent = free
        # chained prefix key -> page holding that page's rows; each
        # entry owns one reference on its page; insertion order = LRU
        self._prefix: "OrderedDict[bytes, int]" = OrderedDict()

    # -- refcounting ------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        return len(self._prefix)

    @property
    def num_usable(self) -> int:
        """Pages the allocator may ever hand out (total minus the
        reserved null/scratch pages)."""
        return self.num_pages - RESERVED_PAGES

    @property
    def occupancy(self) -> float:
        """Fraction of usable pages currently referenced (slots or the
        prefix registry) — the ``serving_page_pool_occupancy`` gauge
        the tracer samples every tick."""
        return (self.num_usable - self.num_free) / self.num_usable

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def needs_copy(self, page: int) -> bool:
        """True when appending a row into ``page`` would be observable
        by another owner (slot or prefix registry) — the COW trigger."""
        return self.refcount(page) > 1

    def alloc(self) -> Optional[int]:
        """An exclusively-owned page (refcount 1), evicting LRU prefix
        entries as needed; None when genuinely out of pages (or when
        the ``pool_alloc`` fault site fires — a transient refusal that
        leaves the registry untouched)."""
        if self.injector.fire("pool_alloc"):
            return None
        while not self._free and self._prefix:
            key, page = self._prefix.popitem(last=False)
            self.release(page)
        if not self._free:
            return None
        page = self._free.popleft()
        self._ref[page] = 1
        return page

    def retain(self, page: int) -> None:
        if page not in self._ref:
            raise ValueError(f"retain of free/reserved page {page}")
        self._ref[page] += 1

    def release(self, page: int) -> None:
        ref = self._ref.get(page, 0)
        if ref <= 0:
            raise ValueError(f"release of free/reserved page {page}")
        if ref == 1:
            del self._ref[page]
            self._free.append(page)
        else:
            self._ref[page] = ref - 1

    # -- prefix cache -----------------------------------------------------

    def match_prefix(self, keys: Sequence[bytes]) -> List[int]:
        """Pages of the longest registered chain prefix of ``keys``,
        each RETAINED for the caller (the admitting slot takes one
        reference per shared page; release on free/preempt)."""
        pages: List[int] = []
        for key in keys:
            page = self._prefix.get(key)
            if page is None:
                break
            self._prefix.move_to_end(key)  # LRU refresh
            self.retain(page)
            pages.append(page)
        return pages

    def register_prefix(self, keys: Sequence[bytes],
                        pages: Sequence[int]) -> None:
        """Publish a prompt's page chain for future sharing. New
        entries take the registry's own reference; keys already
        registered are only LRU-refreshed (their pages stay shared)."""
        if len(keys) != len(pages):
            raise ValueError(
                f"{len(keys)} keys vs {len(pages)} pages")
        for key, page in zip(keys, pages):
            if key in self._prefix:
                self._prefix.move_to_end(key)
                continue
            self.retain(page)
            self._prefix[key] = page

    def evict_prefix(self, key: bytes) -> bool:
        """Drop one registry entry (tests / explicit invalidation)."""
        page = self._prefix.pop(key, None)
        if page is None:
            return False
        self.release(page)
        return True

    # -- runtime audit ----------------------------------------------------

    def check_invariants(self, slot_pages: Optional[
            Sequence[Sequence[int]]] = None) -> bool:
        """Audit the allocator's books; raises
        :class:`~apex_tpu.serving.health.PoolInvariantError` on the
        first inconsistency, returns True when they balance. Checks:

        - the free list is duplicate-free, within the usable id range,
          and disjoint from the refcounted set;
        - free + refcounted partition the usable pages exactly (a page
          in neither is leaked, reserved ids appear in neither);
        - every refcount is positive and covers the registry's own
          reference on each cached page;
        - with ``slot_pages`` (the engine's per-slot page lists): every
          page's refcount equals its slot references plus its registry
          entries — the exact accounting whose violation produced the
          PR-8 COW livelock.

        The chaos tier runs this after every scheduler tick
        (``ContinuousBatchingScheduler(audit=True)``)."""
        free = list(self._free)
        usable = set(range(RESERVED_PAGES, self.num_pages))
        if len(set(free)) != len(free):
            raise PoolInvariantError(
                f"free list holds duplicates: {sorted(free)}")
        if not set(free) <= usable:
            raise PoolInvariantError(
                f"free list holds reserved/out-of-range ids: "
                f"{sorted(set(free) - usable)}")
        held = set(self._ref)
        if held & set(free):
            raise PoolInvariantError(
                f"pages both free and refcounted: "
                f"{sorted(held & set(free))}")
        if not held <= usable:
            raise PoolInvariantError(
                f"refcounted reserved/out-of-range ids: "
                f"{sorted(held - usable)}")
        leaked = usable - held - set(free)
        if leaked:
            raise PoolInvariantError(
                f"pages neither free nor referenced (leaked): "
                f"{sorted(leaked)}")
        bad = {p: r for p, r in self._ref.items() if r <= 0}
        if bad:
            raise PoolInvariantError(f"non-positive refcounts: {bad}")
        registry = Counter(self._prefix.values())
        for page, n in registry.items():
            if self._ref.get(page, 0) < n:
                raise PoolInvariantError(
                    f"page {page}: {n} registry entries but refcount "
                    f"{self._ref.get(page, 0)}")
        if slot_pages is not None:
            expected = Counter(registry)
            for slot, pages in enumerate(slot_pages):
                stray = [p for p in pages if p not in usable]
                if stray:
                    raise PoolInvariantError(
                        f"slot {slot} maps reserved/out-of-range pages "
                        f"{stray}")
                expected.update(pages)
            if dict(expected) != self._ref:
                diff = {p: (expected.get(p, 0), self._ref.get(p, 0))
                        for p in set(expected) | set(self._ref)
                        if expected.get(p, 0) != self._ref.get(p, 0)}
                raise PoolInvariantError(
                    "refcounts out of balance (page: expected slot+"
                    f"registry refs vs actual): {diff}")
        return True

    def snapshot(self) -> Dict:
        """Plain-dict view of the allocator state for diagnostics
        (:class:`~apex_tpu.serving.health.LivelockError` payloads)."""
        return {"num_free": self.num_free,
                "num_cached": self.num_cached,
                "occupancy": self.occupancy,
                "refcounts": dict(self._ref)}

"""Host-side page allocator: free list, refcounts, prefix cache.

The device half of paging (``serving.cache.PagedKVCache``) is dumb
storage — a fixed pool of ``(heads, page_size, head_dim)`` pages per
layer plus per-slot block tables. Everything that decides WHICH page a
logical position lives in happens here, on the host, in plain Python:

- **free list + refcounts** — ``alloc()`` hands out exclusively-owned
  pages (refcount 1); ``retain``/``release`` move shared pages between
  owners; a page returns to the free list when its last reference
  drops. Page ids below ``RESERVED_PAGES`` (the null and scratch pages)
  are never allocated.
- **prefix cache** — completed prompt pages register under a CHAINED
  content hash (``prefix_page_keys``): page ``i``'s key commits to
  every token of pages ``0..i``, so a registry hit at key ``i`` means
  the whole prefix matches, not just one page. ``match_prefix`` walks
  the longest registered chain and retains each hit for the caller —
  two requests sharing a system prompt then hold the SAME physical
  pages (stored once, refcounted). The registry holds its own +1 ref
  per page so cached prefixes survive the submitting request.
- **copy-on-write** — appending a row into a page some other owner
  (another slot or the registry) can still read MUST NOT mutate it.
  ``needs_copy`` is exactly ``refcount > 1``; the engine copies the
  page device-side, releases the shared original, and repoints its
  block table. The cached/shared copy is never perturbed — the
  acceptance contract ``tests/L0/run_serving/test_paging.py`` pins.
- **eviction** — when the free list runs dry, ``alloc()`` drops
  least-recently-used prefix-cache entries (releasing the registry's
  refs) until a page frees or the registry is empty; only then does it
  return ``None`` and the engine preempts.
- **host spill tier** — a :class:`PrefixRegistry` (byte-budgeted,
  LRU, shared across engines AND replicas) catches cold prefixes on
  their way out: when the eviction sweep drops an entry whose page is
  held ONLY by the registry (refcount 1 — never a page a slot still
  attends), the pool's ``spill_hook`` copies the page's rows to host
  memory as a :class:`SpillRecord` under the SAME chained content key.
  A later admission that misses HBM but hits the host tier PROMOTES
  the record back (``PagedDecodeEngine._promote_chain``): checksum +
  versioned-header verification first (:func:`spill_checksum`,
  :func:`encode_spill_header` — the transfer tier's checksum-bound
  wire discipline), then a device scatter into freshly allocated
  pages, priced on the work-charged tick clock like a disaggregated
  handoff. int8 pools spill their per-page-per-head scales with the
  payload, so the quantized format's 2x capacity holds in BOTH tiers.

- **audit** — ``check_invariants()`` cross-checks refcounts against
  the free list, the prefix registry, and (given the engine's per-slot
  page lists) the slots' references; the chaos tier runs it after
  every scheduler tick. The ``pool_alloc`` fault site
  (``serving.faults``) hooks ``alloc()`` to simulate transient
  exhaustion deterministically.

Determinism: nothing here touches device state or RNG — identical
request streams replay identical page decisions, and the decode math
is placement-invariant anyway (see ``_paged_decode_attention``).
"""

import hashlib
import struct
from collections import Counter, OrderedDict, deque
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import numpy as np

from apex_tpu.serving.cache import RESERVED_PAGES
from apex_tpu.serving.faults import FaultInjector
from apex_tpu.serving.health import PoolInvariantError, QuotaExhausted

#: Version tag baked into every hashed page record. The chained key is
#: a CROSS-REPLICA content address (prefix cache, transfer dedup, and
#: transfer integrity all compare raw digests), so the byte layout
#: under the hash is a wire format: bump this when it changes and the
#: old generation's keys simply never match — no silent aliasing.
PAGE_KEY_VERSION = 1


def _encode_page(page: Sequence[int]) -> bytes:
    """Canonical byte record for one page of token ids: a
    ``struct.pack``'d little-endian layout — ``<II`` header (version,
    token count) followed by one ``<i`` int32 per token. Replaces the
    original ``repr(page).encode()``, whose text form depended on the
    Python int formatting of the host that hashed it — too fragile to
    serve as a content address two replicas must agree on. int32 is
    deliberate: token ids are vocabulary indices, and ``struct.pack``
    raises on anything outside int32 range rather than truncating."""
    return struct.pack(f"<II{len(page)}i", PAGE_KEY_VERSION,
                       len(page), *page)


def prefix_page_keys(tokens: Sequence[int],
                     page_size: int) -> List[bytes]:
    """One chained content key per page of ``tokens`` (the last page
    may be partial — its key commits to the partial contents, so only
    an EXACT partial match shares it). Key ``i`` is
    ``sha256(key[i-1] + encode(page_i))`` over the canonical
    :func:`_encode_page` layout, so it commits to every token of pages
    ``0..i`` and the same prompt hashes identically on every replica
    (the encoding-stability test pins exact digests)."""
    if page_size < 1:
        raise ValueError(f"page_size must be positive, got {page_size}")
    keys: List[bytes] = []
    h = b""
    for start in range(0, len(tokens), page_size):
        page = tuple(int(t) for t in tokens[start:start + page_size])
        h = hashlib.sha256(h + _encode_page(page)).digest()
        keys.append(h)
    return keys


# ---------------------------------------------------------------------------
# host spill tier: wire format + registry
# ---------------------------------------------------------------------------

#: Cache-dtype tags in the spill payload header. Append-only — like
#: :data:`PAGE_KEY_VERSION` this is a wire format two tiers (and, via
#: the shared registry, two replicas) must agree on.
SPILL_DTYPE_TAGS = {"bfloat16": 1, "float32": 2, "float16": 3, "int8": 4}

#: ``struct`` layout of the fixed spill-header prefix: version, layers,
#: heads, page_size, head_dim, dtype tag — all little-endian uint32,
#: followed by the 32-byte chained page key the payload belongs to.
_SPILL_HEADER_FMT = "<IIIIII"
SPILL_HEADER_BYTES = struct.calcsize(_SPILL_HEADER_FMT) + 32


def encode_spill_header(key: bytes, num_layers: int, num_heads: int,
                        page_size: int, head_dim: int,
                        dtype_tag: int) -> bytes:
    """Canonical versioned header bound into every spilled payload —
    the same ``struct.pack`` wire-format discipline as
    :func:`_encode_page`. It embeds the CHAINED page key, so a host-
    tier record can only ever verify against the prompt chain that
    produced it (the transfer tier's "payload can never install under
    the wrong prompt" guarantee, extended to the spill tier), plus the
    pool geometry and cache dtype so a record can never scatter into a
    differently-shaped pool. The pinned-hex regression test freezes
    this layout; changes bump :data:`PAGE_KEY_VERSION`."""
    if len(key) != 32:
        raise ValueError(
            f"spill headers embed a 32-byte sha256 chain key, got "
            f"{len(key)} bytes")
    return struct.pack(_SPILL_HEADER_FMT, PAGE_KEY_VERSION, num_layers,
                       num_heads, page_size, head_dim, dtype_tag) + key


def decode_spill_header(header: bytes) -> Dict:
    """Parse :func:`encode_spill_header` output; raises ``ValueError``
    on a malformed length (content checks are the promoter's job)."""
    if len(header) != SPILL_HEADER_BYTES:
        raise ValueError(
            f"spill header must be {SPILL_HEADER_BYTES} bytes, got "
            f"{len(header)}")
    version, layers, heads, page_size, head_dim, tag = struct.unpack(
        _SPILL_HEADER_FMT, header[:-32])
    return {"version": version, "num_layers": layers,
            "num_heads": heads, "page_size": page_size,
            "head_dim": head_dim, "dtype_tag": tag, "key": header[-32:]}


def spill_checksum(header: bytes, k, v, k_scale=None,
                   v_scale=None) -> bytes:
    """sha256 over the header (which embeds the chain key — identity)
    plus the staged tile bytes (integrity), the exact shape of
    ``transfer.transfer_checksum`` with the scale planes of an int8
    page folded in. Recomputed before every promotion; a mismatch
    quarantines the record (dropped, never installed)."""
    h = hashlib.sha256()
    h.update(header)
    h.update(np.ascontiguousarray(k).tobytes())
    h.update(np.ascontiguousarray(v).tobytes())
    if k_scale is not None:
        h.update(np.ascontiguousarray(k_scale).tobytes())
        h.update(np.ascontiguousarray(v_scale).tobytes())
    return h.digest()


class SpillRecord(NamedTuple):
    """One spilled page in host memory: the versioned header, the
    page's K/V tiles as host arrays ``(layers, 1, heads, page_size,
    head_dim)``, the int8 pool's per-page-per-head scale planes
    ``(layers, 1, heads)`` (``None`` for float pools — they must
    travel together or the page dequantizes wrong), and the
    :func:`spill_checksum` digest computed at spill time."""

    header: bytes
    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray]
    v_scale: Optional[np.ndarray]
    digest: bytes

    @property
    def nbytes(self) -> int:
        n = len(self.header) + self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n


class PrefixRegistry:
    """The host-memory spill tier: a byte-budgeted LRU map from
    chained prefix page keys to :class:`SpillRecord` payloads. ONE
    instance is shared by every engine (and both replicas of a
    :class:`~apex_tpu.serving.router.DisaggregatedRouter`) — the keys
    are a global content address, so any replica's prefill seeds
    everyone's cache and a promotion never cares which pool spilled
    the bytes.

    Capacity is measured in BYTES, not pages, deliberately: an int8
    pool's records are roughly half a bf16 pool's, so KV quantization
    doubles the effective capacity of this tier exactly as it does
    HBM's. Eviction is LRU by insertion/refresh order; ``get`` hits
    refresh recency and feed the hit-rate gauge. Deterministic host
    state: no RNG, no clocks — identical request streams replay
    identical spill/promote decisions (and APX401-style discipline
    applies: never read from traced code)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be positive, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._entries: "OrderedDict[bytes, SpillRecord]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.rejected = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    @property
    def num_pages(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    @property
    def hit_rate(self) -> float:
        probes = self.hits + self.misses
        return self.hits / probes if probes else 0.0

    def put(self, key: bytes, record: SpillRecord) -> bool:
        """Admit one spilled page; False when deduped (already held —
        only LRU-refreshed) or rejected (a single record over the whole
        byte budget). Admission may LRU-evict colder records to fit."""
        if record.header[-32:] != key:
            raise ValueError(
                "spill record header embeds a different chain key than "
                "it is being registered under")
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        if record.nbytes > self.capacity_bytes:
            self.rejected += 1
            return False
        self._entries[key] = record
        self._bytes += record.nbytes
        while self._bytes > self.capacity_bytes:
            _, old = self._entries.popitem(last=False)
            self._bytes -= old.nbytes
            self.evictions += 1
        return True

    def get(self, key: bytes) -> Optional[SpillRecord]:
        """Look one key up, refreshing recency on a hit. Promotion-path
        verification (checksum, header) is the caller's job — the
        registry only answers "do I hold these bytes"."""
        rec = self._entries.get(key)
        if rec is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return rec

    def drop(self, key: bytes) -> bool:
        """Evict one record (failed verification, explicit
        invalidation); False when absent."""
        rec = self._entries.pop(key, None)
        if rec is None:
            return False
        self._bytes -= rec.nbytes
        return True

    def stats(self) -> Dict:
        """``host_*``-prefixed gauge sources, merged into
        :meth:`PagePool.stats` per-tier breakdowns."""
        return {"host_pages": self.num_pages,
                "host_bytes": self._bytes,
                "host_capacity_bytes": self.capacity_bytes,
                "host_hits": self.hits,
                "host_misses": self.misses,
                "host_hit_rate": self.hit_rate,
                "host_evictions": self.evictions}

    def check_invariants(self) -> bool:
        """Audit the tier's books: byte accounting exact, budget
        respected, every record keyed consistently with its embedded
        header key, every digest recomputing. Raises
        :class:`~apex_tpu.serving.health.PoolInvariantError`; folded
        into ``PagePool.check_invariants`` (the per-tick chaos audit)
        when the pool carries a host tier."""
        total = sum(r.nbytes for r in self._entries.values())
        if total != self._bytes:
            raise PoolInvariantError(
                f"host tier byte accounting drifted: tracked "
                f"{self._bytes}, actual {total}")
        if self._bytes > self.capacity_bytes:
            raise PoolInvariantError(
                f"host tier over budget: {self._bytes} > "
                f"{self.capacity_bytes}")
        for key, rec in self._entries.items():
            if rec.header[-32:] != key:
                raise PoolInvariantError(
                    f"host tier record {key.hex()[:12]} embeds a "
                    "different chain key in its header")
            if spill_checksum(rec.header, rec.k, rec.v, rec.k_scale,
                              rec.v_scale) != rec.digest:
                raise PoolInvariantError(
                    f"host tier record {key.hex()[:12]} fails its "
                    "spill checksum")
        return True


class QuotaLedger:
    """Per-tenant page-reservation accounting for the tenancy
    front-end (``serving.tenancy``). Reservations are CONSERVATIVE:
    a request charges its worst-case page need (prompt +
    ``max_new_tokens`` + speculative headroom) when it is first
    admitted and credits it back exactly once, when it finishes —
    preemption, requeue and retry in between never touch the books,
    which is what makes the ledger trivially leak-free (every charge
    has exactly one credit, at the single exit point every request
    passes through).

    ``quotas`` maps tenant name -> page cap (``None`` = unlimited).
    The ledger attaches to a :class:`PagePool` (``pool.ledger``) so
    the chaos tier's per-tick ``check_invariants`` audit covers the
    tenancy books alongside the refcounts. Host state (APX401).
    """

    def __init__(self, quotas: Dict[str, Optional[int]]):
        for tenant in sorted(quotas):
            q = quotas[tenant]
            if q is not None and q < 1:
                raise ValueError(
                    f"tenant {tenant!r} quota must be >= 1 pages or "
                    f"None, got {q}")
        self.quotas: Dict[str, Optional[int]] = dict(quotas)
        self._charged: Dict[str, int] = {t: 0 for t in quotas}

    def quota(self, tenant: str) -> Optional[int]:
        return self.quotas.get(tenant)

    def charged(self, tenant: str) -> int:
        return self._charged.get(tenant, 0)

    def can_charge(self, tenant: str, pages: int) -> bool:
        q = self.quotas.get(tenant)
        if q is None:
            return True
        return self._charged.get(tenant, 0) + pages <= q

    def charge(self, tenant: str, pages: int) -> None:
        if not self.can_charge(tenant, pages):
            q = self.quotas.get(tenant)
            raise QuotaExhausted(
                f"tenant {tenant!r}: charging {pages} pages would "
                f"exceed the {q}-page quota "
                f"({self._charged.get(tenant, 0)} already reserved)",
                tenant=tenant, need=pages, quota=q or 0,
                charged=self._charged.get(tenant, 0))
        self._charged[tenant] = self._charged.get(tenant, 0) + pages

    def credit(self, tenant: str, pages: int) -> None:
        held = self._charged.get(tenant, 0)
        if pages > held:
            raise PoolInvariantError(
                f"tenant {tenant!r}: crediting {pages} pages but only "
                f"{held} are reserved — double credit")
        self._charged[tenant] = held - pages

    def check(self) -> bool:
        """Audit the books: reservations non-negative and within each
        tenant's quota. Raises :class:`PoolInvariantError` on the first
        inconsistency (the per-tick chaos audit calls this through
        ``PagePool.check_invariants``)."""
        for tenant in sorted(self._charged):
            held = self._charged[tenant]
            if held < 0:
                raise PoolInvariantError(
                    f"tenant {tenant!r}: negative page reservation "
                    f"{held}")
            q = self.quotas.get(tenant)
            if q is not None and held > q:
                raise PoolInvariantError(
                    f"tenant {tenant!r}: {held} pages reserved over "
                    f"the {q}-page quota")
        return True

    def snapshot(self) -> Dict[str, Dict[str, Optional[int]]]:
        return {t: {"quota": self.quotas.get(t),
                    "charged": self._charged.get(t, 0)}
                for t in sorted(self._charged)}


class PagePool:
    """Free list + per-page refcounts + LRU prefix registry (see
    module doc). ``free_order`` overrides the initial free-list order —
    the placement bit-identity tests admit the same requests through
    permuted orders and require identical logits. ``host_tier`` hangs
    a shared :class:`PrefixRegistry` under the pool; the owning engine
    installs ``spill_hook`` so the eviction sweep can copy out
    sole-registry-owned pages before releasing them."""

    def __init__(self, num_pages: int, page_size: int,
                 free_order: Optional[Sequence[int]] = None,
                 injector: Optional[FaultInjector] = None,
                 host_tier: Optional[PrefixRegistry] = None):
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if num_pages <= RESERVED_PAGES:
            raise ValueError(
                f"num_pages {num_pages} must exceed the "
                f"{RESERVED_PAGES} reserved pages")
        self.num_pages = num_pages
        self.page_size = page_size
        usable = range(RESERVED_PAGES, num_pages)
        if free_order is None:
            free_order = list(usable)
        if sorted(free_order) != list(usable):
            raise ValueError(
                f"free_order must be a permutation of {usable}")
        self._free = deque(free_order)
        # fault hook: the ``pool_alloc`` site makes alloc() report a
        # transient exhaustion (no LRU sweep, nothing evicted)
        self.injector = injector or FaultInjector()
        self._ref: Dict[int, int] = {}  # page -> refcount; absent = free
        # chained prefix key -> page holding that page's rows; each
        # entry owns one reference on its page; insertion order = LRU
        self._prefix: "OrderedDict[bytes, int]" = OrderedDict()
        # the host spill tier (shared across pools) and the engine's
        # spill callback ``(key, page) -> None`` — consulted by the
        # eviction sweep ONLY for pages the registry solely owns
        self.host_tier = host_tier
        self.spill_hook: Optional[Callable[[bytes, int], None]] = None
        # the tenancy front-end attaches its QuotaLedger here so the
        # per-tick invariant audit covers the reservation books too
        self.ledger: Optional[QuotaLedger] = None

    # -- refcounting ------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        return len(self._prefix)

    @property
    def num_usable(self) -> int:
        """Pages the allocator may ever hand out (total minus the
        reserved null/scratch pages)."""
        return self.num_pages - RESERVED_PAGES

    @property
    def occupancy(self) -> float:
        """Fraction of usable pages currently referenced (slots or the
        prefix registry) — the ``serving_page_pool_occupancy`` gauge
        the tracer samples every tick."""
        return (self.num_usable - self.num_free) / self.num_usable

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def needs_copy(self, page: int) -> bool:
        """True when appending a row into ``page`` would be observable
        by another owner (slot or prefix registry) — the COW trigger."""
        return self.refcount(page) > 1

    def alloc(self) -> Optional[int]:
        """An exclusively-owned page (refcount 1), evicting LRU prefix
        entries as needed; None when genuinely out of pages (or when
        the ``pool_alloc`` fault site fires — a transient refusal that
        leaves the registry untouched)."""
        if self.injector.fire("pool_alloc"):
            return None
        while not self._free and self._prefix:
            key, page = self._prefix.popitem(last=False)
            # spill on the way out — but NEVER a page a slot still
            # attends (refcount > 1): only the registry's sole
            # reference guarantees the rows are the pristine
            # registered prefix (COW protects shared pages from
            # mutation, and an attended page keeps serving from HBM)
            if self.spill_hook is not None \
                    and self._ref.get(page, 0) == 1:
                self.spill_hook(key, page)
            self.release(page)
        if not self._free:
            return None
        page = self._free.popleft()
        self._ref[page] = 1
        return page

    def retain(self, page: int) -> None:
        if page not in self._ref:
            raise ValueError(f"retain of free/reserved page {page}")
        self._ref[page] += 1

    def release(self, page: int) -> None:
        ref = self._ref.get(page, 0)
        if ref <= 0:
            raise ValueError(f"release of free/reserved page {page}")
        if ref == 1:
            del self._ref[page]
            self._free.append(page)
        else:
            self._ref[page] = ref - 1

    # -- prefix cache -----------------------------------------------------

    def match_prefix(self, keys: Sequence[bytes]) -> List[int]:
        """Pages of the longest registered chain prefix of ``keys``,
        each RETAINED for the caller (the admitting slot takes one
        reference per shared page; release on free/preempt)."""
        pages: List[int] = []
        for key in keys:
            page = self._prefix.get(key)
            if page is None:
                break
            self._prefix.move_to_end(key)  # LRU refresh
            self.retain(page)
            pages.append(page)
        return pages

    def register_prefix(self, keys: Sequence[bytes],
                        pages: Sequence[int]) -> None:
        """Publish a prompt's page chain for future sharing. New
        entries take the registry's own reference; keys already
        registered are only LRU-refreshed (their pages stay shared)."""
        if len(keys) != len(pages):
            raise ValueError(
                f"{len(keys)} keys vs {len(pages)} pages")
        for key, page in zip(keys, pages):
            if key in self._prefix:
                self._prefix.move_to_end(key)
                continue
            self.retain(page)
            self._prefix[key] = page

    def evict_prefix(self, key: bytes) -> bool:
        """Drop one registry entry (tests / explicit invalidation)."""
        page = self._prefix.pop(key, None)
        if page is None:
            return False
        self.release(page)
        return True

    # -- runtime audit ----------------------------------------------------

    def check_invariants(self, slot_pages: Optional[
            Sequence[Sequence[int]]] = None) -> bool:
        """Audit the allocator's books; raises
        :class:`~apex_tpu.serving.health.PoolInvariantError` on the
        first inconsistency, returns True when they balance. Checks:

        - the free list is duplicate-free, within the usable id range,
          and disjoint from the refcounted set;
        - free + refcounted partition the usable pages exactly (a page
          in neither is leaked, reserved ids appear in neither);
        - every refcount is positive and covers the registry's own
          reference on each cached page;
        - with ``slot_pages`` (the engine's per-slot page lists): every
          page's refcount equals its slot references plus its registry
          entries — the exact accounting whose violation produced the
          PR-8 COW livelock.

        The chaos tier runs this after every scheduler tick
        (``ContinuousBatchingScheduler(audit=True)``)."""
        free = list(self._free)
        usable = set(range(RESERVED_PAGES, self.num_pages))
        if len(set(free)) != len(free):
            raise PoolInvariantError(
                f"free list holds duplicates: {sorted(free)}")
        if not set(free) <= usable:
            raise PoolInvariantError(
                f"free list holds reserved/out-of-range ids: "
                f"{sorted(set(free) - usable)}")
        held = set(self._ref)
        if held & set(free):
            raise PoolInvariantError(
                f"pages both free and refcounted: "
                f"{sorted(held & set(free))}")
        if not held <= usable:
            raise PoolInvariantError(
                f"refcounted reserved/out-of-range ids: "
                f"{sorted(held - usable)}")
        leaked = usable - held - set(free)
        if leaked:
            raise PoolInvariantError(
                f"pages neither free nor referenced (leaked): "
                f"{sorted(leaked)}")
        bad = {p: r for p, r in self._ref.items() if r <= 0}
        if bad:
            raise PoolInvariantError(f"non-positive refcounts: {bad}")
        registry = Counter(self._prefix.values())
        for page, n in registry.items():
            if self._ref.get(page, 0) < n:
                raise PoolInvariantError(
                    f"page {page}: {n} registry entries but refcount "
                    f"{self._ref.get(page, 0)}")
        if self.host_tier is not None:
            self.host_tier.check_invariants()
        if self.ledger is not None:
            self.ledger.check()
        if slot_pages is not None:
            expected = Counter(registry)
            for slot, pages in enumerate(slot_pages):
                stray = [p for p in pages if p not in usable]
                if stray:
                    raise PoolInvariantError(
                        f"slot {slot} maps reserved/out-of-range pages "
                        f"{stray}")
                expected.update(pages)
            if dict(expected) != self._ref:
                diff = {p: (expected.get(p, 0), self._ref.get(p, 0))
                        for p in sorted(set(expected) | set(self._ref))
                        if expected.get(p, 0) != self._ref.get(p, 0)}
                raise PoolInvariantError(
                    "refcounts out of balance (page: expected slot+"
                    f"registry refs vs actual): {diff}")
        return True

    def stats(self) -> Dict:
        """Per-tier breakdown for gauges and bench ``extra`` blocks:
        the HBM side (usable/free/cached/used pages, occupancy) plus,
        when a host tier is attached, its ``host_*``-prefixed stats
        (:meth:`PrefixRegistry.stats`)."""
        s = {"hbm_pages": self.num_usable,
             "hbm_free": self.num_free,
             "hbm_cached": self.num_cached,
             "hbm_used": self.num_usable - self.num_free,
             "occupancy": self.occupancy}
        if self.host_tier is not None:
            s.update(self.host_tier.stats())
        return s

    def snapshot(self) -> Dict:
        """Plain-dict view of the allocator state for diagnostics
        (:class:`~apex_tpu.serving.health.LivelockError` payloads)."""
        snap = {"num_free": self.num_free,
                "num_cached": self.num_cached,
                "occupancy": self.occupancy,
                "refcounts": dict(self._ref)}
        if self.host_tier is not None:
            snap["host_tier"] = self.host_tier.stats()
        if self.ledger is not None:
            snap["quota_ledger"] = self.ledger.snapshot()
        return snap

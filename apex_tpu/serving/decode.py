"""Prefill + single-token decode steps over the KV cache.

Two execution paths from one body (the ``models/gpt.py`` discipline):
``make_prefill_fn``/``make_decode_fn`` are plain-jnp on full params (the
golden single-chip path); ``make_tp_prefill_fn``/``make_tp_decode_fn``
run the same body inside ``parallel_state.shard_map`` with the Megatron
TP layers — heads (and the cache's head axis) shard over the ``model``
mesh axis, and logits leave through the existing ``_tied_lm_logits``
vocab-sharded head followed by a rank-order gather, so every rank
returns the full ``(b, V)`` row.

Contracts:

- **prefill** runs the full forward ONCE over a (bucket-padded) prompt
  for one slot, writes that slot's K/V rows (+ the slot length), and
  returns the logits at the LAST REAL token — the first sampling input.
  The pad tail is masked out of attention (`key_mask`) and zeroed
  before entering the cache, so pad K/V can never be attended to, now
  or after later in-place writes.
- **decode** advances every slot one token: writes the new K/V row at
  ``pos = lengths`` and attends with an ``s <= pos`` mask. Its logits
  must match a full-sequence forward at the same positions to fp32
  tolerance (the headline serving contract; see
  ``tests/L0/run_serving``).
- both jitted steps DONATE the cache: the update lowers to an in-place
  buffer write instead of a fresh ``O(L·B·H·S·d)`` copy per token.
  APX512 (trace tier) verifies the donation survives into the jaxpr.
"""

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.gpt import (
    GPTConfig, GPTModel, _block_decode, _block_prefill, _ln,
    _rope_or_none, _tied_lm_logits,
)
from apex_tpu.serving.cache import KVCache, cache_partition_specs


# ---------------------------------------------------------------------------
# shared cores (parameterized by the linear/embedding/logits impls)
# ---------------------------------------------------------------------------

def _prefill_core(params, cfg: GPTConfig, cache: KVCache, ids, mask,
                  slot, *, embed_fn, dense_fns, logits_fn):
    """ids (1, s_bucket) already bucket-padded; mask (s_bucket,) int32
    with 1 = real token (``utils.seqlen.pad_to_bucket``'s convention);
    slot: scalar int32 cache row. Returns (cache', logits (1, V))."""
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(f"prefill takes one slot's (1, s) ids, got "
                         f"{ids.shape}")
    s = ids.shape[1]
    if s > cache.k.shape[3]:
        raise ValueError(f"prompt bucket {s} exceeds cache max_len "
                         f"{cache.k.shape[3]}")
    x = embed_fn(params, ids)
    freqs = _rope_or_none(cfg, s)
    key_mask = mask[None, :]

    def body(x, lp):
        x, k, v = _block_prefill(lp, x, cfg, freqs, key_mask, *dense_fns)
        return x, (k, v)

    x, (k, v) = lax.scan(body, x, params["layers"])
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    length = jnp.sum(mask).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(hidden, length - 1, 1, 1)[:, 0]
    logits = logits_fn(params, h_last)
    # zero the pad tail before it enters the cache: decode's s <= pos
    # mask already can't reach rows past `length`, but zeroed rows make
    # the cache contents independent of pad ids outright (and keep the
    # donation bit-identity tests deterministic)
    mz = mask.astype(k.dtype)[None, None, None, :, None]
    new = KVCache(
        k=lax.dynamic_update_slice(cache.k, (k * mz).astype(cache.k.dtype),
                                   (0, slot, 0, 0, 0)),
        v=lax.dynamic_update_slice(cache.v, (v * mz).astype(cache.v.dtype),
                                   (0, slot, 0, 0, 0)),
        lengths=lax.dynamic_update_slice(cache.lengths, length[None],
                                         (slot,)))
    return new, logits


def _decode_core(params, cfg: GPTConfig, cache: KVCache, tokens, active,
                 *, embed_fn, dense_fns, logits_fn):
    """tokens (B,) int32 — each slot's previous token; active (B,) bool
    gates the length advance (freed slots stay parked). Returns
    (cache', logits (B, V) fp32)."""
    pos = cache.lengths
    x = embed_fn(params, tokens[:, None], pos=pos)
    freqs = _rope_or_none(cfg, cache.k.shape[3])

    def body(x, layer_slice):
        lp, kc, vc = layer_slice
        x, kc, vc = _block_decode(lp, x, kc, vc, pos, cfg, freqs,
                                  *dense_fns)
        return x, (kc, vc)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden[:, 0])
    return KVCache(k, v, jnp.where(active, pos + 1, pos)), logits


# ---------------------------------------------------------------------------
# unsharded (single-chip) builders
# ---------------------------------------------------------------------------

def _dense(p, x):
    return jnp.dot(x, p["kernel"].astype(x.dtype)) \
        + p["bias"].astype(x.dtype)


def _embed_unsharded(cfg: GPTConfig, compute_dtype):
    def embed(params, ids, pos=None):
        table = params["embedding"]["word"]["embedding"]
        if compute_dtype is not None:
            table = table.astype(compute_dtype)
        x = jnp.take(table, ids, axis=0)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                # decode: each slot sits at its own absolute position
                x = x + jnp.take(ptab, pos, axis=0).astype(
                    x.dtype)[:, None, :]
        return x
    return embed


def _logits_unsharded(params, hidden):
    table = params["embedding"]["word"]["embedding"]
    return jnp.dot(hidden, table.astype(hidden.dtype).T).astype(
        jnp.float32)


def make_prefill_fn(cfg: GPTConfig, compute_dtype=None):
    """jit(prefill) with the cache DONATED. One compiled executable per
    (bucket length, cache shape) — call through a bucketing layer (the
    scheduler does) so recompiles are per bucket, never per request."""
    embed = _embed_unsharded(cfg, compute_dtype)

    def prefill(params, cache, ids, mask, slot):
        return _prefill_core(params, cfg, cache, ids, mask, slot,
                             embed_fn=embed, dense_fns=(_dense,) * 4,
                             logits_fn=_logits_unsharded)

    return jax.jit(prefill, donate_argnums=1)


def make_decode_fn(cfg: GPTConfig, compute_dtype=None):
    """jit(decode) with the cache DONATED; compiles once per cache
    shape (batch of slots advances together)."""
    embed = _embed_unsharded(cfg, compute_dtype)

    def decode(params, cache, tokens, active):
        return _decode_core(params, cfg, cache, tokens, active,
                            embed_fn=embed, dense_fns=(_dense,) * 4,
                            logits_fn=_logits_unsharded)

    return jax.jit(decode, donate_argnums=1)


# ---------------------------------------------------------------------------
# TP-sharded builders — heads (and the cache head axis) over ``model``
# ---------------------------------------------------------------------------

def _tp_fns(model: GPTModel):
    from apex_tpu.transformer.tensor_parallel import mappings

    cfg = model.cfg

    def embed(params, ids, pos=None):
        x = model.embed.apply(params["embedding"]["word"], ids)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                x = x + jnp.take(ptab, pos, axis=0).astype(
                    x.dtype)[:, None, :]
        return x

    def logits(params, hidden):
        local = _tied_lm_logits(hidden,
                                params["embedding"]["word"]["embedding"])
        # rank-order gather -> the full vocab row on every rank (the
        # serving head wants a samplable (b, V), unlike training's
        # vocab-parallel CE which keeps logits sharded)
        return mappings.gather_from_tensor_model_parallel_region(local)

    dense_fns = (model.qkv.apply, model.out.apply, model.fc1.apply,
                 model.fc2.apply)
    return embed, dense_fns, logits


def make_tp_prefill_fn(model: GPTModel, mesh=None):
    """TP prefill: ``jit(shard_map(...))`` over the global mesh, cache
    donated. Params use ``model.partition_specs()``; the cache uses
    ``cache_partition_specs()`` (heads over ``model``)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    embed, dense_fns, logits_fn = _tp_fns(model)
    cspecs = cache_partition_specs()

    def prefill(params, cache, ids, mask, slot):
        return _prefill_core(params, cfg, cache, ids, mask, slot,
                             embed_fn=embed, dense_fns=dense_fns,
                             logits_fn=logits_fn)

    sharded = ps.shard_map(
        prefill, mesh=mesh,
        in_specs=(model.partition_specs(), cspecs, P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_decode_fn(model: GPTModel, mesh=None):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    embed, dense_fns, logits_fn = _tp_fns(model)
    cspecs = cache_partition_specs()

    def decode(params, cache, tokens, active):
        return _decode_core(params, cfg, cache, tokens, active,
                            embed_fn=embed, dense_fns=dense_fns,
                            logits_fn=logits_fn)

    sharded = ps.shard_map(
        decode, mesh=mesh,
        in_specs=(model.partition_specs(), cspecs, P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)

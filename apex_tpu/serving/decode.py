"""Prefill + single-token decode steps over the KV cache.

Two execution paths from one body (the ``models/gpt.py`` discipline):
``make_prefill_fn``/``make_decode_fn`` are plain-jnp on full params (the
golden single-chip path); ``make_tp_prefill_fn``/``make_tp_decode_fn``
run the same body inside ``parallel_state.shard_map`` with the Megatron
TP layers — heads (and the cache's head axis) shard over the ``model``
mesh axis, and logits leave through the existing ``_tied_lm_logits``
vocab-sharded head followed by a rank-order gather, so every rank
returns the full ``(b, V)`` row.

Contracts:

- **prefill** runs the full forward ONCE over a (bucket-padded) prompt
  for one slot, writes that slot's K/V rows (+ the slot length), and
  returns the logits at the LAST REAL token — the first sampling input.
  The pad tail is masked out of attention (`key_mask`) and zeroed
  before entering the cache, so pad K/V can never be attended to, now
  or after later in-place writes.
- **decode** advances every slot one token: writes the new K/V row at
  ``pos = lengths`` and attends with an ``s <= pos`` mask. Its logits
  must match a full-sequence forward at the same positions to fp32
  tolerance (the headline serving contract; see
  ``tests/L0/run_serving``).
- **verify** (speculative decoding) advances every slot over k+1
  candidate positions at once — the last committed token plus k
  drafted candidates — returning exact per-position logits
  ``(B, k+1, V)``. K/V rows for ALL candidates are written before
  attending (per-query ``s <= pos + j`` masks keep causality exact);
  slot lengths are NOT advanced in-step — the host commits the
  accepted prefix afterwards (``PagedDecodeEngine.commit``), so a
  rejected candidate's row is simply never admitted by any later mask
  before the next step re-writes it. That is the whole rollback
  contract, and it is pinned by bit-identity tests.
- **chunk prefill** runs the prompt forward INCREMENTALLY: one chunk of
  ``chunk_tokens`` positions per call, write-then-attend against the
  live cache at absolute positions (the verify mechanics applied to
  prefill, per Sarathi-Serve). Each call writes the chunk's K/V rows
  and advances the slot length to the chunk's end; the last call's
  logits row (at the last REAL token — the final chunk is the only
  padded one) is the first sampling input. One jitted, donated
  executable per (chunk bucket, cache shape) — every chunk pads to the
  same ``chunk_tokens`` bucket. On the paged path chunks are whole
  pages, so the write is the same page-granular scatter as monolithic
  paged prefill; the attend gathers through a ``gather_row`` passed
  separately from the ``store_row`` the core installs, because the
  scheduler keeps the stored row parked on ``SCRATCH_PAGE`` until the
  final chunk (co-tenant decode/verify steps write a row for EVERY
  slot each tick — mid-prefill those garbage writes must land on
  scratch, never on a prefix-shared page). Refused for the int8 pool:
  chunk queries would re-read earlier chunks' k/v dequantized while
  monolithic prefill attends them fresh in bf16, so first-token logits
  could drift from the synchronous path beyond the bit-identity
  contract.
- **tree verify** generalizes verify to a draft TREE per slot: node j
  (topological order, node 0 = the pending token) writes its K/V at
  physical row ``pos + j`` but attends at position ``pos + depth[j]``
  under an ancestor-matrix mask, so logits row j is the exact
  teacher-forced distribution over j's root-to-node token path — one
  forward scores every branch (SpecInfer-style). Lengths are NOT
  advanced; the host walks the accepted path
  (``sampling.tree_speculative_accept``) and advances only the
  row-CONTIGUOUS committed prefix, re-sending any committed token
  whose row landed off the leftmost chain (the forced-prefix rule) —
  the same write-then-attend rollback, no compaction pass.
- both jitted steps DONATE the cache: the update lowers to an in-place
  buffer write instead of a fresh ``O(L·B·H·S·d)`` copy per token.
  APX512 (trace tier) verifies the donation survives into the jaxpr.
"""

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.gpt import (
    GPTConfig, GPTModel, _block_chunk_prefill, _block_chunk_prefill_paged,
    _block_decode, _block_decode_paged, _block_decode_paged_q8,
    _block_prefill, _block_tree_verify, _block_tree_verify_paged,
    _block_verify, _block_verify_paged, _block_verify_paged_q8, _ln,
    _rope_or_none, _tied_lm_logits,
)
from apex_tpu.serving.cache import (
    KVCache, PagedKVCache, cache_partition_specs,
    paged_cache_partition_specs,
)


# ---------------------------------------------------------------------------
# shared cores (parameterized by the linear/embedding/logits impls)
# ---------------------------------------------------------------------------

def _prefill_core(params, cfg: GPTConfig, cache: KVCache, ids, mask,
                  slot, *, embed_fn, dense_fns, logits_fn):
    """ids (1, s_bucket) already bucket-padded; mask (s_bucket,) int32
    with 1 = real token (``utils.seqlen.pad_to_bucket``'s convention);
    slot: scalar int32 cache row. Returns (cache', logits (1, V))."""
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(f"prefill takes one slot's (1, s) ids, got "
                         f"{ids.shape}")
    s = ids.shape[1]
    if s > cache.k.shape[3]:
        raise ValueError(f"prompt bucket {s} exceeds cache max_len "
                         f"{cache.k.shape[3]}")
    x = embed_fn(params, ids)
    freqs = _rope_or_none(cfg, s)
    key_mask = mask[None, :]

    def body(x, lp):
        x, k, v = _block_prefill(lp, x, cfg, freqs, key_mask, *dense_fns)
        return x, (k, v)

    x, (k, v) = lax.scan(body, x, params["layers"])
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    length = jnp.sum(mask).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(hidden, length - 1, 1, 1)[:, 0]
    logits = logits_fn(params, h_last)
    # zero the pad tail before it enters the cache: decode's s <= pos
    # mask already can't reach rows past `length`, but zeroed rows make
    # the cache contents independent of pad ids outright (and keep the
    # donation bit-identity tests deterministic)
    mz = mask.astype(k.dtype)[None, None, None, :, None]
    new = KVCache(
        k=lax.dynamic_update_slice(cache.k, (k * mz).astype(cache.k.dtype),
                                   (0, slot, 0, 0, 0)),
        v=lax.dynamic_update_slice(cache.v, (v * mz).astype(cache.v.dtype),
                                   (0, slot, 0, 0, 0)),
        lengths=lax.dynamic_update_slice(cache.lengths, length[None],
                                         (slot,)))
    return new, logits


def _decode_core(params, cfg: GPTConfig, cache: KVCache, tokens, active,
                 *, embed_fn, dense_fns, logits_fn):
    """tokens (B,) int32 — each slot's previous token; active (B,) bool
    gates the length advance (freed slots stay parked). Returns
    (cache', logits (B, V) fp32)."""
    pos = cache.lengths
    x = embed_fn(params, tokens[:, None], pos=pos)
    freqs = _rope_or_none(cfg, cache.k.shape[3])

    def body(x, layer_slice):
        lp, kc, vc = layer_slice
        x, kc, vc = _block_decode(lp, x, kc, vc, pos, cfg, freqs,
                                  *dense_fns)
        return x, (kc, vc)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden[:, 0])
    return KVCache(k, v, jnp.where(active, pos + 1, pos)), logits


def _self_rewrite(x):
    """Rewrite row 0 of ``x`` with itself. Numerically a no-op, but it
    gives XLA an update op to land the donated buffer in — an output
    that IS an invar gives the donation nothing to alias, and APX512
    flags the dropped pair (the paged decode core's block-table idiom,
    shared by the verify steps whose lengths pass through unchanged)."""
    first = lax.dynamic_slice(x, (0,) * x.ndim, (1,) + x.shape[1:])
    return lax.dynamic_update_slice(x, first, (0,) * x.ndim)


def _verify_core(params, cfg: GPTConfig, cache: KVCache, tokens, *,
                 embed_fn, dense_fns, logits_fn):
    """Speculative *verify*: tokens (B, k1) int32 — column 0 is each
    slot's last committed (pending) token, columns 1..k its drafted
    candidates; row j attends at absolute position ``lengths + j``.
    Returns (cache', logits (B, k1, V) fp32) where logits row j is
    exactly the teacher-forced distribution for the token following
    position ``lengths + j``. Lengths are NOT advanced — acceptance is
    a host decision (the accepted count is only known after sampling),
    committed via a tiny host-side ``_replace`` on the returned cache.
    The caller guarantees ``lengths + k1 <= S_max`` for every slot
    (the scheduler's headroom guard)."""
    pos = cache.lengths
    x = embed_fn(params, tokens, pos=pos)
    freqs = _rope_or_none(cfg, cache.k.shape[3])

    def body(x, layer_slice):
        lp, kc, vc = layer_slice
        x, kc, vc = _block_verify(lp, x, kc, vc, pos, cfg, freqs,
                                  *dense_fns)
        return x, (kc, vc)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden)
    return KVCache(k, v, _self_rewrite(pos)), logits


def _tree_verify_core(params, cfg: GPTConfig, cache: KVCache, tokens,
                      depth, anc, *, embed_fn, dense_fns, logits_fn):
    """Tree verify: tokens (B, k1) int32 in topological order (column 0
    = each slot's pending token, the root every branch hangs off);
    depth (B, k1) int32 node depths (depth[0] = 0); anc (B, k1, k1)
    bool ancestor-or-self matrix (anc[i, j]: node i on j's root path,
    anc[j, j] = True; a linear chain is anc[i, j] = i <= j with
    depth[j] = j, which reduces this exactly to :func:`_verify_core`).
    Node j's position embedding/RoPE angle is ``lengths + depth[j]``
    and logits row j is the teacher-forced distribution following j's
    root-to-node path. Lengths are NOT advanced — the host walks the
    accepted path and commits the contiguous row prefix."""
    pos = cache.lengths
    x = embed_fn(params, tokens, pos=pos[:, None] + depth)
    freqs = _rope_or_none(cfg, cache.k.shape[3])

    def body(x, layer_slice):
        lp, kc, vc = layer_slice
        x, kc, vc = _block_tree_verify(lp, x, kc, vc, pos, depth, anc,
                                       cfg, freqs, *dense_fns)
        return x, (kc, vc)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden)
    return KVCache(k, v, _self_rewrite(pos)), logits


def _chunk_prefill_core(params, cfg: GPTConfig, cache: KVCache, ids,
                        mask, slot, pos, *, embed_fn, dense_fns,
                        logits_fn):
    """Chunked prefill: ids (1, chunk_tokens) — one chunk of one slot's
    prompt, already padded to the chunk bucket; mask (chunk_tokens,)
    int32 with 1 = real token (all-ones except the final chunk); slot
    and pos scalar int32 (cache row, absolute start position). Runs the
    verify-style write-then-attend forward over the chunk, advances the
    slot length to ``pos + sum(mask)`` (= the true prompt length after
    the final chunk), and returns (cache', logits (1, V)) with the
    logits taken at the chunk's last REAL token — only the final
    chunk's row is a sampling input; earlier chunks' rows are
    discarded by the caller."""
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(f"chunk prefill takes one slot's (1, sc) ids, "
                         f"got {ids.shape}")
    sc = ids.shape[1]
    if sc > cache.k.shape[3]:
        raise ValueError(f"chunk bucket {sc} exceeds cache max_len "
                         f"{cache.k.shape[3]}")
    x = embed_fn(params, ids, pos=pos[None])
    freqs = _rope_or_none(cfg, cache.k.shape[3])
    key_mask = mask[None, :]

    def body(x, layer_slice):
        lp, kc, vc = layer_slice
        x, kc, vc = _block_chunk_prefill(lp, x, kc, vc, slot, pos, cfg,
                                         freqs, key_mask, *dense_fns)
        return x, (kc, vc)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    n_real = jnp.sum(mask).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(hidden, n_real - 1, 1, 1)[:, 0]
    logits = logits_fn(params, h_last)
    lengths = lax.dynamic_update_slice(cache.lengths,
                                       (pos + n_real)[None], (slot,))
    return KVCache(k, v, lengths), logits


# ---------------------------------------------------------------------------
# paged cores — same forwards, block-table indirection into the pool
# ---------------------------------------------------------------------------

def _paged_prefill_core(params, cfg: GPTConfig, cache: PagedKVCache, ids,
                        mask, slot, write_pages, table_row, *, embed_fn,
                        dense_fns, logits_fn):
    """Bucketed prefill into the page pool. The forward is IDENTICAL to
    :func:`_prefill_core` (flash attention over the padded prompt); only
    the cache write differs: the stacked per-layer k/v tiles are cut
    into whole pages and scattered to ``write_pages`` (one physical
    page per bucket page — the host redirects prefix-shared pages and
    the pad tail to ``SCRATCH_PAGE``, so shared pages are never
    rewritten), and ``table_row`` ((max_pages,) int32, NULL-padded)
    becomes the slot's block-table row. One compiled executable per
    bucket, independent of how many pages are shared."""
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(f"prefill takes one slot's (1, s) ids, got "
                         f"{ids.shape}")
    s = ids.shape[1]
    page_size = cache.k.shape[3]
    if s % page_size:
        raise ValueError(f"prompt bucket {s} is not a multiple of "
                         f"page_size {page_size}")
    n_bucket_pages = s // page_size
    if write_pages.shape != (n_bucket_pages,):
        raise ValueError(f"write_pages {write_pages.shape} != one page "
                         f"per bucket page ({n_bucket_pages},)")
    if table_row.shape != (cache.block_tables.shape[1],):
        raise ValueError(f"table_row {table_row.shape} != block-table "
                         f"row ({cache.block_tables.shape[1]},)")
    x = embed_fn(params, ids)
    freqs = _rope_or_none(cfg, s)
    key_mask = mask[None, :]

    def body(x, lp):
        x, k, v = _block_prefill(lp, x, cfg, freqs, key_mask, *dense_fns)
        return x, (k, v)

    x, (k, v) = lax.scan(body, x, params["layers"])
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    length = jnp.sum(mask).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(hidden, length - 1, 1, 1)[:, 0]
    logits = logits_fn(params, h_last)
    mz = mask.astype(k.dtype)[None, None, None, :, None]

    def tiles(t):
        # (L, 1, nh, s, hd) -> page tiles (L, n_bucket_pages, nh,
        # page_size, hd), zero-padded tail included (scratch eats it)
        lyr, _, nh, _, hd = t.shape
        t = (t * mz)[:, 0]
        t = t.reshape(lyr, nh, n_bucket_pages, page_size, hd)
        return t.transpose(0, 2, 1, 3, 4)

    lengths = lax.dynamic_update_slice(cache.lengths, length[None],
                                       (slot,))
    block_tables = lax.dynamic_update_slice(
        cache.block_tables, table_row[None, :], (slot, 0))
    if cache.k_scale is not None:
        # int8 pool: quantize each freshly-written page per head (amax
        # over the page, zeroed pad rows quantize to exact 0) and
        # scatter tiles + scales together — 6 alias pairs
        from apex_tpu.quant.kernels import kv_quantize

        kq, ks = kv_quantize(tiles(k))
        vq, vs = kv_quantize(tiles(v))
        new = PagedKVCache(
            k=cache.k.at[:, write_pages].set(kq),
            v=cache.v.at[:, write_pages].set(vq),
            lengths=lengths, block_tables=block_tables,
            k_scale=cache.k_scale.at[:, write_pages].set(ks),
            v_scale=cache.v_scale.at[:, write_pages].set(vs))
        return new, logits
    new = PagedKVCache(
        k=cache.k.at[:, write_pages].set(tiles(k).astype(cache.k.dtype)),
        v=cache.v.at[:, write_pages].set(tiles(v).astype(cache.v.dtype)),
        lengths=lengths, block_tables=block_tables)
    return new, logits


def _paged_decode_core(params, cfg: GPTConfig, cache: PagedKVCache,
                       tokens, active, *, embed_fn, dense_fns,
                       logits_fn):
    """One token for every slot against the page pool; the host has
    already made every slot's write target exclusive (page-boundary
    allocation + copy-on-write happen in
    ``PagedDecodeEngine.prepare_decode`` BEFORE this runs). Block
    tables are host-owned state riding the donated cache tuple; they
    come back numerically unchanged, but through a self-row rewrite
    rather than an invar passthrough — an output that IS the invar
    gives XLA nothing to land the donation in, and APX512 would flag
    the dropped alias pair."""
    pos = cache.lengths
    bt = cache.block_tables
    x = embed_fn(params, tokens[:, None], pos=pos)
    freqs = _rope_or_none(cfg, bt.shape[1] * cache.k.shape[3])

    if cache.k_scale is not None:
        def body(x, layer_slice):
            lp, kp, vp, ks, vs = layer_slice
            x, kp, vp, ks, vs = _block_decode_paged_q8(
                lp, x, kp, vp, ks, vs, bt, pos, cfg, freqs, *dense_fns)
            return x, (kp, vp, ks, vs)

        x, (k, v, ks, vs) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v,
                      cache.k_scale, cache.v_scale))
        hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
        logits = logits_fn(params, hidden[:, 0])
        bt = _self_rewrite(bt)
        return PagedKVCache(k, v, jnp.where(active, pos + 1, pos), bt,
                            ks, vs), logits

    def body(x, layer_slice):
        lp, kp, vp = layer_slice
        x, kp, vp = _block_decode_paged(lp, x, kp, vp, bt, pos, cfg,
                                        freqs, *dense_fns)
        return x, (kp, vp)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden[:, 0])
    bt = _self_rewrite(bt)
    return PagedKVCache(k, v, jnp.where(active, pos + 1, pos), bt), logits


def _paged_verify_core(params, cfg: GPTConfig, cache: PagedKVCache,
                       tokens, *, embed_fn, dense_fns, logits_fn):
    """:func:`_verify_core` over the page pool. The host has already
    made every one of the k1 write targets exclusive
    (``prepare_decode(..., n_new=k1)`` runs boundary allocation +
    copy-on-write for every page the candidate positions touch), so
    the unrolled scatters never land on a shared page. Lengths and
    block tables ride the donated tuple through the self-row rewrite."""
    pos = cache.lengths
    bt = cache.block_tables
    x = embed_fn(params, tokens, pos=pos)
    freqs = _rope_or_none(cfg, bt.shape[1] * cache.k.shape[3])

    if cache.k_scale is not None:
        def body(x, layer_slice):
            lp, kp, vp, ks, vs = layer_slice
            x, kp, vp, ks, vs = _block_verify_paged_q8(
                lp, x, kp, vp, ks, vs, bt, pos, cfg, freqs, *dense_fns)
            return x, (kp, vp, ks, vs)

        x, (k, v, ks, vs) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v,
                      cache.k_scale, cache.v_scale))
        hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
        logits = logits_fn(params, hidden)
        return PagedKVCache(k, v, _self_rewrite(pos), _self_rewrite(bt),
                            ks, vs), logits

    def body(x, layer_slice):
        lp, kp, vp = layer_slice
        x, kp, vp = _block_verify_paged(lp, x, kp, vp, bt, pos, cfg,
                                        freqs, *dense_fns)
        return x, (kp, vp)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden)
    return PagedKVCache(k, v, _self_rewrite(pos), _self_rewrite(bt)), \
        logits


def _paged_tree_verify_core(params, cfg: GPTConfig, cache: PagedKVCache,
                            tokens, depth, anc, *, embed_fn, dense_fns,
                            logits_fn):
    """:func:`_tree_verify_core` over the page pool (same
    ``prepare_decode(..., n_new=k1)`` exclusivity precondition as
    :func:`_paged_verify_core`). Refused for the int8 pool: committing
    a non-leftmost branch would re-round quantized history at
    branch-dependent scales, breaking the kv8 rejected-tail
    bit-identity contract — the engine pins linear spec there."""
    if cache.k_scale is not None:
        raise ValueError("tree verify is not offered over the int8 page "
                         "pool (kv8 keeps linear speculation)")
    pos = cache.lengths
    bt = cache.block_tables
    x = embed_fn(params, tokens, pos=pos[:, None] + depth)
    freqs = _rope_or_none(cfg, bt.shape[1] * cache.k.shape[3])

    def body(x, layer_slice):
        lp, kp, vp = layer_slice
        x, kp, vp = _block_tree_verify_paged(
            lp, x, kp, vp, bt, pos, depth, anc, cfg, freqs, *dense_fns)
        return x, (kp, vp)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    logits = logits_fn(params, hidden)
    return PagedKVCache(k, v, _self_rewrite(pos), _self_rewrite(bt)), \
        logits


def _paged_chunk_prefill_core(params, cfg: GPTConfig,
                              cache: PagedKVCache, ids, mask, slot, pos,
                              write_pages, gather_row, store_row, *,
                              embed_fn, dense_fns, logits_fn):
    """:func:`_chunk_prefill_core` over the page pool. Chunks are whole
    pages, so the write is the monolithic paged prefill's page-granular
    scatter to ``write_pages`` (prefix-shared pages redirected to
    ``SCRATCH_PAGE`` by the host); the attend gathers through
    ``gather_row`` (the slot's real NULL-padded row) while
    ``store_row`` becomes the slot's block-table row — the scheduler
    passes an all-scratch parked row until the final chunk, so
    co-tenant decode/verify writes mid-prefill land on scratch (see the
    module docstring). Refused for the int8 pool: chunk queries would
    re-read earlier chunks dequantized where monolithic prefill attends
    fresh bf16 values, drifting first-token logits off the synchronous
    path."""
    if cache.k_scale is not None:
        raise ValueError("chunked prefill is not offered over the int8 "
                         "page pool (kv8 keeps monolithic prefill)")
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(f"chunk prefill takes one slot's (1, sc) ids, "
                         f"got {ids.shape}")
    sc = ids.shape[1]
    page_size = cache.k.shape[3]
    if sc % page_size:
        raise ValueError(f"chunk bucket {sc} is not a multiple of "
                         f"page_size {page_size}")
    n_chunk_pages = sc // page_size
    if write_pages.shape != (n_chunk_pages,):
        raise ValueError(f"write_pages {write_pages.shape} != one page "
                         f"per chunk page ({n_chunk_pages},)")
    max_pages = cache.block_tables.shape[1]
    for name, row in (("gather_row", gather_row),
                      ("store_row", store_row)):
        if row.shape != (max_pages,):
            raise ValueError(f"{name} {row.shape} != block-table row "
                             f"({max_pages},)")
    x = embed_fn(params, ids, pos=pos[None])
    freqs = _rope_or_none(cfg, max_pages * page_size)
    key_mask = mask[None, :]

    def body(x, layer_slice):
        lp, kp, vp = layer_slice
        x, kp, vp = _block_chunk_prefill_paged(
            lp, x, kp, vp, write_pages, gather_row, pos, cfg, freqs,
            key_mask, *dense_fns)
        return x, (kp, vp)

    x, (k, v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    hidden = _ln(params["final_ln"], x, cfg.layer_norm_eps)
    n_real = jnp.sum(mask).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(hidden, n_real - 1, 1, 1)[:, 0]
    logits = logits_fn(params, h_last)
    lengths = lax.dynamic_update_slice(cache.lengths,
                                       (pos + n_real)[None], (slot,))
    block_tables = lax.dynamic_update_slice(
        cache.block_tables, store_row[None, :], (slot, 0))
    return PagedKVCache(k, v, lengths, block_tables), logits


# ---------------------------------------------------------------------------
# unsharded (single-chip) builders
# ---------------------------------------------------------------------------

def _pos_idx(pos, s):
    """(b, s) absolute position indices from either a (b,) start (the
    decode/verify convention: consecutive from ``pos``) or an explicit
    (b, s) array (tree verify: ``pos + depth``, not consecutive)."""
    if pos.ndim == 2:
        return pos
    return pos[:, None] + jnp.arange(s)[None, :]


def _dense(p, x):
    return jnp.dot(x, p["kernel"].astype(x.dtype)) \
        + p["bias"].astype(x.dtype)


def _embed_unsharded(cfg: GPTConfig, compute_dtype):
    def embed(params, ids, pos=None):
        table = params["embedding"]["word"]["embedding"]
        if compute_dtype is not None:
            table = table.astype(compute_dtype)
        x = jnp.take(table, ids, axis=0)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                # decode/verify: slot b's s tokens sit at absolute
                # positions pos[b], pos[b]+1, ... (s = 1 for decode);
                # tree verify passes explicit (b, s) positions
                idx = _pos_idx(pos, ids.shape[1])
                x = x + jnp.take(ptab, idx, axis=0).astype(x.dtype)
        return x
    return embed


def _logits_unsharded(params, hidden):
    table = params["embedding"]["word"]["embedding"]
    return jnp.dot(hidden, table.astype(hidden.dtype).T).astype(
        jnp.float32)


def _dense_w8(p, x):
    """Weight-only int8 linear: the dequant-fused Pallas matmul against
    the layer's int8 kernel + per-output-channel fp32 scale."""
    from apex_tpu.quant.kernels import w8_matmul

    return w8_matmul(x, p["kernel"], p["scale"], p["bias"],
                     out_dtype=x.dtype)


def _embed_w8(cfg: GPTConfig, compute_dtype):
    """Embedding lookup from the int8 word table: take rows, dequant
    each against its per-row (per-vocab-entry) scale — the gather is
    O(b·s·h), so the dequant stays plain jnp."""

    def embed(params, ids, pos=None):
        word = params["embedding"]["word"]
        x = jnp.take(word["embedding"], ids, axis=0).astype(jnp.float32) \
            * jnp.take(word["scale"], ids, axis=0)[..., None]
        x = x.astype(jnp.float32 if compute_dtype is None
                     else compute_dtype)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                idx = _pos_idx(pos, ids.shape[1])
                x = x + jnp.take(ptab, idx, axis=0).astype(x.dtype)
        return x

    return embed


def _logits_w8(params, hidden):
    """Tied logits head against the output-channel-major int8 word
    table — ``w8_matmul_nk`` contracts without transposing it."""
    from apex_tpu.quant.kernels import w8_matmul_nk

    word = params["embedding"]["word"]
    return w8_matmul_nk(hidden, word["embedding"], word["scale"])


def _unsharded_fns(cfg: GPTConfig, compute_dtype, quantized):
    if quantized:
        return (_embed_w8(cfg, compute_dtype), (_dense_w8,) * 4,
                _logits_w8)
    return (_embed_unsharded(cfg, compute_dtype), (_dense,) * 4,
            _logits_unsharded)


def make_prefill_fn(cfg: GPTConfig, compute_dtype=None, quantized=False):
    """jit(prefill) with the cache DONATED. One compiled executable per
    (bucket length, cache shape) — call through a bucketing layer (the
    scheduler does) so recompiles are per bucket, never per request.
    ``quantized`` expects the weight-only int8 tree of
    ``apex_tpu.quant.quantize_params`` (every builder here does)."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def prefill(params, cache, ids, mask, slot):
        return _prefill_core(params, cfg, cache, ids, mask, slot,
                             embed_fn=embed, dense_fns=dense_fns,
                             logits_fn=logits_fn)

    return jax.jit(prefill, donate_argnums=1)


def make_decode_fn(cfg: GPTConfig, compute_dtype=None, quantized=False):
    """jit(decode) with the cache DONATED; compiles once per cache
    shape (batch of slots advances together)."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def decode(params, cache, tokens, active):
        return _decode_core(params, cfg, cache, tokens, active,
                            embed_fn=embed, dense_fns=dense_fns,
                            logits_fn=logits_fn)

    return jax.jit(decode, donate_argnums=1)


def make_paged_prefill_fn(cfg: GPTConfig, compute_dtype=None,
                          quantized=False):
    """jit(paged prefill), cache DONATED (4 alias pairs: pool k/v,
    lengths, block tables; 6 with an int8 cache's scales). Compiles per
    bucket, like the dense path."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def prefill(params, cache, ids, mask, slot, write_pages, table_row):
        return _paged_prefill_core(params, cfg, cache, ids, mask, slot,
                                   write_pages, table_row,
                                   embed_fn=embed,
                                   dense_fns=dense_fns,
                                   logits_fn=logits_fn)

    return jax.jit(prefill, donate_argnums=1)


def make_paged_decode_fn(cfg: GPTConfig, compute_dtype=None,
                         quantized=False):
    """jit(paged decode), cache DONATED; one executable per pool
    shape."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def decode(params, cache, tokens, active):
        return _paged_decode_core(params, cfg, cache, tokens, active,
                                  embed_fn=embed,
                                  dense_fns=dense_fns,
                                  logits_fn=logits_fn)

    return jax.jit(decode, donate_argnums=1)


def make_verify_fn(cfg: GPTConfig, compute_dtype=None, quantized=False):
    """jit(speculative verify) with the cache DONATED; one executable
    per (cache shape, k1) — the scheduler runs a single k1 = spec_k + 1
    bucket (shorter drafts pad with token 0; the host bounds acceptance
    by the true draft length), so this compiles once."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def verify(params, cache, tokens):
        return _verify_core(params, cfg, cache, tokens,
                            embed_fn=embed, dense_fns=dense_fns,
                            logits_fn=logits_fn)

    return jax.jit(verify, donate_argnums=1)


def make_paged_verify_fn(cfg: GPTConfig, compute_dtype=None,
                         quantized=False):
    """jit(paged speculative verify), cache DONATED (4 alias pairs; 6
    with an int8 cache's scales)."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def verify(params, cache, tokens):
        return _paged_verify_core(params, cfg, cache, tokens,
                                  embed_fn=embed,
                                  dense_fns=dense_fns,
                                  logits_fn=logits_fn)

    return jax.jit(verify, donate_argnums=1)


def make_tree_verify_fn(cfg: GPTConfig, compute_dtype=None,
                        quantized=False):
    """jit(tree verify) with the cache DONATED; one executable per
    (cache shape, k1). Takes (params, cache, tokens (B, k1), depth
    (B, k1) int32, anc (B, k1, k1) bool) — see
    :func:`_tree_verify_core` for the node contract."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def verify(params, cache, tokens, depth, anc):
        return _tree_verify_core(params, cfg, cache, tokens, depth, anc,
                                 embed_fn=embed, dense_fns=dense_fns,
                                 logits_fn=logits_fn)

    return jax.jit(verify, donate_argnums=1)


def make_paged_tree_verify_fn(cfg: GPTConfig, compute_dtype=None,
                              quantized=False):
    """jit(paged tree verify), cache DONATED (4 alias pairs). Int8
    pools are refused — see :func:`_paged_tree_verify_core`."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def verify(params, cache, tokens, depth, anc):
        return _paged_tree_verify_core(params, cfg, cache, tokens,
                                       depth, anc, embed_fn=embed,
                                       dense_fns=dense_fns,
                                       logits_fn=logits_fn)

    return jax.jit(verify, donate_argnums=1)


def make_chunk_prefill_fn(cfg: GPTConfig, compute_dtype=None,
                          quantized=False):
    """jit(chunked prefill) with the cache DONATED (3 alias pairs: k,
    v, lengths). One compiled executable per (chunk bucket, cache
    shape) — the scheduler pads every chunk to the same
    ``chunk_tokens`` bucket, so this compiles once per engine."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def chunk_prefill(params, cache, ids, mask, slot, pos):
        return _chunk_prefill_core(params, cfg, cache, ids, mask, slot,
                                   pos, embed_fn=embed,
                                   dense_fns=dense_fns,
                                   logits_fn=logits_fn)

    return jax.jit(chunk_prefill, donate_argnums=1)


def make_paged_chunk_prefill_fn(cfg: GPTConfig, compute_dtype=None,
                                quantized=False):
    """jit(paged chunked prefill), cache DONATED (4 alias pairs: pool
    k/v, lengths, block tables). Int8 pools are refused — see
    :func:`_paged_chunk_prefill_core`."""
    embed, dense_fns, logits_fn = _unsharded_fns(cfg, compute_dtype,
                                                 quantized)

    def chunk_prefill(params, cache, ids, mask, slot, pos, write_pages,
                      gather_row, store_row):
        return _paged_chunk_prefill_core(
            params, cfg, cache, ids, mask, slot, pos, write_pages,
            gather_row, store_row, embed_fn=embed, dense_fns=dense_fns,
            logits_fn=logits_fn)

    return jax.jit(chunk_prefill, donate_argnums=1)


def make_copy_page_fn():
    """jit(copy one physical page across all layers), cache DONATED —
    the device half of copy-on-write: the host picks ``src``/``dst``
    (``PagePool.needs_copy``), this clones the rows so the shared
    original is never mutated. Scalar page ids keep it one executable
    regardless of which pages diverge. An int8 cache clones the page's
    scale rows together with its tiles — the COW copy of a quantized
    page is bit-identical (same int8 rows, same scales)."""

    def copy(cache, src, dst):
        def clone(pool):
            page = lax.dynamic_slice_in_dim(pool, src, 1, axis=1)
            return lax.dynamic_update_slice_in_dim(pool, page, dst,
                                                   axis=1)

        new = cache._replace(k=clone(cache.k), v=clone(cache.v))
        if cache.k_scale is not None:
            new = new._replace(k_scale=clone(cache.k_scale),
                               v_scale=clone(cache.v_scale))
        return new

    return jax.jit(copy, donate_argnums=0)


# ---------------------------------------------------------------------------
# TP-sharded builders — heads (and the cache head axis) over ``model``
# ---------------------------------------------------------------------------

def _tp_fns(model: GPTModel):
    from apex_tpu.transformer.tensor_parallel import mappings

    cfg = model.cfg

    def embed(params, ids, pos=None):
        x = model.embed.apply(params["embedding"]["word"], ids)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                idx = _pos_idx(pos, ids.shape[1])
                x = x + jnp.take(ptab, idx, axis=0).astype(x.dtype)
        return x

    def logits(params, hidden):
        local = _tied_lm_logits(hidden,
                                params["embedding"]["word"]["embedding"])
        # rank-order gather -> the full vocab row on every rank (the
        # serving head wants a samplable (b, V), unlike training's
        # vocab-parallel CE which keeps logits sharded)
        return mappings.gather_from_tensor_model_parallel_region(local)

    dense_fns = (model.qkv.apply, model.out.apply, model.fc1.apply,
                 model.fc2.apply)
    return embed, dense_fns, logits


def _tp_quant_fns(model: GPTModel):
    """Quantized twins of :func:`_tp_fns`: the same Megatron collective
    structure (Column: copy-in, no gather; Row: local matmul, reduce,
    then the replicated bias; vocab-parallel embed/logits) with the
    local matmuls swapped for the dequant-fused int8 kernels. The
    quantized tree shards exactly like bf16 (kernel paths unchanged,
    scales split with their output channel —
    ``apex_tpu.quant.quant_partition_specs``), so each rank's
    ``w8_matmul`` sees a coherent (local kernel, local scale) pair."""
    from jax import lax

    from apex_tpu.quant.kernels import w8_matmul, w8_matmul_nk
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.transformer.tensor_parallel import mappings

    cfg = model.cfg

    def embed(params, ids, pos=None):
        # VocabParallelEmbedding.apply over the int8 row shard: local
        # rows dequant per vocab entry, out-of-range rows zero, psum
        word = params["embedding"]["word"]
        table = word["embedding"]          # (V/p, h) int8 local shard
        per_rank = table.shape[0]
        start = lax.axis_index(ps.TENSOR_AXIS) * per_rank
        local = ids - start
        in_range = (local >= 0) & (local < per_rank)
        safe = jnp.where(in_range, local, 0)
        out = jnp.take(table, safe, axis=0).astype(jnp.float32) \
            * jnp.take(word["scale"], safe, axis=0)[..., None]
        out = jnp.where(in_range[..., None], out, 0.0)
        x = mappings.reduce_from_tensor_model_parallel_region(out)
        if not cfg.use_rope:
            ptab = params["embedding"]["position"]["embedding"]
            if pos is None:
                x = x + ptab[: ids.shape[1]].astype(x.dtype)[None]
            else:
                idx = _pos_idx(pos, ids.shape[1])
                x = x + jnp.take(ptab, idx, axis=0).astype(x.dtype)
        return x

    def column(p, x):
        x = mappings.copy_to_tensor_model_parallel_region(x)
        return w8_matmul(x, p["kernel"], p["scale"], p["bias"],
                         out_dtype=x.dtype)

    def row(p, x):
        # bias AFTER the reduction, replicated — RowParallelLinear's
        # contract (adding it per-rank would add it p times)
        y = w8_matmul(x, p["kernel"], p["scale"], out_dtype=x.dtype)
        y = mappings.reduce_from_tensor_model_parallel_region(y)
        return y + p["bias"].astype(y.dtype)

    def logits(params, hidden):
        word = params["embedding"]["word"]
        hidden = mappings.copy_to_tensor_model_parallel_region(hidden)
        local = w8_matmul_nk(hidden, word["embedding"], word["scale"])
        return mappings.gather_from_tensor_model_parallel_region(local)

    return embed, (column, row, column, row), logits


def _tp_build(model: GPTModel, quantized: bool):
    """(embed/dense/logits fns, param specs) for the TP builders."""
    if quantized:
        from apex_tpu.quant.params import quant_partition_specs

        return _tp_quant_fns(model), quant_partition_specs(model.cfg)
    return _tp_fns(model), model.partition_specs()


def make_tp_prefill_fn(model: GPTModel, mesh=None, quantized=False):
    """TP prefill: ``jit(shard_map(...))`` over the global mesh, cache
    donated. Params use ``model.partition_specs()`` (or the quantized
    tree's ``quant_partition_specs``); the cache uses
    ``cache_partition_specs()`` (heads over ``model``)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = cache_partition_specs()

    def prefill(params, cache, ids, mask, slot):
        return _prefill_core(params, cfg, cache, ids, mask, slot,
                             embed_fn=embed, dense_fns=dense_fns,
                             logits_fn=logits_fn)

    sharded = ps.shard_map(
        prefill, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_decode_fn(model: GPTModel, mesh=None, quantized=False):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = cache_partition_specs()

    def decode(params, cache, tokens, active):
        return _decode_core(params, cfg, cache, tokens, active,
                            embed_fn=embed, dense_fns=dense_fns,
                            logits_fn=logits_fn)

    sharded = ps.shard_map(
        decode, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_verify_fn(model: GPTModel, mesh=None, quantized=False):
    """TP speculative verify: the (b, k1, V) logits leave through the
    same vocab-sharded head + rank-order gather as decode's."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = cache_partition_specs()

    def verify(params, cache, tokens):
        return _verify_core(params, cfg, cache, tokens,
                            embed_fn=embed, dense_fns=dense_fns,
                            logits_fn=logits_fn)

    sharded = ps.shard_map(
        verify, mesh=mesh,
        in_specs=(pspecs, cspecs, P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_paged_prefill_fn(model: GPTModel, mesh=None, quantized=False,
                             kv_quantized=False):
    """TP paged prefill: the pool's head axis shards over ``model``;
    block tables / page ids are replicated host decisions, so every
    rank scatters its local heads' tiles to the same physical pages.
    ``kv_quantized`` switches the cache specs to the int8 pool's (the
    scales shard their head axis over ``model`` too)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = paged_cache_partition_specs(quantized=kv_quantized)

    def prefill(params, cache, ids, mask, slot, write_pages, table_row):
        return _paged_prefill_core(params, cfg, cache, ids, mask, slot,
                                   write_pages, table_row,
                                   embed_fn=embed, dense_fns=dense_fns,
                                   logits_fn=logits_fn)

    sharded = ps.shard_map(
        prefill, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P(), P(),
                  P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_paged_decode_fn(model: GPTModel, mesh=None, quantized=False,
                            kv_quantized=False):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = paged_cache_partition_specs(quantized=kv_quantized)

    def decode(params, cache, tokens, active):
        return _paged_decode_core(params, cfg, cache, tokens, active,
                                  embed_fn=embed, dense_fns=dense_fns,
                                  logits_fn=logits_fn)

    sharded = ps.shard_map(
        decode, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_paged_verify_fn(model: GPTModel, mesh=None, quantized=False,
                            kv_quantized=False):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = paged_cache_partition_specs(quantized=kv_quantized)

    def verify(params, cache, tokens):
        return _paged_verify_core(params, cfg, cache, tokens,
                                  embed_fn=embed, dense_fns=dense_fns,
                                  logits_fn=logits_fn)

    sharded = ps.shard_map(
        verify, mesh=mesh,
        in_specs=(pspecs, cspecs, P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_tree_verify_fn(model: GPTModel, mesh=None, quantized=False):
    """TP tree verify: the depth/anc tree descriptors are replicated
    host decisions (like block tables); heads shard over ``model`` and
    the (b, k1, V) logits leave through the vocab-sharded head +
    rank-order gather, exactly as :func:`make_tp_verify_fn`."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = cache_partition_specs()

    def verify(params, cache, tokens, depth, anc):
        return _tree_verify_core(params, cfg, cache, tokens, depth, anc,
                                 embed_fn=embed, dense_fns=dense_fns,
                                 logits_fn=logits_fn)

    sharded = ps.shard_map(
        verify, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_chunk_prefill_fn(model: GPTModel, mesh=None, quantized=False):
    """TP chunked prefill: heads (and the cache head axis) shard over
    ``model``; slot/pos/mask are replicated host decisions, and the
    final chunk's (1, V) logits leave through the vocab-sharded head +
    rank-order gather, exactly as :func:`make_tp_prefill_fn`."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = cache_partition_specs()

    def chunk_prefill(params, cache, ids, mask, slot, pos):
        return _chunk_prefill_core(params, cfg, cache, ids, mask, slot,
                                   pos, embed_fn=embed,
                                   dense_fns=dense_fns,
                                   logits_fn=logits_fn)

    sharded = ps.shard_map(
        chunk_prefill, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_paged_chunk_prefill_fn(model: GPTModel, mesh=None,
                                   quantized=False):
    """TP paged chunked prefill: page ids and both block-table rows are
    replicated host decisions, so every rank scatters its local heads'
    tiles to the same physical pages (int8 pools refused — no
    ``kv_quantized`` switch, as with tree verify)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = paged_cache_partition_specs()

    def chunk_prefill(params, cache, ids, mask, slot, pos, write_pages,
                      gather_row, store_row):
        return _paged_chunk_prefill_core(
            params, cfg, cache, ids, mask, slot, pos, write_pages,
            gather_row, store_row, embed_fn=embed, dense_fns=dense_fns,
            logits_fn=logits_fn)

    sharded = ps.shard_map(
        chunk_prefill, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P(), P(), P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)


def make_tp_paged_tree_verify_fn(model: GPTModel, mesh=None,
                                 quantized=False):
    """TP paged tree verify (int8 pools refused — linear spec only
    there, so no ``kv_quantized`` switch)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    cfg = model.cfg
    (embed, dense_fns, logits_fn), pspecs = _tp_build(model, quantized)
    cspecs = paged_cache_partition_specs()

    def verify(params, cache, tokens, depth, anc):
        return _paged_tree_verify_core(params, cfg, cache, tokens,
                                       depth, anc, embed_fn=embed,
                                       dense_fns=dense_fns,
                                       logits_fn=logits_fn)

    sharded = ps.shard_map(
        verify, mesh=mesh,
        in_specs=(pspecs, cspecs, P(), P(), P()),
        out_specs=(cspecs, P()))
    return jax.jit(sharded, donate_argnums=1)

"""Per-token streaming delivery for the serving front-end.

The scheduler commits tokens in bursts — one per plain decode tick,
1..k+1 per speculative verify tick, none during a prefill chunk — but
callers of a serving API want them as they land, not as a wholesale
:class:`~apex_tpu.serving.health.RequestOutcome` at the end. This
module is that fan-out layer: a :class:`TokenStream` per request, fed
by a :class:`StreamMux` the scheduler stages committed tokens into at
commit time and flushes ONCE at the end of every tick, so each flush
delivers exactly the tokens that tick committed (1..k+1 under
speculation, possibly zero under chunked prefill).

Two contracts anchor the design:

- **Delivery is host-side fan-out, never part of the committed
  stream.** The mux only observes tokens the scheduler already
  committed; it never touches slots, queues, fault draws on the
  engine's sites, or sampling keys — a scheduler run with streaming
  on commits byte-identical outcomes to one with streaming off.
- **Strict prefix on failure.** Each flush consults the
  ``stream_emit`` fault site once per request with staged tokens, in
  sorted request order (deterministic draw indices). A fired draw
  drops that request's ENTIRE staged batch, records a typed
  :class:`~apex_tpu.serving.health.StreamFailed` on the stream, and
  closes it — so ``stream.delivered`` is always a prefix of the final
  ``outcome.tokens``, and a STRICT prefix whenever the stream failed.
  The request itself keeps decoding: a consumer losing its socket
  must not cost the tenant its tokens.

Host state (APX401): streams, staging buffers and the injector's
draw counters live here — never read them inside a traced function.
"""

from typing import Callable, Dict, List, Optional, Tuple

from apex_tpu.serving.faults import FaultInjector
from apex_tpu.serving.health import ServingStats, StreamFailed
from apex_tpu.serving.observe import Tracer


class TokenStream:
    """One request's delivery-side view: the tokens actually handed to
    the consumer (``delivered`` — a prefix of the committed stream),
    the close state, and the typed :class:`StreamFailed` if delivery
    died early. Constructed by :meth:`StreamMux.open` at ``submit()``;
    read it from ``scheduler.streams.streams[request_id]``."""

    __slots__ = ("request_id", "tenant", "delivered", "closed",
                 "reason", "error")

    def __init__(self, request_id: int, tenant: str = "default"):
        self.request_id = request_id
        self.tenant = tenant
        self.delivered: List[int] = []
        self.closed = False
        self.reason: Optional[str] = None   # outcome reason once closed
        self.error: Optional[StreamFailed] = None

    @property
    def failed(self) -> bool:
        return self.error is not None

    def as_dict(self) -> Dict:
        return {"request_id": self.request_id, "tenant": self.tenant,
                "delivered": list(self.delivered), "closed": self.closed,
                "reason": self.reason,
                "error": None if self.error is None else str(self.error)}

    def __repr__(self):
        return (f"TokenStream(rid={self.request_id}, "
                f"tenant={self.tenant!r}, n={len(self.delivered)}, "
                f"closed={self.closed}, failed={self.failed})")


class StreamMux:
    """The scheduler-facing staging buffer over all open streams.

    The scheduler calls :meth:`stage` at every commit point (O(1)
    append), :meth:`finish` when a request terminates, and
    :meth:`flush` once at the end of every tick. ``flush`` walks the
    staged requests in sorted id order, draws ``stream_emit`` once per
    request batch, and either extends the stream (optionally invoking
    ``sink(request_id, tenant, tokens)`` — the caller's delivery
    callback) or drops the batch under the strict-prefix contract.

    Constructed implicitly by ``ContinuousBatchingScheduler(...,
    streams=True)`` — which wires the engine's injector/tracer/stats
    so fault draws, instants and counters land in the same
    deterministic sequence the chaos tier replays — or explicitly when
    the caller wants its own ``sink``.
    """

    def __init__(self, injector: Optional[FaultInjector] = None,
                 tracer: Optional[Tracer] = None,
                 stats: Optional[ServingStats] = None,
                 sink: Optional[Callable[[int, str, List[int]],
                                         None]] = None):
        self.injector = injector if injector is not None else FaultInjector()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.stats = stats if stats is not None else ServingStats()
        self.sink = sink
        self.streams: Dict[int, TokenStream] = {}
        self._staged: Dict[int, List[int]] = {}
        self._closing: Dict[int, str] = {}  # rid -> reason, this tick

    def open(self, request_id: int, tenant: str = "default") -> TokenStream:
        st = TokenStream(request_id, tenant)
        self.streams[request_id] = st
        return st

    def stage(self, request_id: int, token: int) -> None:
        """Record one committed token for the next flush (called from
        the scheduler's commit bookkeeping — keep it O(1))."""
        buf = self._staged.get(request_id)
        if buf is None:
            buf = self._staged[request_id] = []
        buf.append(token)

    def finish(self, request_id: int, reason: str) -> None:
        """Mark a request terminated: its stream closes at the next
        flush, AFTER its final staged batch delivers."""
        self._closing[request_id] = reason

    def flush(self) -> int:
        """End-of-tick delivery pass; returns tokens delivered. One
        ``stream_emit`` draw per request with staged tokens, in sorted
        request order — draw indices are a pure function of the commit
        history, so chaos runs replay bit-for-bit."""
        delivered = 0
        for rid in sorted(self._staged):
            toks = self._staged[rid]
            st = self.streams.get(rid)
            if st is None or st.closed or not toks:
                continue  # failed/closed earlier: batch drops, prefix holds
            fired, _ = self.injector.draw("stream_emit")
            if fired:
                idx = self.injector.calls("stream_emit") - 1
                err = StreamFailed(
                    f"stream for request {rid} dropped a "
                    f"{len(toks)}-token batch at stream_emit[{idx}]; "
                    f"{len(st.delivered)} delivered tokens remain a "
                    f"strict prefix of the committed stream",
                    request_id=rid, delivered=len(st.delivered),
                    dropped=len(toks))
                st.error = self.tracer.attach(err)
                st.closed = True
                self.stats.stream_failures += 1
                if self.tracer.enabled:
                    self.tracer.instant("stream_emit", request_id=rid,
                                        tenant=st.tenant, ok=False,
                                        dropped=len(toks))
                continue
            st.delivered.extend(toks)
            delivered += len(toks)
            self.stats.stream_batches += 1
            self.stats.stream_tokens += len(toks)
            if self.sink is not None:
                self.sink(rid, st.tenant, list(toks))
            if self.tracer.enabled:
                self.tracer.instant("stream_emit", request_id=rid,
                                    tenant=st.tenant, tokens=len(toks))
        self._staged.clear()
        for rid in sorted(self._closing):
            st = self.streams.get(rid)
            if st is not None and not st.closed:
                st.closed = True
                st.reason = self._closing[rid]
        self._closing.clear()
        return delivered

    def snapshot(self) -> List[Tuple[int, int, bool, bool]]:
        """``(request_id, delivered, closed, failed)`` rows in id
        order — the diagnostic view for tests and error payloads."""
        return [(rid, len(st.delivered), st.closed, st.failed)
                for rid, st in sorted(self.streams.items())]

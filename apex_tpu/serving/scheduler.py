"""Continuous batching over a fixed-slot KV cache.

The scheduler is the host-side half of serving: a FIFO of requests is
multiplexed onto ``num_slots`` cache rows. A slot is admitted with one
bucketed prefill (compiling once per bucket length, never per request),
then every tick advances ALL occupied slots with a single decode step;
a slot is evicted the moment it emits EOS, hits its ``max_new_tokens``,
or fills its cache row — and the freed row is re-admitted from the
queue on the same tick. The decode step therefore always runs at the
full slot batch and only two executables exist in steady state: one
decode program plus one prefill program per touched bucket.

Determinism: every sampled token draws from
``fold_in(PRNGKey(request.seed), n_generated)`` — replaying the same
request stream regenerates identical outputs regardless of how requests
interleave across slots.

The engine's cache is DONATED to each jitted step (see
``serving.decode``); ``DecodeEngine`` immediately rebinds
``self.cache``, so never hold a stale reference to it across a step.
"""

import dataclasses
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.serving.cache import init_cache
from apex_tpu.serving.decode import make_decode_fn, make_prefill_fn
from apex_tpu.serving.sampling import sample_tokens
from apex_tpu.utils.seqlen import bucket_for, default_buckets, pad_to_bucket


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``temperature <= 0`` means greedy;
    ``seed`` roots this request's PRNG stream (independent of slot
    placement and co-tenants)."""
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0


@dataclasses.dataclass
class _Slot:
    request_id: int
    request: Request
    prompt_len: int
    generated: List[int]
    pos: int            # cache rows written (prompt + decode steps)


class DecodeEngine:
    """Owns the params, the cache, and the three jitted programs
    (bucketed prefill, batched decode, sampling). ``top_k`` is static —
    an engine setting, compiled into the sampler."""

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, cache_dtype=jnp.bfloat16, top_k: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 compute_dtype=None):
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        if buckets is None:
            buckets = default_buckets(max_len, min(128, max_len))
        # clamp the ladder to the cache: prefill rejects buckets beyond
        # S_max, and the top-of-ladder bucket may overshoot max_len
        self.buckets = tuple(sorted({min(int(b), max_len)
                                     for b in buckets}))
        self.top_k = top_k
        self.cache = init_cache(cfg, num_slots, max_len, cache_dtype)
        self._prefill = make_prefill_fn(cfg, compute_dtype)
        self._decode = make_decode_fn(cfg, compute_dtype)
        self._sample = jax.jit(sample_tokens, static_argnames="top_k")

    def prefill(self, slot: int, prompt: Sequence[int]) -> jax.Array:
        """Run the full forward over ``prompt`` into cache row ``slot``;
        returns the last-real-token logits (1, V)."""
        ids = np.asarray(prompt, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=self.buckets)
        self.cache, logits = self._prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot))
        return logits

    def decode(self, tokens: jax.Array, active: jax.Array) -> jax.Array:
        """One token for every slot; ``active`` gates length advance.
        Returns (num_slots, V) fp32 logits."""
        self.cache, logits = self._decode(self.params, self.cache,
                                          tokens, active)
        return logits

    def sample(self, logits, keys, temperature) -> jax.Array:
        return self._sample(logits, keys, temperature, top_k=self.top_k)


class ContinuousBatchingScheduler:
    """FIFO → fixed slots → batched decode ticks (see module doc)."""

    def __init__(self, engine: DecodeEngine, eos_id: int):
        self.engine = engine
        self.eos_id = eos_id
        self._queue: deque = deque()
        self._slots: List[Optional[_Slot]] = [None] * engine.num_slots
        self._results: dict = {}
        self._next_id = 0

    def submit(self, request: Request) -> int:
        if not len(request.prompt):
            raise ValueError("empty prompt")
        if len(request.prompt) > self.engine.max_len:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds cache "
                f"max_len {self.engine.max_len}")
        # fail fast at submit, not mid-run inside _admit
        bucket_for(len(request.prompt), self.engine.buckets)
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, request))
        return rid

    def _slot_key(self, slot: _Slot) -> jax.Array:
        return jax.random.fold_in(
            jax.random.PRNGKey(slot.request.seed), len(slot.generated))

    def _admit(self) -> None:
        eng = self.engine
        for i in range(eng.num_slots):
            if self._slots[i] is not None or not self._queue:
                continue
            rid, req = self._queue.popleft()
            slot = _Slot(rid, req, len(req.prompt), [], len(req.prompt))
            logits = eng.prefill(i, req.prompt)
            # the FIRST generated token comes from the prefill logits
            tok = int(eng.sample(
                logits, self._slot_key(slot)[None, :],
                jnp.asarray([req.temperature], jnp.float32))[0])
            slot.generated.append(tok)
            self._slots[i] = slot
            self._maybe_evict(i)

    def _maybe_evict(self, i: int) -> None:
        slot = self._slots[i]
        done = (slot.generated[-1] == self.eos_id
                or len(slot.generated) >= slot.request.max_new_tokens
                or slot.pos >= self.engine.max_len)  # cache row full
        if done:
            self._results[slot.request_id] = list(slot.generated)
            self._slots[i] = None

    def _tick(self) -> None:
        eng = self.engine
        occupied = [s for s in self._slots if s is not None]
        if not occupied:
            return
        tokens = jnp.asarray(
            [s.generated[-1] if s else 0 for s in self._slots],
            jnp.int32)
        active = jnp.asarray([s is not None for s in self._slots])
        temps = jnp.asarray(
            [s.request.temperature if s else 0.0 for s in self._slots],
            jnp.float32)
        keys = jnp.stack(
            [self._slot_key(s) if s else jax.random.PRNGKey(0)
             for s in self._slots])
        logits = eng.decode(tokens, active)
        next_tokens = np.asarray(eng.sample(logits, keys, temps))
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.generated.append(int(next_tokens[i]))
            slot.pos += 1
            self._maybe_evict(i)

    def run(self) -> List[List[int]]:
        """Drain the queue; returns generated tokens (EOS included when
        emitted) per request, in submission order."""
        while self._queue or any(s is not None for s in self._slots):
            self._admit()
            self._tick()
        return [self._results[rid] for rid in sorted(self._results)]

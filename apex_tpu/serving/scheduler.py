"""Continuous batching over a fixed-slot KV cache.

The scheduler is the host-side half of serving: a FIFO of requests is
multiplexed onto ``num_slots`` cache rows. A slot is admitted with one
bucketed prefill (compiling once per bucket length, never per request),
then every tick advances ALL occupied slots with a single decode step;
a slot is evicted the moment it emits EOS, hits its ``max_new_tokens``,
or fills its cache row — and the freed row is re-admitted from the
queue on the same tick. The decode step therefore always runs at the
full slot batch and only two executables exist in steady state: one
decode program plus one prefill program per touched bucket.

Determinism: every sampled token draws from
``fold_in(PRNGKey(request.seed), n_generated)`` — replaying the same
request stream regenerates identical outputs regardless of how requests
interleave across slots.

The engine's cache is DONATED to each jitted step (see
``serving.decode``); ``DecodeEngine`` immediately rebinds
``self.cache``, so never hold a stale reference to it across a step.
"""

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.serving.cache import (
    NULL_PAGE, RESERVED_PAGES, SCRATCH_PAGE, init_cache,
    init_paged_cache, max_pages_per_slot,
)
from apex_tpu.serving.decode import (
    make_copy_page_fn, make_decode_fn, make_paged_decode_fn,
    make_paged_prefill_fn, make_prefill_fn,
)
from apex_tpu.serving.paging import PagePool, prefix_page_keys
from apex_tpu.serving.sampling import sample_tokens
from apex_tpu.utils.seqlen import bucket_for, default_buckets, pad_to_bucket


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``temperature <= 0`` means greedy;
    ``seed`` roots this request's PRNG stream (independent of slot
    placement and co-tenants)."""
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0


@dataclasses.dataclass
class _Slot:
    request_id: int
    request: Request
    prompt_len: int
    generated: List[int]
    pos: int            # cache rows written (prompt + decode steps)


class DecodeEngine:
    """Owns the params, the cache, and the three jitted programs
    (bucketed prefill, batched decode, sampling). ``top_k`` is static —
    an engine setting, compiled into the sampler."""

    paged = False

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, cache_dtype=jnp.bfloat16, top_k: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 compute_dtype=None):
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        if buckets is None:
            buckets = default_buckets(max_len, min(128, max_len))
        # clamp the ladder to the cache: prefill rejects buckets beyond
        # S_max, and the top-of-ladder bucket may overshoot max_len
        self.buckets = tuple(sorted({min(int(b), max_len)
                                     for b in buckets}))
        self.top_k = top_k
        self.cache = init_cache(cfg, num_slots, max_len, cache_dtype)
        self._prefill = make_prefill_fn(cfg, compute_dtype)
        self._decode = make_decode_fn(cfg, compute_dtype)
        self._sample = jax.jit(sample_tokens, static_argnames="top_k")

    def prefill(self, slot: int,
                prompt: Sequence[int]) -> Optional[jax.Array]:
        """Run the full forward over ``prompt`` into cache row ``slot``;
        returns the last-real-token logits (1, V). (The paged engine
        may instead return None — out of pages, admission must wait.)"""
        ids = np.asarray(prompt, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=self.buckets)
        self.cache, logits = self._prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot))
        return logits

    def decode(self, tokens: jax.Array, active: jax.Array) -> jax.Array:
        """One token for every slot; ``active`` gates length advance.
        Returns (num_slots, V) fp32 logits."""
        self.cache, logits = self._decode(self.params, self.cache,
                                          tokens, active)
        return logits

    def sample(self, logits, keys, temperature) -> jax.Array:
        return self._sample(logits, keys, temperature, top_k=self.top_k)

    # scheduler hooks, no-ops for the dense engine: a cache row needs
    # no per-token capacity and frees by being overwritten
    def page_demand(self, total_len: int) -> None:
        """Validate a request's worst-case capacity need at submit."""

    def prepare_decode(self, positions: Dict[int, int]) -> List[int]:
        """Make every slot's next write target exclusive; returns slots
        that had to be preempted (none for the dense cache)."""
        return []

    def free_slot(self, slot: int) -> None:
        """Release slot-owned resources on eviction/preemption."""


class PagedDecodeEngine(DecodeEngine):
    """:class:`DecodeEngine` over the paged cache: a fixed page pool,
    per-slot block tables, and a host-side :class:`PagePool` deciding
    placement. Adds prefix sharing at admission (page runs keyed by the
    chained prompt-prefix hash are retained instead of recomputed —
    including a partial last page on an exact match) and copy-on-write:
    ``prepare_decode`` runs before every decode tick to allocate
    page-boundary pages and clone any shared page a slot is about to
    append into, so the jitted decode step only ever writes
    exclusively-owned (or scratch) pages.

    ``free_order`` permutes the initial free list — physical placement
    is an allocator detail the logits provably don't depend on (the
    bit-identity tests drive different orders through this knob).
    """

    paged = True

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, num_pages: int, page_size: int,
                 cache_dtype=jnp.bfloat16, top_k: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 compute_dtype=None,
                 free_order: Optional[Sequence[int]] = None,
                 prefix_sharing: bool = True):
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages = max_pages_per_slot(max_len, page_size)
        self.prefix_sharing = prefix_sharing
        if buckets is None:
            buckets = default_buckets(max_len, min(128, max_len))
        self.buckets = tuple(sorted({min(int(b), max_len)
                                     for b in buckets}))
        bad = [b for b in self.buckets if b % page_size]
        if bad:
            raise ValueError(
                f"paged prefill writes whole pages: buckets {bad} are "
                f"not multiples of page_size {page_size}")
        self.top_k = top_k
        self.cache = init_paged_cache(cfg, num_slots, max_len, num_pages,
                                      page_size, cache_dtype)
        self.pool = PagePool(num_pages, page_size, free_order)
        self._slot_pages: List[List[int]] = [[] for _ in range(num_slots)]
        self._prefill = make_paged_prefill_fn(cfg, compute_dtype)
        self._decode = make_paged_decode_fn(cfg, compute_dtype)
        self._copy = make_copy_page_fn()
        self._sample = jax.jit(sample_tokens, static_argnames="top_k")

    def page_demand(self, total_len: int) -> None:
        need = max_pages_per_slot(min(total_len, self.max_len),
                                  self.page_size)
        usable = self.pool.num_pages - RESERVED_PAGES
        if need > usable:
            raise ValueError(
                f"request needs up to {need} pages but the pool only "
                f"has {usable} usable pages")

    def prefill(self, slot: int,
                prompt: Sequence[int]) -> Optional[jax.Array]:
        """Admit ``prompt`` into ``slot``: share the longest cached
        prefix run, allocate private pages for the rest, register the
        chain for future requests, and prefill — writing ONLY the
        private pages (shared ones are redirected to scratch; their
        rows were produced by the original request and are reused
        verbatim). Returns None when the pool can't cover the prompt
        even after LRU eviction — the caller requeues. Raises for a
        prompt beyond ``max_len`` BEFORE touching the pool (the
        scheduler's submit check normally screens this, but the engine
        must not leak page references when driven directly)."""
        toks = [int(t) for t in prompt]
        if len(toks) > self.max_len:
            raise ValueError(
                f"prompt length {len(toks)} exceeds cache max_len "
                f"{self.max_len}")
        n_pages = max_pages_per_slot(len(toks), self.page_size)
        keys = prefix_page_keys(toks, self.page_size)
        shared = self.pool.match_prefix(keys) if self.prefix_sharing \
            else []
        private: List[int] = []
        for _ in range(n_pages - len(shared)):
            p = self.pool.alloc()
            if p is None:
                for q in shared + private:
                    self.pool.release(q)
                return None
            private.append(p)
        pages = shared + private
        if self.prefix_sharing:
            self.pool.register_prefix(keys, pages)
        self._slot_pages[slot] = list(pages)

        ids = np.asarray(toks, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=self.buckets)
        write = np.full((ids.shape[1] // self.page_size,), SCRATCH_PAGE,
                        np.int32)
        write[len(shared):n_pages] = private
        row = np.full((self.max_pages,), NULL_PAGE, np.int32)
        row[:n_pages] = pages
        self.cache, logits = self._prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot),
            jnp.asarray(write), jnp.asarray(row))
        return logits

    def prepare_decode(self, positions: Dict[int, int]) -> List[int]:
        """Before a decode tick writes row ``pos`` for each slot: cross
        a page boundary by allocating a fresh page, and clone (COW) a
        shared page about to receive an appended row — unless the
        failed clone alloc's registry eviction left the slot sole
        owner, in which case the append proceeds in place. A slot the
        pool genuinely cannot serve is preempted — its pages are
        released (often unblocking the rest of the batch) and the
        caller requeues the request."""
        preempted: List[int] = []
        for i, pos in sorted(positions.items()):
            pages = self._slot_pages[i]
            idx = pos // self.page_size
            if idx == len(pages):                       # page boundary
                p = self.pool.alloc()
                if p is None:
                    self.free_slot(i)
                    preempted.append(i)
                    continue
                pages.append(p)
                self.cache = self.cache._replace(
                    block_tables=self.cache.block_tables.at[i, idx].set(p))
            elif self.pool.needs_copy(pages[idx]):      # COW
                dst = self.pool.alloc()
                if dst is None:
                    # the failed alloc's LRU sweep emptied the prefix
                    # registry; if the page's only co-owner was the
                    # registry the append is now in-place legal — no
                    # copy needed. Preempting instead would livelock:
                    # re-admission recreates the exact same state
                    # (registered partial last page at refcount 2,
                    # pool at the validated worst-case fit)
                    if not self.pool.needs_copy(pages[idx]):
                        continue
                    self.free_slot(i)
                    preempted.append(i)
                    continue
                self.cache = self._copy(self.cache,
                                        jnp.int32(pages[idx]),
                                        jnp.int32(dst))
                self.cache = self.cache._replace(
                    block_tables=self.cache.block_tables.at[i, idx].set(
                        dst))
                self.pool.release(pages[idx])
                pages[idx] = dst
        return preempted

    def free_slot(self, slot: int) -> None:
        """Release the slot's page references and park its block-table
        row on scratch (a freed slot's parked decode writes must never
        land in a page the allocator may hand to someone else)."""
        for p in self._slot_pages[slot]:
            self.pool.release(p)
        self._slot_pages[slot] = []
        self.cache = self.cache._replace(
            block_tables=self.cache.block_tables.at[slot].set(
                jnp.full((self.max_pages,), SCRATCH_PAGE, jnp.int32)))


class ContinuousBatchingScheduler:
    """FIFO → fixed slots → batched decode ticks (see module doc)."""

    def __init__(self, engine: DecodeEngine, eos_id: int):
        self.engine = engine
        self.eos_id = eos_id
        self._queue: deque = deque()
        self._slots: List[Optional[_Slot]] = [None] * engine.num_slots
        self._results: dict = {}
        self._next_id = 0

    def submit(self, request: Request) -> int:
        if not len(request.prompt):
            raise ValueError("empty prompt")
        if len(request.prompt) > self.engine.max_len:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds cache "
                f"max_len {self.engine.max_len}")
        # fail fast at submit, not mid-run inside _admit: the prompt
        # must have a bucket rung and (paged) fit the pool even running
        # alone at its worst-case generated length
        bucket_for(len(request.prompt), self.engine.buckets)
        self.engine.page_demand(
            len(request.prompt) + request.max_new_tokens)
        rid = self._next_id
        self._next_id += 1
        # third element: tokens already generated — empty for fresh
        # submissions, carried through preemption-by-requeue
        self._queue.append((rid, request, []))
        return rid

    def _slot_key(self, slot: _Slot) -> jax.Array:
        return jax.random.fold_in(
            jax.random.PRNGKey(slot.request.seed), len(slot.generated))

    def _admit(self) -> None:
        eng = self.engine
        for i in range(eng.num_slots):
            if self._slots[i] is not None or not self._queue:
                continue
            rid, req, resume = self._queue[0]
            # a preempted request resumes by re-prefilling everything
            # it had produced EXCEPT its last sampled token, which the
            # next decode tick feeds (the normal teacher-forcing shape)
            tokens = tuple(req.prompt) + tuple(resume[:-1])
            logits = eng.prefill(i, tokens)
            if logits is None:
                # out of pages: keep FIFO order, wait for evictions
                if all(s is None for s in self._slots):
                    raise RuntimeError(
                        "page pool cannot admit the queue head even "
                        "with every slot free — submit-time validation "
                        "should have rejected it")
                break
            self._queue.popleft()
            slot = _Slot(rid, req, len(req.prompt), list(resume),
                         len(tokens))
            if not resume:
                # the FIRST generated token comes from the prefill
                # logits; on resume it already exists
                tok = int(eng.sample(
                    logits, self._slot_key(slot)[None, :],
                    jnp.asarray([req.temperature], jnp.float32))[0])
                slot.generated.append(tok)
            self._slots[i] = slot
            self._maybe_evict(i)

    def _maybe_evict(self, i: int) -> None:
        slot = self._slots[i]
        done = (slot.generated[-1] == self.eos_id
                or len(slot.generated) >= slot.request.max_new_tokens
                or slot.pos >= self.engine.max_len)  # cache row full
        if done:
            self._results[slot.request_id] = list(slot.generated)
            self._slots[i] = None
            self.engine.free_slot(i)

    def _tick(self) -> None:
        eng = self.engine
        # give every occupied slot an exclusive write target for this
        # tick; slots the pool can't serve are preempted back to the
        # queue FRONT with their progress (sampling keys depend only on
        # (seed, n_generated), so a resumed request continues its
        # original stream bit-for-bit)
        positions = {i: s.pos for i, s in enumerate(self._slots)
                     if s is not None}
        # requeue in submission order: appendleft of the newest request
        # first leaves the oldest at the queue front (slot-index order
        # would let a later request resume before an earlier one)
        preempted = eng.prepare_decode(positions)
        for i in sorted(preempted,
                        key=lambda j: self._slots[j].request_id,
                        reverse=True):
            s = self._slots[i]
            self._queue.appendleft((s.request_id, s.request,
                                    list(s.generated)))
            self._slots[i] = None
        occupied = [s for s in self._slots if s is not None]
        if not occupied:
            return
        tokens = jnp.asarray(
            [s.generated[-1] if s else 0 for s in self._slots],
            jnp.int32)
        active = jnp.asarray([s is not None for s in self._slots])
        temps = jnp.asarray(
            [s.request.temperature if s else 0.0 for s in self._slots],
            jnp.float32)
        keys = jnp.stack(
            [self._slot_key(s) if s else jax.random.PRNGKey(0)
             for s in self._slots])
        logits = eng.decode(tokens, active)
        next_tokens = np.asarray(eng.sample(logits, keys, temps))
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.generated.append(int(next_tokens[i]))
            slot.pos += 1
            self._maybe_evict(i)

    def run(self) -> List[List[int]]:
        """Drain the queue; returns generated tokens (EOS included when
        emitted) per request, in submission order."""
        while self._queue or any(s is not None for s in self._slots):
            self._admit()
            self._tick()
        return [self._results[rid] for rid in sorted(self._results)]

"""Continuous batching over a fixed-slot KV cache.

The scheduler is the host-side half of serving: a FIFO of requests is
multiplexed onto ``num_slots`` cache rows. A slot is admitted with one
bucketed prefill (compiling once per bucket length, never per request),
then every tick advances ALL occupied slots with a single decode step;
a slot is evicted the moment it emits EOS, hits its ``max_new_tokens``,
or fills its cache row — and the freed row is re-admitted from the
queue on the same tick. The decode step therefore always runs at the
full slot batch and only two executables exist in steady state: one
decode program plus one prefill program per touched bucket.

Determinism: every sampled token draws from
``fold_in(PRNGKey(request.seed), n_generated)`` — replaying the same
request stream regenerates identical outputs regardless of how requests
interleave across slots.

Chunked prefill (``chunk_tokens=``, the Sarathi-Serve move): a
monolithic prompt forward stalls every co-tenant decode for the whole
prompt length, which is exactly what blows up p99 inter-token latency
under mixed prompt/decode load. With chunking on, admission only
STAGES a prefill (pages allocated up front, all-or-nothing); each tick
then runs the decode step first and spends whatever remains of
``tick_token_budget`` on page-aligned prompt chunks — one jitted
executable total, every chunk padded to ``chunk_tokens``. Concurrent
prefills are ordered earliest-deadline-first and round-robined one
chunk at a time (fair share); at least one chunk always runs so a
saturated decode batch cannot starve admission. A mid-prefill slot is
invisible to the decode path, and on the paged cache its block-table
row stays parked on scratch until the final chunk installs it — the
garbage row co-tenant ticks write for every slot must never land in a
shared page. The final chunk yields the same first-token logits
position as monolithic prefill and samples with the same key, so the
COMMITTED token streams are bit-identical to the synchronous
scheduler: chunking only reorders when prompt work happens, never what
any request observes.

Speculative decoding (``spec_k > 0``): each tick first asks the
host-side n-gram drafter (``serving.draft``) for up to ``spec_k``
candidate tokens per slot, then runs ONE verify step over the k+1
candidate positions (``serving.decode``), samples every position with
the key the plain stream would have used there
(``fold_in(seed, n_generated + j)``), and commits the longest prefix
where the samples reproduce the drafts, plus the first non-matching
sample — 1..k+1 tokens per slot per tick. Because the keys are the
plain stream's keys, the committed tokens are BIT-IDENTICAL to plain
decode; acceptance only changes the step count (see
``serving.sampling``). A tick that commits m tokens advances the
scheduler clock by m, so deadlines and watchdog progress stay
comparable between modes. The tick degrades to a plain decode step
whenever every draft is empty (including a fired ``draft_exec`` fault
site) or any active slot lacks ``spec_k + 1`` rows of cache headroom.

Model-based & tree speculation (PR 12) layer three upgrades onto that
base, each independently switchable and all preserving the committed
streams bit-for-bit:

- **model drafting** (``draft_model=``): a tiny TP-sharded draft GPT
  (``serving.draft_model.DraftModel``) replaces the n-gram lookup,
  advanced in lockstep with the target's slots and re-synced by common
  prefix after rejections. Its ``draft_exec`` fault ladder degrades
  model draft → n-gram draft → plain tick, charging no retry budget.
- **tree speculation** (``tree_spec=True``): drafts become small trees
  (chain + alternate root branch) verified in ONE tree-attention
  forward (``decode.make_tree_verify_fn``); the accept walk
  (``sampling.tree_speculative_accept``) follows the sampled
  root-to-leaf path. Cache lengths only ever advance by the
  row-contiguous committed prefix; committed tokens stranded off the
  leftmost chain are RE-SENT as next tick's forced chain (the
  forced-prefix rule — bounded by tree depth, never compounding).
- **adaptive depth** (``adaptive_spec=True``): a per-stream EWMA of
  the measured acceptance rate scales each slot's draft depth between
  0 (plain ticks, with a periodic probe) and ``spec_k``, and the
  verify grid narrows to the widest draft actually proposed — so a
  stream that stops accepting stops paying for speculation.

Failure is an expected state (the dynamic-loss-scaler discipline,
applied to serving — see ``serving.health``): pool exhaustion, NaN
logits, bad samples, and transient exec faults all degrade gracefully
instead of crashing or spinning:

- **typed taxonomy** — ``PagedDecodeEngine.prefill`` raises
  :class:`~apex_tpu.serving.health.PoolExhausted` instead of returning
  ``None``; every request ends in a
  :class:`~apex_tpu.serving.health.RequestOutcome` with a typed
  reason, in ``scheduler.outcomes``.
- **quarantine + retry budget** — non-finite logits or an
  out-of-vocabulary sampled token quarantines the slot: the corrupt
  token is never committed, the slot is freed and the request requeued
  at the queue FRONT with its progress. Because resume re-prefills the
  committed tokens and keys depend only on ``(seed, n_generated)``,
  the recovered stream is bit-identical to the fault-free one — and
  co-tenant slots never notice. Each fault-path requeue charges the
  request's retry budget (``max_retries``); exhaustion terminates it
  with ``RetryBudgetExhausted``. Capacity preemptions stay free: they
  consume no budget (pressure is not the request's fault).
- **backpressure** — ``max_queue`` bounds the admission queue;
  ``submit`` sheds load with ``AdmissionRejected`` beyond it.
- **deadlines** — ``Request.deadline_ticks`` bounds a request's
  lifetime in scheduler ticks (deterministic, unlike wall clocks);
  overruns terminate with ``DeadlineExceeded`` and partial tokens.
- **watchdog** — ``run()`` raises a diagnostic
  :class:`~apex_tpu.serving.health.LivelockError` (stuck requests +
  pool snapshot) after ``watchdog_limit`` ticks without progress,
  instead of spinning (the PR-8 COW livelock, generalized). Progress
  is strictly monotonic evidence of convergence: a token committed, a
  request terminated, or a (finite) retry consumed — capacity
  preemptions deliberately do NOT count.
- **audit** — ``audit=True`` runs the engine's pool-invariant checker
  after every tick (the chaos tier's setting).

Fault injection (``serving.faults``) drives all of these paths
deterministically: the engines consult their
:class:`~apex_tpu.serving.faults.FaultInjector` at the named sites
through host-side hooks, so the jitted programs — and a replayed chaos
run — stay bit-exact.

The engine's cache is DONATED to each jitted step (see
``serving.decode``); ``DecodeEngine`` immediately rebinds
``self.cache``, so never hold a stale reference to it across a step.
"""

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.serving.cache import (
    NULL_PAGE, RESERVED_PAGES, SCRATCH_PAGE, audit_block_tables,
    init_cache, init_paged_cache, max_pages_per_slot,
)
from apex_tpu.serving.decode import (
    make_chunk_prefill_fn, make_copy_page_fn, make_decode_fn,
    make_paged_chunk_prefill_fn, make_paged_decode_fn,
    make_paged_prefill_fn, make_paged_tree_verify_fn,
    make_paged_verify_fn, make_prefill_fn, make_tree_verify_fn,
    make_verify_fn,
)
from apex_tpu.serving.draft import ngram_draft, tree_arrays
from apex_tpu.serving.faults import FaultInjector, InjectedFault
from apex_tpu.serving.health import (
    AdmissionRejected, DeadlineExceeded, LivelockError, NonFiniteLogits,
    PoolExhausted, PromoteFailed, QuotaExhausted, RequestOutcome,
    RetryBudgetExhausted, ServingStats, SpillFailed,
)
from apex_tpu.quant.params import is_quantized_tree
from apex_tpu.serving.observe import Tracer
from apex_tpu.serving.paging import (
    PAGE_KEY_VERSION, SPILL_DTYPE_TAGS, PagePool, PrefixRegistry,
    SpillRecord, decode_spill_header, encode_spill_header,
    prefix_page_keys, spill_checksum,
)
from apex_tpu.serving.transfer import (
    make_extract_pages_fn, make_extract_pages_quant_fn,
    make_insert_pages_fn, make_insert_pages_quant_fn,
)
from apex_tpu.serving.sampling import (
    finite_rows, sample_token_grid, sample_tokens,
    tree_speculative_accept,
)
from apex_tpu.utils.seqlen import bucket_for, default_buckets, pad_to_bucket


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``temperature <= 0`` means greedy;
    ``seed`` roots this request's PRNG stream (independent of slot
    placement and co-tenants). ``deadline_ticks``, when set, bounds the
    request's lifetime in scheduler ticks since submission — a
    deterministic deadline (overruns end in a ``deadline`` outcome with
    the tokens committed so far). ``tenant_id`` names the traffic
    class the tenancy front-end (``serving.tenancy``) accounts the
    request under; the default tenant keeps the untenanted scheduler
    byte-compatible."""
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    temperature: float = 0.0
    seed: int = 0
    deadline_ticks: Optional[int] = None
    tenant_id: str = "default"


@dataclasses.dataclass
class _PrefillProgress:
    """In-flight chunked prefill for a slot: the full teacher-forcing
    sequence being prefilled, the next chunk's start position, and the
    engine's opaque staging state from ``begin_chunk_prefill`` (page
    plan, prefix keys). While ``_Slot.prefill`` holds one of these the
    slot owns cache capacity but is invisible to the decode path."""
    tokens: Tuple[int, ...]
    next: int
    state: Dict


@dataclasses.dataclass
class _Slot:
    request_id: int
    request: Request
    prompt_len: int
    generated: List[int]
    pos: int            # cache rows written (prompt + decode steps)
    prefill: Optional[_PrefillProgress] = None


class DecodeEngine:
    """Owns the params, the cache, and the jitted programs (bucketed
    prefill, batched decode, speculative verify, sampling). ``top_k``,
    ``top_p`` and ``spec_k`` are static — engine settings, compiled
    into the programs (``spec_k`` is the DRAFT DEPTH; 0 disables
    speculation). ``injector`` hooks the fault sites (inert by
    default); ``tracer`` hooks the observability sites the same way
    (``serving.observe`` — disabled by default, one attribute check
    per site); ``stats`` is the
    :class:`~apex_tpu.serving.health.ServingStats` counter block the
    scheduler shares, a view over the tracer's metrics registry."""

    paged = False
    #: The tenant whose request the scheduler is currently admitting —
    #: stamped (tenancy mode only) right before ``prefill`` /
    #: ``begin_chunk_prefill`` so composite engines can thread it into
    #: their routing observability and affinity tiebreaks
    #: (``serving.router``). Host state, never read under trace.
    admission_tenant: Optional[str] = None

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, cache_dtype=jnp.bfloat16, top_k: int = 0,
                 top_p: float = 0.0, spec_k: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 compute_dtype=None,
                 injector: Optional[FaultInjector] = None,
                 draft_model=None, tree_spec: bool = False,
                 adaptive_spec: bool = False,
                 tracer: Optional[Tracer] = None):
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        if buckets is None:
            buckets = default_buckets(max_len, min(128, max_len))
        # clamp the ladder to the cache: prefill rejects buckets beyond
        # S_max, and the top-of-ladder bucket may overshoot max_len
        self.buckets = tuple(sorted({min(int(b), max_len)
                                     for b in buckets}))
        self.top_k = top_k
        self.top_p = top_p
        self.spec_k = spec_k
        self._check_spec_config(draft_model, tree_spec, adaptive_spec)
        self.draft_model = draft_model
        self.tree_spec = tree_spec
        self.adaptive_spec = adaptive_spec
        self.injector = injector or FaultInjector()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.stats = ServingStats(registry=self.tracer.registry)
        if jnp.dtype(cache_dtype) == jnp.int8:
            raise ValueError(
                "the dense cache has no int8 mode (per-page scales need "
                "pages); use PagedDecodeEngine for kv_dtype=int8")
        # weight-only int8 trees are auto-detected: the builders swap in
        # the dequant-fused kernels, everything else is unchanged
        quantized = is_quantized_tree(params)
        self.cache = init_cache(cfg, num_slots, max_len, cache_dtype)
        self._prefill = make_prefill_fn(cfg, compute_dtype, quantized)
        self._chunk_prefill = make_chunk_prefill_fn(cfg, compute_dtype,
                                                    quantized)
        self._decode = make_decode_fn(cfg, compute_dtype, quantized)
        self._verify = make_verify_fn(cfg, compute_dtype, quantized)
        self._tree_verify = make_tree_verify_fn(
            cfg, compute_dtype, quantized) if tree_spec else None
        self._init_samplers()

    def _check_spec_config(self, draft_model, tree_spec,
                           adaptive_spec) -> None:
        if (draft_model is not None or tree_spec or adaptive_spec) \
                and self.spec_k < 1:
            raise ValueError(
                "draft_model / tree_spec / adaptive_spec require "
                "spec_k >= 1 (speculation is otherwise disabled)")
        if draft_model is not None:
            if draft_model.num_slots != self.num_slots:
                raise ValueError(
                    f"draft model has {draft_model.num_slots} slots, "
                    f"engine has {self.num_slots}")
            if draft_model.cfg.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    "draft and target models must share a vocabulary "
                    f"({draft_model.cfg.vocab_size} vs "
                    f"{self.cfg.vocab_size})")

    def _init_samplers(self) -> None:
        self._sample = jax.jit(sample_tokens,
                               static_argnames=("top_k", "top_p"))
        self._sample_grid = jax.jit(sample_token_grid,
                                    static_argnames=("top_k", "top_p"))
        self._finite = jax.jit(finite_rows)

    def prefill(self, slot: int, prompt: Sequence[int]) -> jax.Array:
        """Run the full forward over ``prompt`` into cache row ``slot``;
        returns the last-real-token logits (1, V). Raises
        :class:`~apex_tpu.serving.health.PoolExhausted` when capacity
        can't cover the prompt (paged engine) and
        :class:`~apex_tpu.serving.faults.InjectedFault` under an armed
        ``prefill_exec`` fault site — both with all transient resources
        rolled back."""
        fired, _ = self.injector.draw("prefill_exec")
        if fired:
            raise InjectedFault("prefill_exec",
                                self.injector.calls("prefill_exec") - 1)
        ids = np.asarray(prompt, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=self.buckets)
        trc = self.tracer
        if trc.enabled:
            trc.begin("prefill")
        self.cache, logits = self._prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot))
        if trc.enabled:
            trc.end("prefill", slot=slot, bucket=int(ids.shape[1]))
        return logits

    # -- chunked prefill ------------------------------------------------

    def begin_chunk_prefill(self, slot: int,
                            prompt: Sequence[int]) -> Dict:
        """Stage a chunked prefill of ``prompt`` into ``slot``; returns
        the opaque per-request state :meth:`chunk_prefill` consumes.
        The dense cache needs no staging (rows are slot-owned), so the
        state only carries the chunking start offset."""
        if len(prompt) > self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds cache max_len "
                f"{self.max_len}")
        return {"start": 0}

    def chunk_prefill(self, slot: int, chunk: Sequence[int], pos: int,
                      state: Dict, bucket: int,
                      final: bool) -> jax.Array:
        """Run ONE prompt chunk (rows ``pos .. pos+len(chunk)-1``) for
        ``slot``; every call pads to ``bucket`` tokens, so exactly one
        executable exists per chunk size. Returns the chunk's
        last-real-token logits (1, V) — only the final chunk's feed the
        first sampled token. An armed ``chunk_prefill_exec`` fault site
        raises :class:`InjectedFault` BEFORE touching the cache."""
        fired, _ = self.injector.draw("chunk_prefill_exec")
        if fired:
            raise InjectedFault(
                "chunk_prefill_exec",
                self.injector.calls("chunk_prefill_exec") - 1)
        ids = np.asarray(chunk, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=(bucket,))
        trc = self.tracer
        if trc.enabled:
            trc.begin("chunk_prefill")
        self.cache, logits = self._chunk_prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot),
            jnp.int32(pos))
        if trc.enabled:
            trc.end("chunk_prefill", slot=slot, pos=pos, bucket=bucket,
                    final=final)
        return logits

    def finish_chunk_prefill(self, slot: int, state: Dict) -> None:
        """Post-final-chunk bookkeeping (prefix registration on the
        paged engine); a no-op for the dense cache."""

    def decode(self, tokens: jax.Array, active: jax.Array) -> jax.Array:
        """One token for every slot; ``active`` gates length advance.
        Returns (num_slots, V) fp32 logits. An armed ``decode_exec``
        fault site overwrites one deterministic victim row with NaN
        AFTER the jitted step — the compiled program and the other
        rows stay bit-exact, and the scheduler's finiteness gate
        (:func:`~apex_tpu.serving.sampling.finite_rows`) must catch
        it."""
        trc = self.tracer
        if trc.enabled:
            trc.begin("exec")
        self.cache, logits = self._decode(self.params, self.cache,
                                          tokens, active)
        if trc.enabled:
            trc.end("exec", kind="decode")
        fired, payload = self.injector.draw("decode_exec")
        if fired:
            victim = int(payload % logits.shape[0])
            logits = logits.at[victim].set(jnp.nan)
        return logits

    def sample(self, logits, keys, temperature) -> jax.Array:
        toks = self._sample(logits, keys, temperature, top_k=self.top_k,
                            top_p=self.top_p)
        fired, payload = self.injector.draw("sample")
        if fired:
            # out-of-vocabulary id: negative, so it can never collide
            # with a real token — the scheduler's range check quarantines
            victim = int(payload % toks.shape[0])
            toks = toks.at[victim].set(jnp.int32(-1 - payload % 7))
        return toks

    def finite(self, logits) -> jax.Array:
        """(B,) bool device reduction: which logits rows are safe to
        sample (see :func:`~apex_tpu.serving.sampling.finite_rows`)."""
        return self._finite(logits)

    # -- speculative decoding -------------------------------------------

    def draft(self, history: Sequence[int]) -> List[int]:
        """Host-side n-gram draft of up to ``spec_k`` candidates from
        one slot's prompt+generated history. An armed ``draft_exec``
        fault site raises :class:`InjectedFault` — the scheduler
        degrades that slot to an empty draft (plain decode pace) for
        the tick; drafting is best-effort, so no retry budget is
        charged."""
        fired, _ = self.injector.draw("draft_exec")
        if fired:
            raise InjectedFault("draft_exec",
                                self.injector.calls("draft_exec") - 1)
        return ngram_draft(history, self.spec_k)

    def _draft_ladder(self) -> bool:
        """The model drafter's two-rung ``draft_exec`` ladder: one draw
        decides whether the MODEL draft fails this tick; a fired draw
        counts a draft fault and takes a second draw deciding whether
        the n-gram fallback fails too (raising :class:`InjectedFault`,
        which the scheduler turns into a plain tick). Returns True when
        the caller should use the n-gram rung. No rung charges retry
        budget — drafting is best-effort."""
        fired, _ = self.injector.draw("draft_exec")
        if not fired:
            return False
        self.stats.draft_faults += 1
        fired, _ = self.injector.draw("draft_exec")
        if fired:
            raise InjectedFault("draft_exec",
                                self.injector.calls("draft_exec") - 1)
        return True

    def draft_batch(self, histories, ks) -> List[List[int]]:
        """Model-draft every slot in ONE batched call: up to ``ks[i]``
        greedy continuation tokens of ``histories[i]`` from the
        attached :class:`~apex_tpu.serving.draft_model.DraftModel`
        (``None`` history or ``k = 0`` yields an empty draft). The
        ``draft_exec`` ladder (:meth:`_draft_ladder`) degrades model →
        n-gram → plain."""
        if self._draft_ladder():
            return [list(ngram_draft(h, k)) if h is not None else []
                    for h, k in zip(histories, ks)]
        return [[int(t) for t in c]
                for c in self.draft_model.draft(histories, ks)]

    def draft_tree_batch(self, histories, ks):
        """Tree drafts (``(tokens, parents)`` per slot, ``None`` when
        inactive) from the model drafter — a greedy chain plus an
        alternate root branch, see :meth:`DraftModel.draft_tree`. The
        same ``draft_exec`` ladder applies; its n-gram rung emits
        single-chain trees."""
        if self._draft_ladder():
            out = []
            for h, k in zip(histories, ks):
                c = [int(t) for t in ngram_draft(h, k)] \
                    if h is not None else []
                out.append((c, [-1] + list(range(len(c) - 1)))
                           if c else None)
            return out
        return self.draft_model.draft_tree(histories, ks)

    def verify(self, tokens: jax.Array) -> jax.Array:
        """One speculative verify step: ``tokens`` (num_slots, spec_k+1)
        int32 — column 0 the pending token, columns 1.. the (0-padded)
        drafts. Returns (num_slots, spec_k+1, V) fp32 logits; slot
        lengths are committed separately (:meth:`commit`) once the host
        accept walk knows each slot's count. The ``decode_exec`` fault
        site covers this step too (the victim row goes NaN across all
        positions, post-jit)."""
        trc = self.tracer
        if trc.enabled:
            trc.begin("exec")
        self.cache, logits = self._verify(self.params, self.cache,
                                          tokens)
        if trc.enabled:
            trc.end("exec", kind="verify", k1=int(tokens.shape[1]))
        fired, payload = self.injector.draw("decode_exec")
        if fired:
            victim = int(payload % logits.shape[0])
            logits = logits.at[victim].set(jnp.nan)
        return logits

    def tree_verify(self, tokens: jax.Array, depth: jax.Array,
                    anc: jax.Array) -> jax.Array:
        """One tree-attention verify step over a packed draft grid (see
        :func:`~apex_tpu.serving.draft.tree_arrays`): column j writes
        K/V at physical row ``lengths + j`` with sequence position
        ``lengths + depth[:, j]`` and attends committed rows plus its
        ancestor columns under ``anc``. Returns (num_slots, k1, V) fp32
        logits; commits stay host-side (:meth:`commit`). Shares the
        ``decode_exec`` fault site with the other step kinds."""
        trc = self.tracer
        if trc.enabled:
            trc.begin("exec")
        self.cache, logits = self._tree_verify(self.params, self.cache,
                                               tokens, depth, anc)
        if trc.enabled:
            trc.end("exec", kind="tree_verify", k1=int(tokens.shape[1]))
        fired, payload = self.injector.draw("decode_exec")
        if fired:
            victim = int(payload % logits.shape[0])
            logits = logits.at[victim].set(jnp.nan)
        return logits

    def commit(self, counts: Sequence[int]) -> None:
        """Advance slot lengths by each slot's committed token count —
        the host half of the verify step's rollback contract: rows
        beyond ``lengths + count`` were written but are never admitted
        by any mask before the next step re-writes them."""
        trc = self.tracer
        if trc.enabled:
            trc.begin("commit")
        self.cache = self.cache._replace(
            lengths=self.cache.lengths
            + jnp.asarray(counts, jnp.int32))
        if trc.enabled:
            trc.end("commit", rows=int(sum(int(c) for c in counts)))

    def sample_grid(self, logits, keys, temperature) -> jax.Array:
        """Sample every (slot, position) of a verify step's logits with
        its own key; the ``sample`` fault site corrupts the victim
        slot's FIRST position (the one a plain tick would have drawn),
        so the scheduler's range gate quarantines before any commit."""
        toks = self._sample_grid(logits, keys, temperature,
                                 top_k=self.top_k, top_p=self.top_p)
        fired, payload = self.injector.draw("sample")
        if fired:
            victim = int(payload % toks.shape[0])
            toks = toks.at[victim, 0].set(jnp.int32(-1 - payload % 7))
        return toks

    # scheduler hooks, no-ops for the dense engine: a cache row needs
    # no per-token capacity and frees by being overwritten
    def page_demand(self, total_len: int) -> None:
        """Validate a request's worst-case capacity need at submit."""

    def prepare_decode(self, positions: Dict[int, int],
                       n_new: int = 1) -> List[int]:
        """Make every slot's next ``n_new`` write targets exclusive;
        returns slots that had to be preempted (none for the dense
        cache)."""
        return []

    def free_slot(self, slot: int) -> None:
        """Release slot-owned resources on eviction/preemption (the
        attached draft model's lockstep cache row, when present)."""
        if self.draft_model is not None:
            self.draft_model.free_slot(slot)

    def check_invariants(self) -> bool:
        """Audit engine-owned bookkeeping (pool refcounts, block
        tables); trivially true for the dense cache."""
        return True

    def pool_snapshot(self) -> Dict:
        """Allocator state for diagnostics (LivelockError payloads)."""
        return {}

    def pool_gauges(self) -> Optional[Dict[str, float]]:
        """Gauge sources for the tracer's end-of-tick rollup
        (``None``: the dense cache has no page pool to meter)."""
        return None

    def pop_admit_charge(self, default: int) -> int:
        """Tick-clock cost of the admission/prefill forward the
        scheduler just ran — consumed (and reset) by
        ``ContinuousBatchingScheduler._charge_work``. The base engine
        charges the ``default`` (the forward's sequential depth);
        engines that replaced part of that depth with cheaper work
        stage a different charge here: a host-tier promotion prices
        the skipped prefix at transfer ticks, the disaggregated
        composite prices a remote prefill at handoff ticks, and the
        pool composite at the per-link reshard horizon it extends
        (so concurrent handoffs on different links overlap). Purely
        accounting — sampling keys never see the clock."""
        return default


class PagedDecodeEngine(DecodeEngine):
    """:class:`DecodeEngine` over the paged cache: a fixed page pool,
    per-slot block tables, and a host-side :class:`PagePool` deciding
    placement. Adds prefix sharing at admission (page runs keyed by the
    chained prompt-prefix hash are retained instead of recomputed —
    including a partial last page on an exact match) and copy-on-write:
    ``prepare_decode`` runs before every decode tick to allocate
    page-boundary pages and clone any shared page a slot is about to
    append into, so the jitted decode step only ever writes
    exclusively-owned (or scratch) pages.

    ``free_order`` permutes the initial free list — physical placement
    is an allocator detail the logits provably don't depend on (the
    bit-identity tests drive different orders through this knob).
    """

    paged = True

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, num_pages: int, page_size: int,
                 cache_dtype=jnp.bfloat16, top_k: int = 0,
                 top_p: float = 0.0, spec_k: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 compute_dtype=None,
                 free_order: Optional[Sequence[int]] = None,
                 prefix_sharing: bool = True,
                 injector: Optional[FaultInjector] = None,
                 draft_model=None, tree_spec: bool = False,
                 adaptive_spec: bool = False,
                 tracer: Optional[Tracer] = None,
                 host_tier: Optional[PrefixRegistry] = None,
                 promote_ticks_per_page: float = 0.125):
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages = max_pages_per_slot(max_len, page_size)
        self.prefix_sharing = prefix_sharing
        if buckets is None:
            buckets = default_buckets(max_len, min(128, max_len))
        self.buckets = tuple(sorted({min(int(b), max_len)
                                     for b in buckets}))
        bad = [b for b in self.buckets if b % page_size]
        if bad:
            raise ValueError(
                f"paged prefill writes whole pages: buckets {bad} are "
                f"not multiples of page_size {page_size}")
        self.top_k = top_k
        self.top_p = top_p
        self.spec_k = spec_k
        self._check_spec_config(draft_model, tree_spec, adaptive_spec)
        if tree_spec and jnp.dtype(cache_dtype) == jnp.int8:
            raise ValueError(
                "tree verify is not offered over the int8 page pool: a "
                "branch commit would re-round committed history at "
                "branch-dependent scales; kv8 keeps linear speculation")
        self.draft_model = draft_model
        self.tree_spec = tree_spec
        self.adaptive_spec = adaptive_spec
        self.injector = injector or FaultInjector()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.stats = ServingStats(registry=self.tracer.registry)
        # both quantization levers are independent: weight-only int8 is
        # detected from the tree (dequant-fused dense/logits kernels),
        # kv_dtype=int8 from the cache (the cores branch on the scale
        # leaves the int8 pool carries) — the host side (PagePool, COW,
        # block tables) is dtype-agnostic throughout
        quantized = is_quantized_tree(params)
        self.cache = init_paged_cache(cfg, num_slots, max_len, num_pages,
                                      page_size, cache_dtype)
        self.pool = PagePool(num_pages, page_size, free_order,
                             injector=self.injector,
                             host_tier=host_tier)
        # host spill tier (see serving.paging): the pool's eviction
        # sweep calls _spill_page for sole-registry-owned pages; a
        # prefix-registry hit at admission promotes records back via
        # _promote_chain. The staged admission charge reprices the
        # monolithic prefill's sequential depth at (suffix depth +
        # promote ticks) — pure clock accounting, streams untouched.
        self.host_tier = host_tier
        self.promote_ticks_per_page = float(promote_ticks_per_page)
        self._admit_charge: Optional[int] = None
        self._admit_extra = 0
        if host_tier is not None:
            quant = jnp.dtype(cache_dtype) == jnp.int8
            name = jnp.dtype(cache_dtype).name
            if name not in SPILL_DTYPE_TAGS:
                raise ValueError(
                    f"cache dtype {name!r} has no spill wire tag; the "
                    f"host tier speaks {sorted(SPILL_DTYPE_TAGS)}")
            self._spill_geometry = (cfg.num_layers, cfg.num_heads,
                                    page_size, cfg.head_dim,
                                    SPILL_DTYPE_TAGS[name])
            self._tier_extract = (make_extract_pages_quant_fn()
                                  if quant else make_extract_pages_fn())
            self._tier_insert = (make_insert_pages_quant_fn()
                                 if quant else make_insert_pages_fn())
            self.pool.spill_hook = self._spill_page
        self._slot_pages: List[List[int]] = [[] for _ in range(num_slots)]
        # slots mid-chunked-prefill: their device block-table row is
        # parked on scratch (see begin_chunk_prefill), so the audit
        # must not expect it to mirror _slot_pages yet
        self._prefill_parked: set = set()
        self._prefill = make_paged_prefill_fn(cfg, compute_dtype,
                                              quantized)
        self._chunk_prefill = make_paged_chunk_prefill_fn(
            cfg, compute_dtype, quantized)
        self._decode = make_paged_decode_fn(cfg, compute_dtype, quantized)
        self._verify = make_paged_verify_fn(cfg, compute_dtype, quantized)
        self._tree_verify = make_paged_tree_verify_fn(
            cfg, compute_dtype, quantized) if tree_spec else None
        self._copy = make_copy_page_fn()
        self._init_samplers()

    def page_demand(self, total_len: int) -> None:
        need = max_pages_per_slot(min(total_len, self.max_len),
                                  self.page_size)
        usable = self.pool.num_pages - RESERVED_PAGES
        if need > usable:
            raise ValueError(
                f"request needs up to {need} pages but the pool only "
                f"has {usable} usable pages")

    def prefill(self, slot: int, prompt: Sequence[int]) -> jax.Array:
        """Admit ``prompt`` into ``slot``: share the longest cached
        prefix run, allocate private pages for the rest, prefill —
        writing ONLY the private pages (shared ones are redirected to
        scratch; their rows were produced by the original request and
        are reused verbatim) — and register the chain for future
        requests. Raises :class:`PoolExhausted` when the pool can't
        cover the prompt even after LRU eviction, and
        :class:`InjectedFault` under an armed ``prefill_exec`` site;
        BOTH release every transient page reference first, so the
        caller can simply requeue (``check_invariants`` audits this
        rollback). Raises ``ValueError`` for a prompt beyond
        ``max_len`` BEFORE touching the pool (the scheduler's submit
        check normally screens this, but the engine must not leak page
        references when driven directly)."""
        toks = [int(t) for t in prompt]
        if len(toks) > self.max_len:
            raise ValueError(
                f"prompt length {len(toks)} exceeds cache max_len "
                f"{self.max_len}")
        n_pages = max_pages_per_slot(len(toks), self.page_size)
        keys = prefix_page_keys(toks, self.page_size)
        shared = self.pool.match_prefix(keys) if self.prefix_sharing \
            else []
        promoted: List[int] = []
        promote_ticks = 0
        if self.host_tier is not None and self.prefix_sharing \
                and len(shared) < n_pages:
            promoted, promote_ticks = self._promote_chain(
                keys, len(shared))
        covered = len(shared) + len(promoted)
        private: List[int] = []
        for _ in range(n_pages - covered):
            p = self.pool.alloc()
            if p is None:
                for q in shared + promoted + private:
                    self.pool.release(q)
                raise PoolExhausted(
                    f"prompt needs {n_pages} pages; pool has "
                    f"{self.pool.num_free} free and nothing left to "
                    "evict", need=n_pages, free=self.pool.num_free,
                    cached=self.pool.num_cached)
            private.append(p)
        pages = shared + promoted + private
        fired, _ = self.injector.draw("prefill_exec")
        if fired:
            for q in pages:
                self.pool.release(q)
            raise InjectedFault("prefill_exec",
                                self.injector.calls("prefill_exec") - 1)
        self._slot_pages[slot] = list(pages)

        row = np.full((self.max_pages,), NULL_PAGE, np.int32)
        row[:n_pages] = pages
        # a host-tier engine skips fully-covered leading pages the way
        # chunked prefill does: the suffix runs as one final "chunk"
        # whose attention gathers the covered pages through the real
        # row — that sequential-depth saving is the promotion's whole
        # TTFT win. The int8 pool keeps the monolithic forward (the
        # chunk core refuses it); its covered pages are still reused
        # verbatim by decode, exactly like HBM-shared ones.
        skip = 0
        if self.host_tier is not None and covered \
                and self.cache.k_scale is None:
            skip = min(covered, max(n_pages - 1, 0))
        start = skip * self.page_size
        trc = self.tracer
        if trc.enabled:
            trc.begin("prefill")
        if skip:
            ids = np.asarray(toks[start:], np.int32)[None, :]
            ids, mask = pad_to_bucket(ids, ids.shape[1],
                                      buckets=self.buckets)
            write = np.full((ids.shape[1] // self.page_size,),
                            SCRATCH_PAGE, np.int32)
            for j in range(write.shape[0]):
                ai = skip + j
                if covered <= ai < n_pages:
                    write[j] = pages[ai]
            self.cache, logits = self._chunk_prefill(
                self.params, self.cache, ids, mask, jnp.int32(slot),
                jnp.int32(start), jnp.asarray(write), jnp.asarray(row),
                jnp.asarray(row))
        else:
            ids = np.asarray(toks, np.int32)[None, :]
            ids, mask = pad_to_bucket(ids, ids.shape[1],
                                      buckets=self.buckets)
            write = np.full((ids.shape[1] // self.page_size,),
                            SCRATCH_PAGE, np.int32)
            write[covered:n_pages] = private
            self.cache, logits = self._prefill(
                self.params, self.cache, ids, mask, jnp.int32(slot),
                jnp.asarray(write), jnp.asarray(row))
        if trc.enabled:
            trc.end("prefill", slot=slot, bucket=int(ids.shape[1]),
                    shared_pages=covered)
        if self.prefix_sharing:
            self.pool.register_prefix(keys, pages)
        if self.host_tier is not None:
            # reprice the admission: the forward only ran the suffix's
            # depth, and each promotion costs transfer ticks (the same
            # pop_admit_charge handshake the disagg handoff uses)
            self._admit_charge = (len(toks) - start) + promote_ticks
        return logits

    # -- chunked prefill ------------------------------------------------

    def begin_chunk_prefill(self, slot: int,
                            prompt: Sequence[int]) -> Dict:
        """Stage a chunked prefill: share the longest cached prefix
        run and allocate the private pages UP FRONT (all-or-nothing,
        with the same rollback as :meth:`prefill`), but run no forward
        yet. While chunks are in flight the slot's device block-table
        row stays parked on scratch: co-tenant decode/verify ticks
        write a garbage row for EVERY slot, and a mid-prefill slot's
        write target could be a SHARED page — parking routes those
        writes to the scratch page until the final chunk atomically
        installs the real row. Fully-shared leading pages are skipped
        (their rows are the original owner's, reused verbatim); the
        last page always runs so the final chunk yields the
        first-token logits."""
        toks = [int(t) for t in prompt]
        if len(toks) > self.max_len:
            raise ValueError(
                f"prompt length {len(toks)} exceeds cache max_len "
                f"{self.max_len}")
        n_pages = max_pages_per_slot(len(toks), self.page_size)
        keys = prefix_page_keys(toks, self.page_size)
        shared = self.pool.match_prefix(keys) if self.prefix_sharing \
            else []
        promoted: List[int] = []
        promote_ticks = 0
        if self.host_tier is not None and self.prefix_sharing \
                and len(shared) < n_pages:
            promoted, promote_ticks = self._promote_chain(
                keys, len(shared))
        covered = len(shared) + len(promoted)
        private: List[int] = []
        for _ in range(n_pages - covered):
            p = self.pool.alloc()
            if p is None:
                for q in shared + promoted + private:
                    self.pool.release(q)
                raise PoolExhausted(
                    f"prompt needs {n_pages} pages; pool has "
                    f"{self.pool.num_free} free and nothing left to "
                    "evict", need=n_pages, free=self.pool.num_free,
                    cached=self.pool.num_cached)
            private.append(p)
        pages = shared + promoted + private
        self._slot_pages[slot] = list(pages)
        self._prefill_parked.add(slot)
        if promote_ticks:
            # promotions cost transfer ticks; chunked admission charges
            # per chunk, so the extra rides the next pop (additively —
            # several staged prefills may promote before one pops)
            self._admit_extra += promote_ticks
        row = np.full((self.max_pages,), NULL_PAGE, np.int32)
        row[:n_pages] = pages
        skip = min(covered, max(n_pages - 1, 0))
        return {"keys": keys, "pages": pages, "shared": covered,
                "n_pages": n_pages, "row": row,
                "start": skip * self.page_size}

    def chunk_prefill(self, slot: int, chunk: Sequence[int], pos: int,
                      state: Dict, bucket: int,
                      final: bool) -> jax.Array:
        """Run one page-aligned prompt chunk for ``slot``: the chunk's
        tokens write whole private pages (shared and beyond-prompt
        pages redirect to scratch) while attention gathers through the
        real NULL-padded row — earlier chunks' pages AND the shared
        prefix are visible, later positions are masked out. The final
        chunk additionally installs the real block-table row (ending
        the scratch parking, see :meth:`begin_chunk_prefill`). An
        armed ``chunk_prefill_exec`` site raises
        :class:`InjectedFault` before touching the cache — the caller
        frees the slot, which releases every staged page."""
        fired, _ = self.injector.draw("chunk_prefill_exec")
        if fired:
            raise InjectedFault(
                "chunk_prefill_exec",
                self.injector.calls("chunk_prefill_exec") - 1)
        ids = np.asarray(chunk, np.int32)[None, :]
        ids, mask = pad_to_bucket(ids, ids.shape[1], buckets=(bucket,))
        first_page = pos // self.page_size
        write = np.full((bucket // self.page_size,), SCRATCH_PAGE,
                        np.int32)
        for j in range(write.shape[0]):
            ai = first_page + j
            if state["shared"] <= ai < state["n_pages"]:
                write[j] = state["pages"][ai]
        if final:
            store = state["row"]
            self._prefill_parked.discard(slot)
        else:
            store = np.full((self.max_pages,), SCRATCH_PAGE, np.int32)
        trc = self.tracer
        if trc.enabled:
            trc.begin("chunk_prefill")
        self.cache, logits = self._chunk_prefill(
            self.params, self.cache, ids, mask, jnp.int32(slot),
            jnp.int32(pos), jnp.asarray(write),
            jnp.asarray(state["row"]), jnp.asarray(store))
        if trc.enabled:
            trc.end("chunk_prefill", slot=slot, pos=pos, bucket=bucket,
                    final=final, shared_pages=state["shared"])
        return logits

    def finish_chunk_prefill(self, slot: int, state: Dict) -> None:
        """Register the completed prompt's prefix chain for future
        admissions — the same registration monolithic prefill does."""
        if self.prefix_sharing:
            self.pool.register_prefix(state["keys"], state["pages"])

    def pop_admit_charge(self, default: int) -> int:
        """Pop the staged admission charge (see base class). A
        host-tier prefill stages an ABSOLUTE charge (suffix depth +
        promote ticks); chunked admissions accumulate promote ticks
        ADDITIVELY on top of the per-chunk default."""
        charge, self._admit_charge = self._admit_charge, None
        extra, self._admit_extra = self._admit_extra, 0
        return (default if charge is None else charge) + extra

    def _spill_page(self, key: bytes, page: int) -> None:
        """Pool eviction hook: copy ``page`` (sole-owned by the prefix
        registry, so its content is pristine — COW guarantees no slot
        ever appended to it) out to the host tier under its chain key.
        A fired ``host_spill`` site drops the spill on the floor: the
        prefix simply leaves both tiers and a later admission
        re-prefills it — graceful, nothing retried."""
        fired, _ = self.injector.draw("host_spill")
        if fired:
            self.stats.host_spill_failures += 1
            if self.tracer.enabled:
                self.tracer.instant("host_spill", page=page, ok=False)
            return
        ids = jnp.asarray([page], jnp.int32)
        tiles = self._tier_extract(self.cache, ids)
        if len(tiles) == 4:
            k, v, ks, vs = (np.asarray(t) for t in tiles)
        else:
            k, v = (np.asarray(t) for t in tiles)
            ks = vs = None
        header = encode_spill_header(key, *self._spill_geometry)
        rec = SpillRecord(header, k, v, ks, vs,
                          spill_checksum(header, k, v, ks, vs))
        if self.host_tier.put(key, rec):
            self.stats.host_spills += 1
            self.stats.host_spill_bytes += rec.nbytes
            if self.tracer.enabled:
                self.tracer.instant("host_spill", page=page,
                                    bytes=rec.nbytes)

    def _verify_spill(self, key: bytes, rec: SpillRecord) -> None:
        """Checksum + header verification for a promoted record — the
        same trust boundary the cross-replica page handoff enforces.
        Raises :class:`PromoteFailed` on any mismatch."""
        digest = spill_checksum(rec.header, rec.k, rec.v,
                                rec.k_scale, rec.v_scale)
        if digest != rec.digest:
            raise PromoteFailed(
                f"spill record checksum mismatch for {key.hex()[:16]}",
                key=key.hex())
        hdr = decode_spill_header(rec.header)
        if hdr["key"] != key:
            raise PromoteFailed(
                f"spill header bound to {hdr['key'].hex()[:16]} but "
                f"registered under {key.hex()[:16]}", key=key.hex())
        geom = (hdr["num_layers"], hdr["num_heads"], hdr["page_size"],
                hdr["head_dim"], hdr["dtype_tag"])
        if hdr["version"] != PAGE_KEY_VERSION \
                or geom != self._spill_geometry:
            raise PromoteFailed(
                f"spill geometry {hdr} does not match this engine",
                key=key.hex())

    def _promote_chain(self, keys: List[bytes],
                       start: int) -> Tuple[List[int], int]:
        """Extend an HBM prefix match by promoting consecutive chain
        links from the host tier: for each key past the HBM-shared run,
        verify the registry record, allocate an HBM page and batch-copy
        the payload back in. The chain breaks at the first miss, fired
        ``host_promote`` site, verification failure (the stale record
        is dropped), or pool exhaustion — pages promoted so far are
        kept and the remainder of the prompt re-prefills. Returns
        ``(pages, ticks)``; the caller owns one reference per page and
        must charge ``ticks`` on the work clock."""
        pages: List[int] = []
        records: List[SpillRecord] = []
        failed: Optional[PromoteFailed] = None
        for key in keys[start:]:
            rec = self.host_tier.get(key)
            if rec is None:
                break
            fired, _ = self.injector.draw("host_promote")
            if fired:
                failed = PromoteFailed(
                    "injected host_promote fault", key=key.hex(),
                    pages=len(pages))
                break
            try:
                self._verify_spill(key, rec)
            except PromoteFailed as e:
                self.host_tier.drop(key)
                failed = e
                break
            p = self.pool.alloc()
            if p is None:
                break
            pages.append(p)
            records.append(rec)
        if failed is not None:
            self.stats.host_promote_failures += 1
            if self.tracer.enabled:
                self.tracer.instant("host_promote", ok=False,
                                    pages=len(pages))
        if not pages:
            return [], 0
        ids = jnp.asarray(pages, jnp.int32)
        k = np.concatenate([r.k for r in records], axis=1)
        v = np.concatenate([r.v for r in records], axis=1)
        if records[0].k_scale is not None:
            ks = np.concatenate([r.k_scale for r in records], axis=1)
            vs = np.concatenate([r.v_scale for r in records], axis=1)
            self.cache = self._tier_insert(self.cache, ids, k, v, ks, vs)
        else:
            self.cache = self._tier_insert(self.cache, ids, k, v)
        ticks = max(1, int(np.ceil(
            len(pages) * self.promote_ticks_per_page)))
        nbytes = sum(r.nbytes for r in records)
        self.stats.host_promotes += len(pages)
        self.stats.host_promote_bytes += nbytes
        self.stats.host_promote_ticks += ticks
        if self.tracer.enabled:
            self.tracer.instant("host_promote", pages=len(pages),
                                bytes=nbytes, ticks=ticks)
        return pages, ticks

    def prepare_decode(self, positions: Dict[int, int],
                       n_new: int = 1) -> List[int]:
        """Before a tick writes rows ``pos .. pos + n_new - 1`` for each
        slot (``n_new = spec_k + 1`` on a verify tick): cross each page
        boundary by allocating a fresh page, and clone (COW) a shared
        page about to receive an appended row — unless the failed clone
        alloc's registry eviction left the slot sole owner, in which
        case the append proceeds in place. Pages past the committed
        length may already exist from a prior verify tick's overshoot;
        they were allocated privately then and are simply reused. A
        slot the pool genuinely cannot serve (or whose ``cow_clone``
        fault site fired) is preempted — its pages are released (often
        unblocking the rest of the batch) and the caller requeues the
        request."""
        preempted: List[int] = []
        for i, pos in sorted(positions.items()):
            pages = self._slot_pages[i]
            first = pos // self.page_size
            last = (pos + n_new - 1) // self.page_size
            for idx in range(first, last + 1):
                if idx == len(pages):                   # page boundary
                    p = self.pool.alloc()
                    if p is None:
                        self._preempt(i, preempted)
                        break
                    pages.append(p)
                    self.cache = self.cache._replace(
                        block_tables=self.cache.block_tables.at[
                            i, idx].set(p))
                elif self.pool.needs_copy(pages[idx]):  # COW
                    dst = None if self.injector.fire("cow_clone") \
                        else self.pool.alloc()
                    if dst is None:
                        # the failed alloc's LRU sweep emptied the
                        # prefix registry; if the page's only co-owner
                        # was the registry the append is now in-place
                        # legal — no copy needed. Preempting instead
                        # would livelock: re-admission recreates the
                        # exact same state (registered partial last
                        # page at refcount 2, pool at the validated
                        # worst-case fit)
                        if not self.pool.needs_copy(pages[idx]):
                            continue
                        self._preempt(i, preempted)
                        break
                    self.stats.cow_copies += 1
                    self.cache = self._copy(self.cache,
                                            jnp.int32(pages[idx]),
                                            jnp.int32(dst))
                    self.cache = self.cache._replace(
                        block_tables=self.cache.block_tables.at[
                            i, idx].set(dst))
                    self.pool.release(pages[idx])
                    pages[idx] = dst
        return preempted

    def _preempt(self, slot: int, preempted: List[int]) -> None:
        self.free_slot(slot)
        self.stats.preemptions += 1
        preempted.append(slot)

    def free_slot(self, slot: int) -> None:
        """Release the slot's page references and park its block-table
        row on scratch (a freed slot's parked decode writes must never
        land in a page the allocator may hand to someone else)."""
        for p in self._slot_pages[slot]:
            self.pool.release(p)
        self._slot_pages[slot] = []
        self._prefill_parked.discard(slot)
        self.cache = self.cache._replace(
            block_tables=self.cache.block_tables.at[slot].set(
                jnp.full((self.max_pages,), SCRATCH_PAGE, jnp.int32)))
        if self.draft_model is not None:
            self.draft_model.free_slot(slot)

    def check_invariants(self) -> bool:
        """Full pool audit: host-side refcount/free-list/registry
        accounting against the per-slot page lists
        (:meth:`PagePool.check_invariants`), then the device block
        tables against those same lists
        (:func:`~apex_tpu.serving.cache.audit_block_tables`). Raises
        :class:`~apex_tpu.serving.health.PoolInvariantError`."""
        self.pool.check_invariants(self._slot_pages)
        # mid-chunked-prefill slots hold pages but park their device
        # row on scratch until the final chunk installs it — audit
        # those rows as empty (all scratch/null) instead
        expect = [[] if i in self._prefill_parked else p
                  for i, p in enumerate(self._slot_pages)]
        audit_block_tables(self.cache.block_tables, expect)
        return True

    def pool_snapshot(self) -> Dict:
        snap = self.pool.snapshot()
        snap["slot_pages"] = [list(p) for p in self._slot_pages]
        return snap

    def pool_gauges(self) -> Dict[str, float]:
        gauges = {"free": self.pool.num_free,
                  "cached": self.pool.num_cached,
                  "occupancy": self.pool.occupancy}
        if self.host_tier is not None:
            stats = self.pool.stats()
            gauges["hbm_used"] = stats["hbm_used"]
            gauges["host_pages"] = stats["host_pages"]
            gauges["host_bytes"] = stats["host_bytes"]
            gauges["host_hit_rate"] = stats["host_hit_rate"]
        return gauges


class ContinuousBatchingScheduler:
    """FIFO → fixed slots → batched decode ticks, with the
    graceful-degradation layer (see module doc): typed outcomes in
    ``self.outcomes``, shared ``self.stats`` counters, per-request
    retry budgets, deterministic deadlines, bounded admission, a
    progress watchdog, and an optional per-tick invariant audit."""

    def __init__(self, engine: DecodeEngine, eos_id: int, *,
                 max_retries: int = 3, max_queue: Optional[int] = None,
                 watchdog_limit: int = 64, audit: bool = False,
                 chunk_tokens: Optional[int] = None,
                 tick_token_budget: Optional[int] = None,
                 tenancy=None, streams=None):
        self.engine = engine
        self.eos_id = eos_id
        self.max_retries = max_retries
        self.max_queue = max_queue
        self.watchdog_limit = watchdog_limit
        self.audit = audit
        # chunked prefill: split every admission's prompt forward into
        # chunk_tokens-sized pieces run BETWEEN decode ticks under a
        # per-tick token budget (see _prefill_phase). None keeps the
        # classic monolithic admission prefill.
        if chunk_tokens is not None:
            chunk_tokens = int(chunk_tokens)
            if chunk_tokens < 1:
                raise ValueError(f"chunk_tokens must be >= 1, got "
                                 f"{chunk_tokens}")
            if engine.max_len % chunk_tokens:
                raise ValueError(
                    f"chunk_tokens {chunk_tokens} must divide the "
                    f"cache max_len {engine.max_len} (chunk starts "
                    "must never overrun the cache row)")
            if engine.paged and chunk_tokens % engine.page_size:
                raise ValueError(
                    f"paged chunks write whole pages: chunk_tokens "
                    f"{chunk_tokens} is not a multiple of page_size "
                    f"{engine.page_size}")
            if getattr(engine.cache, "k_scale", None) is not None:
                raise ValueError(
                    "chunked prefill is not offered over the int8 "
                    "page pool: incremental chunk writes would "
                    "re-round committed history at chunk-dependent "
                    "scales; kv8 keeps monolithic prefill")
        self.chunk_tokens = chunk_tokens
        if tick_token_budget is not None:
            tick_token_budget = int(tick_token_budget)
            if tick_token_budget < 1:
                raise ValueError(f"tick_token_budget must be >= 1, "
                                 f"got {tick_token_budget}")
        elif chunk_tokens is not None:
            # default: every decode slot's token plus one prefill chunk
            tick_token_budget = engine.num_slots + chunk_tokens
        self.tick_token_budget = tick_token_budget
        self.stats = engine.stats  # one counter block per engine
        self.tracer = engine.tracer  # one tracer per engine, like stats
        self.outcomes: Dict[int, RequestOutcome] = {}
        self._queue: deque = deque()
        self._slots: List[Optional[_Slot]] = [None] * engine.num_slots
        self._next_id = 0
        self._retries: Dict[int, int] = {}
        self._submit_tick: Dict[int, int] = {}
        # tick-clock latency bookkeeping (feeds RequestOutcome.ttft/
        # total_ticks and, when tracing, the TTFT/ITL histograms)
        self._first_token_tick: Dict[int, int] = {}
        self._last_token_tick: Dict[int, int] = {}
        # ticks that ran prefill work per request (feeds
        # RequestOutcome.prefill_ticks); accumulates across retries
        self._prefill_ticks: Dict[int, int] = {}
        self._tick_no = 0
        self._tokens_emitted = 0
        # progress-watchdog state (instance-held so external drivers
        # can call step() directly, e.g. the Poisson scenario bench)
        self._stalled = 0
        self._watch_snap = None
        # (B,) base keys × (B, k1) offsets -> (B, k1, 2) per-position
        # sampling keys for verify ticks: position j of slot b folds in
        # n_generated[b] + j — the plain stream's key for that token
        self._fold_grid = jax.jit(jax.vmap(
            jax.vmap(jax.random.fold_in, (None, 0)), (0, 0)))
        self._tree_accept = jax.jit(tree_speculative_accept)
        # adaptive controller state: per-slot EWMA of the measured
        # draft acceptance rate (reset to optimistic 1.0 at admission);
        # converged-off slots get one probe draft every _probe_every
        # ticks so repetitive text can re-earn its depth
        self._accept_ewma = [1.0] * engine.num_slots
        self._probe_every = 16
        # tenancy front-end (serving.tenancy): admission selection,
        # quotas, priority preemption, per-tenant SLOs. None keeps the
        # untenanted FIFO path byte-identical. The quota ledger hangs
        # under the engine's page pool so the per-tick invariant audit
        # covers the reservation books.
        self.tenancy = tenancy
        if tenancy is not None:
            if tenancy.needs_quota and not getattr(engine, "paged", False):
                raise ValueError(
                    "tenant page quotas price KV pages: they need a "
                    "paged engine (drop the quotas or use "
                    "PagedDecodeEngine)")
            pool = getattr(engine, "pool", None)
            if pool is not None:
                pool.ledger = tenancy.ledger
        # per-token streaming (serving.streaming): streams=True builds
        # a StreamMux on the engine's injector/tracer/stats; passing a
        # StreamMux keeps the caller's sink. None disables staging.
        if streams is True:
            from apex_tpu.serving.streaming import StreamMux
            streams = StreamMux(injector=engine.injector,
                                tracer=engine.tracer, stats=engine.stats)
        self.streams = streams
        self._req_tenant: Dict[int, str] = {}
        # worst inter-token gap per request (tenancy mode only — feeds
        # the ITL SLO check at finish)
        self._max_itl: Dict[int, int] = {}

    @property
    def clock(self) -> int:
        """The scheduler's work-charged tick clock (decode-step
        equivalents): every forward advances it by the sequential
        depth it covers, so open-loop load generators can pace
        arrivals against it as a wall-time proxy."""
        return self._tick_no

    def advance_clock(self, tick: int) -> None:
        """Fast-forward an idle scheduler's clock to ``tick`` (no-op
        when already past it): load generators jump over quiet gaps
        between arrivals instead of spinning empty ticks through the
        watchdog."""
        self._tick_no = max(self._tick_no, int(tick))
        if self.tracer.enabled:
            self.tracer.set_tick(self._tick_no)

    def submit(self, request: Request,
               at_tick: Optional[int] = None) -> int:
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            self.stats.admission_rejections += 1
            raise AdmissionRejected(
                f"admission queue is at its bound ({self.max_queue}); "
                "shed load and retry after completions")
        if not len(request.prompt):
            raise ValueError("empty prompt")
        if len(request.prompt) > self.engine.max_len:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds cache "
                f"max_len {self.engine.max_len}")
        # fail fast at submit, not mid-run inside _admit: the prompt
        # must have a bucket rung and (paged) fit the pool even running
        # alone at its worst-case generated length — plus the verify
        # step's overshoot (speculative writes can land up to spec_k
        # rows past the final committed token)
        bucket_for(len(request.prompt), self.engine.buckets)
        self.engine.page_demand(
            len(request.prompt) + request.max_new_tokens
            + self.engine.spec_k)
        ten = self.tenancy
        if ten is not None:
            if not ten.has(request.tenant_id):
                raise ValueError(
                    f"unknown tenant {request.tenant_id!r}: declare it "
                    "in the TenancyPolicy before submitting under it")
            # the quota analogue of the page_demand fail-fast above: a
            # request whose worst-case reservation can NEVER fit its
            # tenant's quota is refused typed at submit, not deferred
            # forever at admission
            need = self._quota_need(request)
            if not ten.fits_quota(request.tenant_id, need):
                self.stats.quota_exhausted += 1
                raise QuotaExhausted(
                    f"request needs {need} pages worst-case but tenant "
                    f"{request.tenant_id!r} is capped at "
                    f"{ten.tenants[request.tenant_id].page_quota}",
                    tenant=request.tenant_id, need=need,
                    quota=ten.tenants[request.tenant_id].page_quota)
        rid = self._next_id
        self._next_id += 1
        self._req_tenant[rid] = request.tenant_id
        if ten is not None:
            # idle -> backlogged bookkeeping: clamps a RETURNING
            # tenant's vtime to the busy floor; a tenant with work
            # already outstanding keeps its fair-share deficit
            ten.note_enqueued(request.tenant_id)
        if self.streams is not None:
            self.streams.open(rid, request.tenant_id)
        # ``at_tick`` backdates the arrival for open-loop drivers: a
        # charged forward can jump the clock PAST a request's true
        # arrival time before the driver gets to submit it, and the
        # wait spent behind that forward must still show up in TTFT
        # (and burn the deadline) — otherwise monolithic prefill hides
        # exactly the head-of-line blocking the chunked scheduler is
        # measured against
        self._submit_tick[rid] = self._tick_no if at_tick is None \
            else min(int(at_tick), self._tick_no)
        trc = self.tracer
        if trc.enabled:
            trc.instant("submitted", request_id=rid,
                        prompt_len=len(request.prompt))
        # third element: tokens already generated — empty for fresh
        # submissions, carried through preemption/quarantine requeue
        self._queue.append((rid, request, []))
        return rid

    def _slot_key(self, slot: _Slot) -> jax.Array:
        return jax.random.fold_in(
            jax.random.PRNGKey(slot.request.seed), len(slot.generated))

    # -- typed termination ------------------------------------------------

    def _finish(self, rid: int, tokens: Sequence[int], reason: str,
                error=None) -> None:
        ttft = None
        if rid in self._first_token_tick:
            ttft = (self._first_token_tick[rid]
                    - self._submit_tick.get(rid, 0))
        total = self._tick_no - self._submit_tick.get(rid, self._tick_no)
        trc = self.tracer
        if trc.enabled:
            if error is not None:
                trc.attach(error)  # ship the flight-recorder ring
            trc.instant("finished", request_id=rid, reason=reason,
                        ok=error is None)
        tenant = self._req_tenant.get(rid, "default")
        ten = self.tenancy
        slo = None
        if ten is not None:
            # the single exit point every request passes through:
            # credit the quota reservation here and ONLY here, so the
            # ledger is leak-free by construction
            ten.credit(rid)
            ten.note_finished(tenant)
            slo = ten.slo_check(tenant, ttft, self._max_itl.get(rid))
            if slo is not None:
                self.stats.slo_violations += 1
                if trc.enabled:
                    trc.attach(slo)
                    trc.instant("slo_violation", request_id=rid,
                                tenant=tenant, metric=slo.metric,
                                observed=slo.observed, bound=slo.bound)
        if self.streams is not None:
            self.streams.finish(rid, reason)
        self.outcomes[rid] = RequestOutcome(
            tuple(int(t) for t in tokens), reason, error,
            retries=self._retries.get(rid, 0),
            ttft_ticks=ttft, total_ticks=total,
            prefill_ticks=self._prefill_ticks.get(rid),
            tenant_id=tenant, slo=slo)

    def _charge_work(self, tokens: int) -> None:
        """Advance the scheduler clock by a prefill forward's
        sequential depth. Same decode-step-equivalents rule as the
        multi-token speculative commit (a tick that commits m tokens
        counts m): a forward that advances one stream by ``tokens``
        positions costs that many ticks, so tick-clock TTFT/ITL and
        deadlines price head-of-line blocking honestly — a monolithic
        S-token prefill opens an ~S-tick gap in co-tenant streams,
        while chunked prefill bounds the gap at the tick token
        budget. Purely an accounting change: sampling keys fold in
        token counts, never ticks, so committed streams are
        untouched. The engine may reprice the charge via
        :meth:`DecodeEngine.pop_admit_charge` — a host-tier promote
        shrinks the forward to the suffix depth but adds transfer
        ticks, and the disaggregated router charges handoff ticks the
        same way."""
        tokens = self.engine.pop_admit_charge(tokens)
        if tokens > 1:
            self._tick_no += tokens - 1
            if self.tracer.enabled:
                self.tracer.set_tick(self._tick_no)

    def _note_token(self, rid: int, slot: int) -> None:
        """Per-committed-token tick-clock bookkeeping. The first token
        stamps TTFT; later ones stamp the inter-token gap (tokens
        within one multi-token speculative commit share a tick, so
        their gap records as 0 — honest SLO accounting)."""
        tick = self._tick_no
        trc = self.tracer
        ten = self.tenancy
        if rid not in self._first_token_tick:
            self._first_token_tick[rid] = tick
            if trc.enabled:
                trc.instant("first_token", request_id=rid, slot=slot)
                trc.observe_ttft(tick - self._submit_tick.get(rid, tick))
                if ten is not None:
                    trc.observe_tenant_ttft(
                        self._req_tenant.get(rid, "default"),
                        tick - self._submit_tick.get(rid, tick))
        else:
            gap = tick - self._last_token_tick[rid]
            if ten is not None and gap > self._max_itl.get(rid, 0):
                self._max_itl[rid] = gap
            if trc.enabled:
                trc.observe_itl(gap)
                if ten is not None:
                    trc.observe_tenant_itl(
                        self._req_tenant.get(rid, "default"), gap)
        self._last_token_tick[rid] = tick
        if ten is not None:
            # stride clock: one committed token advances the tenant's
            # virtual time by 1 / weight
            ten.charge_tokens(self._req_tenant.get(rid, "default"), 1)
        if self.streams is not None:
            # stage for the end-of-tick flush — delivery is host-side
            # fan-out, the committed stream is already in the slot
            self.streams.stage(rid, self._slots[slot].generated[-1])

    def _charge_retry(self, rid: int) -> bool:
        """Consume one unit of ``rid``'s retry budget; True when the
        budget is now exhausted (the caller must terminate it)."""
        trc = self.tracer
        if trc.enabled:
            trc.instant("retried", request_id=rid)
        self.stats.retries += 1
        n = self._retries.get(rid, 0) + 1
        self._retries[rid] = n
        return n > self.max_retries

    def _budget_error(self, rid: int, cause) -> RetryBudgetExhausted:
        return RetryBudgetExhausted(
            f"request {rid}: retry budget ({self.max_retries}) "
            f"exhausted; last fault: {cause}", request_id=rid,
            retries=self._retries.get(rid, 0))

    def _quarantine(self, i: int, err: NonFiniteLogits) -> None:
        """Free a slot whose tick output was corrupt; retry the request
        from its committed tokens (requeue at the FRONT — the resumed
        stream is bit-identical to the uncontended one) or, with the
        budget gone, terminate it typed."""
        s = self._slots[i]
        trc = self.tracer
        if trc.enabled:
            trc.instant("quarantined", request_id=s.request_id, slot=i,
                        cause=str(err))
        self._slots[i] = None
        self.engine.free_slot(i)
        rid = s.request_id
        if self._charge_retry(rid):
            self._finish(rid, s.generated, "retry_budget",
                         self._budget_error(rid, err))
        else:
            self._queue.appendleft((rid, s.request, list(s.generated)))

    def _expire_deadlines(self) -> None:
        def expired(req: Request, rid: int) -> bool:
            return (req.deadline_ticks is not None
                    and self._tick_no - self._submit_tick.get(rid, 0)
                    >= req.deadline_ticks)

        if any(expired(req, rid) for rid, req, _ in self._queue):
            keep: deque = deque()
            for rid, req, resume in self._queue:
                if expired(req, rid):
                    self.stats.deadline_expired += 1
                    self._finish(rid, resume, "deadline",
                                 DeadlineExceeded(
                                     f"request {rid}: queued past its "
                                     f"{req.deadline_ticks}-tick "
                                     "deadline"))
                else:
                    keep.append((rid, req, resume))
            self._queue = keep
        for i, s in enumerate(self._slots):
            if s is not None and expired(s.request, s.request_id):
                self.stats.deadline_expired += 1
                self._slots[i] = None
                self.engine.free_slot(i)
                self._finish(s.request_id, s.generated, "deadline",
                             DeadlineExceeded(
                                 f"request {s.request_id}: exceeded its "
                                 f"{s.request.deadline_ticks}-tick "
                                 "deadline mid-decode"))

    # -- tenancy: selection, quotas, priority preemption ------------------

    def _quota_need(self, req: Request) -> int:
        """Worst-case page reservation for one request: the pages that
        hold prompt + ``max_new_tokens`` + the verify step's spec_k
        overshoot, capped at the cache row — the same sizing the
        submit-time ``page_demand`` fail-fast prices. 0 on dense
        engines (quotas price KV pages; dense caches are per-slot)."""
        eng = self.engine
        page_size = getattr(eng, "page_size", None)
        if page_size is None:
            return 0
        total = min(len(req.prompt) + req.max_new_tokens + eng.spec_k,
                    eng.max_len)
        return max_pages_per_slot(total, page_size)

    def _promote_next(self) -> bool:
        """Tenancy admission selection: rotate the best queued
        candidate to the queue FRONT (the head-pop admission logic
        then runs unchanged), preserving relative order among the
        rest — FIFO within a tenant. The key is the policy's
        ``(chargeable, priority desc, vtime asc, tenant id)`` with
        queue position appended, so ties resolve deterministically.
        Returns False when every candidate's tenant is quota-blocked:
        admission defers until a completion credits pages back.
        Untenanted schedulers keep strict FIFO (always True)."""
        ten = self.tenancy
        if ten is None:
            return True
        best = None
        best_key = None
        for idx, (rid, req, _resume) in enumerate(self._queue):
            chargeable = ten.can_admit(rid, req.tenant_id,
                                       self._quota_need(req))
            k = ten.selection_key(req.tenant_id, chargeable) + (idx,)
            if best_key is None or k < best_key:
                best_key, best = k, idx
        if best_key[0] == 1:  # even the best candidate is quota-blocked
            self.stats.quota_deferrals += 1
            return False
        if best:
            q = self._queue
            items = list(q)
            sel = items.pop(best)
            q.clear()
            q.append(sel)
            q.extend(items)
        return True

    def _charge_head_admission(self, rid: int, req: Request) -> None:
        """Reserve the queue head's quota pages (idempotent — a
        preempted request being re-admitted already holds its
        reservation) and stamp the admitting tenant on the engine for
        the router's observability/affinity threading. Only called
        after :meth:`_promote_next` returned True, so the charge
        cannot fail."""
        ten = self.tenancy
        if ten is None:
            return
        ten.charge_admission(rid, req.tenant_id, self._quota_need(req))
        self.engine.admission_tenant = req.tenant_id

    def _preempt_for_priority(self) -> None:
        """A strictly-higher-priority waiting tenant may requeue ONE
        resident lower-priority slot per tick — through the exact
        requeue-resume path pool pressure uses (committed tokens ride
        along, re-prefilled on re-admission, streams bit-identical),
        with no retry charged: priority preemption is a capacity
        decision, not a fault. One victim per tick bounds the churn;
        a quota-blocked burst preempts nobody (the freed slot could
        not admit it anyway)."""
        ten = self.tenancy
        if ten is None or not self._queue:
            return
        if any(s is None for s in self._slots):
            return  # a free slot serves the burst without eviction
        best = None
        best_key = None
        for idx, (rid, req, _resume) in enumerate(self._queue):
            chargeable = ten.can_admit(rid, req.tenant_id,
                                       self._quota_need(req))
            k = ten.selection_key(req.tenant_id, chargeable) + (idx,)
            if best_key is None or k < best_key:
                best_key, best = k, req
        if best_key[0] == 1:
            return  # quota-blocked: a preemption could not admit it
        wait_prio = ten.priority(best.tenant_id)
        victim = None
        victim_key = None
        for i, s in enumerate(self._slots):
            rung = ten.priority(s.request.tenant_id)
            if rung >= wait_prio:
                continue  # only STRICTLY lower rungs are preemptible
            k = (rung, -s.request_id)  # lowest rung, then newest work
            if victim_key is None or k < victim_key:
                victim_key, victim = k, i
        if victim is None:
            return
        s = self._slots[victim]
        self.stats.tenant_preemptions += 1
        if self.tracer.enabled:
            self.tracer.instant(
                "preempted", request_id=s.request_id, slot=victim,
                cause="tenant_priority",
                tenant=self._req_tenant.get(s.request_id, "default"))
        self._queue.appendleft((s.request_id, s.request,
                                list(s.generated)))
        self._slots[victim] = None
        self.engine.free_slot(victim)

    # -- admission / decode ticks -----------------------------------------

    def _admit(self) -> None:
        if self.tenancy is not None:
            self._preempt_for_priority()
        if self.chunk_tokens is not None:
            self._admit_chunked()
            return
        eng = self.engine
        for i in range(eng.num_slots):
            if self._slots[i] is not None or not self._queue:
                continue
            if not self._promote_next():
                break
            rid, req, resume = self._queue[0]
            self._charge_head_admission(rid, req)
            # a preempted request resumes by re-prefilling everything
            # it had produced EXCEPT its last sampled token, which the
            # next decode tick feeds (the normal teacher-forcing shape)
            tokens = tuple(req.prompt) + tuple(resume[:-1])
            try:
                logits = eng.prefill(i, tokens)
            except PoolExhausted as e:
                # out of pages: keep FIFO order, wait for evictions —
                # unless the pool can't serve the head even with every
                # slot free and no fault injection to blame, which is a
                # submit-validation bug worth surfacing typed
                self.stats.pool_exhausted += 1
                if all(s is None for s in self._slots) \
                        and not eng.injector.armed:
                    err = PoolExhausted(
                        "page pool cannot admit the queue head even "
                        f"with every slot free (request {rid}) — "
                        "submit-time validation should have rejected "
                        "it", need=e.need, free=e.free,
                        cached=e.cached)
                    if self.tracer.enabled:
                        self.tracer.attach(err)
                    raise err from e
                break
            except InjectedFault as e:
                # transient exec failure; the engine rolled back its
                # page references, the request stays at the queue front
                if self._charge_retry(rid):
                    self._queue.popleft()
                    self._finish(rid, resume, "retry_budget",
                                 self._budget_error(rid, e))
                    continue
                break
            self._prefill_ticks[rid] = \
                self._prefill_ticks.get(rid, 0) + 1
            self._charge_work(len(tokens))
            first_tok = None
            if not resume:
                # the FIRST generated token comes from the prefill
                # logits; on resume it already exists. Both gates below
                # are the always-on production checks the decode tick
                # also applies.
                if not bool(np.asarray(eng.finite(logits)).all()):
                    self.stats.nan_events += 1
                    if self._fail_admission(i, rid, NonFiniteLogits(
                            f"request {rid}: non-finite prefill "
                            "logits")):
                        continue
                    break
                key = jax.random.fold_in(jax.random.PRNGKey(req.seed), 0)
                first_tok = int(eng.sample(
                    logits, key[None, :],
                    jnp.asarray([req.temperature], jnp.float32))[0])
                if not 0 <= first_tok < eng.cfg.vocab_size:
                    self.stats.bad_samples += 1
                    if self._fail_admission(i, rid, NonFiniteLogits(
                            f"request {rid}: first sampled token "
                            f"{first_tok} outside "
                            f"[0, {eng.cfg.vocab_size})")):
                        continue
                    break
            self._queue.popleft()
            slot = _Slot(rid, req, len(req.prompt), list(resume),
                         len(tokens))
            trc = self.tracer
            if trc.enabled:
                trc.instant("admitted", request_id=rid, slot=i,
                            resumed=bool(resume))
            if first_tok is not None:
                slot.generated.append(first_tok)
                self._tokens_emitted += 1
            self._slots[i] = slot
            if first_tok is not None:
                self._note_token(rid, i)
            self._accept_ewma[i] = 1.0
            self._maybe_evict(i)

    def _fail_admission(self, i: int, rid: int, err) -> bool:
        """Roll back a corrupt admission (slot freed, retry charged).
        True when the request terminated (budget gone) — the caller
        moves on; False when it should back off and retry later."""
        self.engine.free_slot(i)
        if self._charge_retry(rid):
            self._queue.popleft()
            # only fresh admissions sample a first token, so there are
            # no committed tokens to carry into the outcome
            self._finish(rid, (), "retry_budget",
                         self._budget_error(rid, err))
            return True
        return False

    def _admit_chunked(self) -> None:
        """Chunked admission: claim a free slot and STAGE the prefill
        (pages allocated, no forward run) — the chunks execute in
        :meth:`_prefill_phase` under the tick token budget, so a long
        prompt never monopolizes a tick that co-tenant decodes need."""
        eng = self.engine
        trc = self.tracer
        for i in range(eng.num_slots):
            if self._slots[i] is not None or not self._queue:
                continue
            if not self._promote_next():
                break
            rid, req, resume = self._queue[0]
            self._charge_head_admission(rid, req)
            tokens = tuple(req.prompt) + tuple(resume[:-1])
            try:
                state = eng.begin_chunk_prefill(i, tokens)
            except PoolExhausted as e:
                self.stats.pool_exhausted += 1
                if all(s is None for s in self._slots) \
                        and not eng.injector.armed:
                    err = PoolExhausted(
                        "page pool cannot admit the queue head even "
                        f"with every slot free (request {rid}) — "
                        "submit-time validation should have rejected "
                        "it", need=e.need, free=e.free,
                        cached=e.cached)
                    if trc.enabled:
                        trc.attach(err)
                    raise err from e
                break
            self._queue.popleft()
            slot = _Slot(rid, req, len(req.prompt), list(resume),
                         len(tokens))
            slot.prefill = _PrefillProgress(
                tokens=tokens, next=int(state.get("start", 0)),
                state=state)
            if trc.enabled:
                trc.instant("admitted", request_id=rid, slot=i,
                            resumed=bool(resume), chunked=True)
            self._slots[i] = slot
            self._accept_ewma[i] = 1.0

    def _decoding(self, s: Optional[_Slot]) -> bool:
        """A slot the decode path may touch: occupied AND past its
        (possibly in-flight chunked) prefill."""
        return s is not None and s.prefill is None

    def _fail_prefill(self, i: int, err) -> None:
        """A chunk faulted or the completed prefill's first token was
        corrupt: free the slot (releasing every staged page), charge
        the retry budget, and requeue at the FRONT with any committed
        progress — the retried prefill restarts from the prompt start,
        so the recovered stream stays bit-identical."""
        s = self._slots[i]
        self._slots[i] = None
        self.engine.free_slot(i)
        rid = s.request_id
        if self._charge_retry(rid):
            self._finish(rid, s.generated, "retry_budget",
                         self._budget_error(rid, err))
        else:
            self._queue.appendleft((rid, s.request, list(s.generated)))

    def _finish_prefill(self, i: int, logits) -> None:
        """The final chunk just ran: install the slot into the decode
        set, sampling the first token from the chunk logits with the
        SAME gates (finiteness, vocab range) and the same key —
        ``fold_in(seed, 0)`` — the monolithic path uses."""
        eng = self.engine
        s = self._slots[i]
        rid = s.request_id
        eng.finish_chunk_prefill(i, s.prefill.state)
        s.prefill = None
        if not s.generated:
            if not bool(np.asarray(eng.finite(logits)).all()):
                self.stats.nan_events += 1
                self._fail_prefill(i, NonFiniteLogits(
                    f"request {rid}: non-finite prefill logits"))
                return
            key = jax.random.fold_in(
                jax.random.PRNGKey(s.request.seed), 0)
            first_tok = int(eng.sample(
                logits, key[None, :],
                jnp.asarray([s.request.temperature], jnp.float32))[0])
            if not 0 <= first_tok < eng.cfg.vocab_size:
                self.stats.bad_samples += 1
                self._fail_prefill(i, NonFiniteLogits(
                    f"request {rid}: first sampled token {first_tok} "
                    f"outside [0, {eng.cfg.vocab_size})"))
                return
            s.generated.append(first_tok)
            self._tokens_emitted += 1
            self._note_token(rid, i)
        self._maybe_evict(i)

    def _prefill_phase(self, spent: int) -> None:
        """Run prompt chunks with whatever token budget the decode
        phase left over (always at least one chunk — a saturated decode
        batch must not starve prefill, or TTFT would be unbounded).
        Slots are ordered earliest-deadline-first with request id as
        the deterministic tiebreak, then round-robined one chunk at a
        time — fair share across concurrent prefills. Tenancy
        generalizes the ordering: priority rung first, then the
        tenant's fair-share vtime, then the EDF + id key — and every
        chunk's tokens advance the tenant's stride clock, so prefill
        work is priced against the share exactly like decode. Tenancy
        also THROTTLES: a tenant whose vtime has run more than one
        chunk-stride past the busy floor (the minimum vtime among
        resident tenants) has spent its share this interval, and its
        chunks defer until the floor catches up — so a flood tenant's
        prompt ingest converges to its weight ratio instead of
        consuming the whole leftover budget every tick. The floor
        tenant itself always qualifies, so a tick with prefill work
        and no decode can never go progress-free (watchdog-safe)."""
        if not any(s is not None and s.prefill is not None
                   for s in self._slots):
            return
        eng = self.engine
        budget = max(self.tick_token_budget - spent, 0)
        n_chunks = max(budget // self.chunk_tokens, 1)

        def key(i):
            s = self._slots[i]
            dl = s.request.deadline_ticks
            abs_dl = (self._submit_tick.get(s.request_id, 0) + dl
                      if dl is not None else float("inf"))
            ten = self.tenancy
            if ten is not None:
                t = s.request.tenant_id
                return (-ten.priority(t), ten.vtime(t), abs_dl,
                        s.request_id)
            return (abs_dl, s.request_id)

        order = deque(sorted(
            (i for i, s in enumerate(self._slots)
             if s is not None and s.prefill is not None), key=key))
        ten = self.tenancy
        floor = None
        if ten is not None:
            for s in self._slots:
                if s is not None:
                    v = ten.vtime(s.request.tenant_id)
                    if floor is None or v < floor:
                        floor = v
        progressed = set()
        while n_chunks > 0 and order:
            i = order.popleft()
            s = self._slots[i]
            if ten is not None:
                t = s.request.tenant_id
                slack = self.chunk_tokens / ten.tenants[t].weight
                if ten.vtime(t) > floor + slack:
                    # over its share this interval: the chunk defers
                    # until the busy floor catches up (dropped from
                    # THIS tick's rotation only — the slot re-sorts
                    # into next tick's order)
                    self.stats.chunk_deferrals += 1
                    continue
            p = s.prefill
            n_chunks -= 1
            chunk = p.tokens[p.next:p.next + self.chunk_tokens]
            final = p.next + self.chunk_tokens >= len(p.tokens)
            try:
                logits = eng.chunk_prefill(i, chunk, p.next, p.state,
                                           self.chunk_tokens, final)
            except InjectedFault as e:
                self._fail_prefill(i, e)
                continue
            self.stats.prefill_chunks += 1
            progressed.add(s.request_id)
            self._charge_work(len(chunk))
            if self.tenancy is not None:
                self.tenancy.charge_tokens(s.request.tenant_id,
                                           len(chunk))
            if final:
                self._finish_prefill(i, logits)
            else:
                p.next += self.chunk_tokens
                order.append(i)
        for rid in sorted(progressed):
            self._prefill_ticks[rid] = \
                self._prefill_ticks.get(rid, 0) + 1

    def _maybe_evict(self, i: int) -> None:
        slot = self._slots[i]
        if slot.generated[-1] == self.eos_id:
            reason = "eos"
        elif len(slot.generated) >= slot.request.max_new_tokens:
            reason = "length"
        elif slot.prompt_len + len(slot.generated) > self.engine.max_len:
            # cache row full: the committed stream no longer fits even
            # after a tree tick's forced-chain catch-up (in plain mode
            # this reduces to the classic ``pos >= max_len``)
            reason = "cache_full"
        else:
            return
        self.stats.evictions += 1
        self._finish(slot.request_id, slot.generated, reason)
        self._slots[i] = None
        self.engine.free_slot(i)

    def _spec_ks(self, positions: Dict[int, int]) -> List[int]:
        """Per-slot draft depth for this tick. Fixed engines always ask
        for ``spec_k``; adaptive engines scale it by the slot's
        acceptance EWMA (rounding to 0 turns the slot's speculation
        off entirely), with a periodic probe draft so a stream whose
        text turns predictable again can re-earn its depth."""
        eng = self.engine
        ks = [0] * eng.num_slots
        for i in positions:
            if not eng.adaptive_spec:
                ks[i] = eng.spec_k
                continue
            k = int(round(self._accept_ewma[i] * eng.spec_k))
            if k <= 0 and self._tick_no % self._probe_every == 0:
                k = 1
            ks[i] = max(0, min(k, eng.spec_k))
        return ks

    def _histories(self, ks: List[int]) -> List[Optional[Tuple[int, ...]]]:
        return [tuple(s.request.prompt) + tuple(s.generated)
                if s is not None and ks[i] > 0 else None
                for i, s in enumerate(self._slots)]

    def _draft_all(self, ks: List[int]) -> List[List[int]]:
        """One linear draft per slot, up to ``ks[i]`` tokens deep
        (empty for free slots, depth-0 slots, and fired ``draft_exec``
        sites — drafting is best-effort, so a fault degrades to plain
        pace without charging retry budget; model-drafter engines
        degrade down the ladder in
        :meth:`DecodeEngine.draft_batch`)."""
        eng = self.engine
        hists = self._histories(ks)
        if eng.draft_model is not None:
            try:
                return eng.draft_batch(hists, ks)
            except InjectedFault:
                self.stats.draft_faults += 1
                return [[] for _ in self._slots]
        drafts: List[List[int]] = []
        for i, h in enumerate(hists):
            if h is None:
                drafts.append([])
                continue
            try:
                d = self.engine.draft(h)
            except InjectedFault:
                self.stats.draft_faults += 1
                d = []
            drafts.append([int(t) for t in d[:ks[i]]])
        return drafts

    def _draft_trees(self, ks: List[int]):
        """One draft tree per slot (``None`` for free slots, depth-0
        slots, and fault-degraded ticks). Model-drafter engines walk
        the ``draft_exec`` ladder in
        :meth:`DecodeEngine.draft_tree_batch`; n-gram engines chain
        their linear drafts as single-branch trees."""
        eng = self.engine
        hists = self._histories(ks)
        if eng.draft_model is not None:
            try:
                return eng.draft_tree_batch(hists, ks)
            except InjectedFault:
                self.stats.draft_faults += 1
                return [None] * eng.num_slots
        trees = []
        for i, h in enumerate(hists):
            if h is None:
                trees.append(None)
                continue
            try:
                d = self.engine.draft(h)
            except InjectedFault:
                self.stats.draft_faults += 1
                d = []
            d = [int(t) for t in d[:ks[i]]]
            trees.append((d, [-1] + list(range(len(d) - 1)))
                         if d else None)
        return trees

    def _tick(self) -> None:
        spent = self._decode_phase()
        if self.chunk_tokens is not None:
            self._prefill_phase(spent)

    def _decode_phase(self) -> int:
        """One decode/verify step over every DECODING slot (slots mid
        chunked-prefill are invisible here — no cache row of theirs is
        complete). Returns the tick's decode token charge (positions
        computed), which the prefill phase subtracts from the tick
        token budget."""
        eng = self.engine
        trc = self.tracer
        # give every decoding slot an exclusive write target for this
        # tick; slots the pool can't serve are preempted back to the
        # queue FRONT with their progress (sampling keys depend only on
        # (seed, n_generated), so a resumed request continues its
        # original stream bit-for-bit)
        positions = {i: s.pos for i, s in enumerate(self._slots)
                     if self._decoding(s)}
        if eng.tree_spec and eng.spec_k > 0 and positions:
            spent = self._tree_tick(positions)
            if spent is not None:
                return spent
            # every forced chain was trivial and no draft survived —
            # fall through to a plain decode step
            drafts, spec, k1 = None, False, 1
        else:
            # speculate only when EVERY active slot has k1 rows of
            # headroom (a clamped out-of-range cache write would shift
            # onto committed rows) and some draft is non-empty;
            # otherwise this tick is a plain decode step — the k=0
            # degradation the chaos tier leans on. Fixed engines always
            # verify at the compiled spec_k + 1 width; adaptive ones
            # narrow to 1 + the widest draft actually proposed, so the
            # per-tick page charge below tracks the controller.
            if eng.spec_k > 0 and positions:
                if trc.enabled:
                    trc.begin("draft")
                drafts = self._draft_all(self._spec_ks(positions))
                if trc.enabled:
                    trc.end("draft",
                            proposed=sum(len(d) for d in drafts))
            else:
                drafts = None
            k1 = eng.spec_k + 1
            if drafts is not None and eng.adaptive_spec:
                k1 = 1 + max((len(drafts[i]) for i in positions),
                             default=0)
            spec = bool(drafts is not None and k1 > 1
                        and all(pos + k1 <= eng.max_len
                                for pos in positions.values())
                        and any(drafts[i] for i in positions))
        # requeue in submission order: appendleft of the newest request
        # first leaves the oldest at the queue front (slot-index order
        # would let a later request resume before an earlier one)
        if trc.enabled:
            trc.begin("prepare_decode")
        preempted = eng.prepare_decode(
            positions, n_new=k1 if spec else 1)
        if trc.enabled:
            trc.end("prepare_decode", preempted=len(preempted))
        for i in sorted(preempted,
                        key=lambda j: self._slots[j].request_id,
                        reverse=True):
            s = self._slots[i]
            if trc.enabled:
                trc.instant("preempted", request_id=s.request_id,
                            slot=i)
            self._queue.appendleft((s.request_id, s.request,
                                    list(s.generated)))
            self._slots[i] = None
        occupied = [s for s in self._slots if self._decoding(s)]
        if not occupied:
            return 0
        if spec:
            self._spec_tick(drafts, k1)
            return k1 * len(occupied)
        self.stats.plain_ticks += 1
        tokens = jnp.asarray(
            [s.generated[-1] if self._decoding(s) else 0
             for s in self._slots], jnp.int32)
        active = jnp.asarray([self._decoding(s) for s in self._slots])
        temps = jnp.asarray(
            [s.request.temperature if self._decoding(s) else 0.0
             for s in self._slots], jnp.float32)
        keys = jnp.stack(
            [self._slot_key(s) if self._decoding(s)
             else jax.random.PRNGKey(0) for s in self._slots])
        logits = eng.decode(tokens, active)
        if trc.enabled:
            trc.begin("accept")
        finite = np.asarray(eng.finite(logits))
        next_tokens = np.asarray(eng.sample(logits, keys, temps))
        if trc.enabled:
            trc.end("accept")
            trc.begin("commit")
        vocab = eng.cfg.vocab_size
        quarantined: List[Tuple[int, NonFiniteLogits]] = []
        for i, slot in enumerate(self._slots):
            if not self._decoding(slot):
                continue
            if not bool(finite[i]):
                self.stats.nan_events += 1
                quarantined.append((i, NonFiniteLogits(
                    f"slot {i} (request {slot.request_id}): non-finite "
                    "decode logits")))
                continue
            tok = int(next_tokens[i])
            if not 0 <= tok < vocab:
                self.stats.bad_samples += 1
                quarantined.append((i, NonFiniteLogits(
                    f"slot {i} (request {slot.request_id}): sampled "
                    f"token {tok} outside [0, {vocab})")))
                continue
            slot.generated.append(tok)
            slot.pos += 1
            self._tokens_emitted += 1
            self._note_token(slot.request_id, i)
            self._maybe_evict(i)
        if trc.enabled:
            trc.end("commit")
        # quarantine AFTER the healthy slots commit, requeueing at the
        # front in submission order (same rule as preemption)
        for i, err in sorted(
                quarantined,
                key=lambda t: self._slots[t[0]].request_id,
                reverse=True):
            self._quarantine(i, err)
        return len(occupied)

    def _spec_tick(self, drafts: List[List[int]], k1: int) -> None:
        """Draft → verify → accept: one verify step over ``k1``
        candidate positions per slot (``spec_k + 1`` for fixed engines;
        adaptive ones narrow to the widest draft proposed), then a host
        walk that commits the longest prefix of grid samples
        reproducing the drafts plus the first non-matching sample
        (1..k1 tokens per slot). Grid position j samples with
        ``fold_in(seed, n_generated + j)`` — the PLAIN stream's key for
        that token — so the committed stream is bit-identical to
        non-speculative decode (see ``serving.sampling``); acceptance
        only compresses ticks."""
        eng = self.engine
        trc = self.tracer
        self.stats.spec_ticks += 1
        rows = []
        for i, s in enumerate(self._slots):
            d = drafts[i][:k1 - 1]
            rows.append(([s.generated[-1] if self._decoding(s) else 0]
                         + d + [0] * (k1 - 1 - len(d))))
        tokens = jnp.asarray(rows, jnp.int32)
        temps = jnp.asarray(
            [s.request.temperature if self._decoding(s) else 0.0
             for s in self._slots], jnp.float32)
        base = jnp.stack(
            [jax.random.PRNGKey(s.request.seed) if self._decoding(s)
             else jax.random.PRNGKey(0) for s in self._slots])
        offs = jnp.asarray(
            [[(len(s.generated) if self._decoding(s) else 0) + j
              for j in range(k1)] for s in self._slots], jnp.int32)
        keys = self._fold_grid(base, offs)
        logits = eng.verify(tokens)
        if trc.enabled:
            trc.begin("accept")
        finite = np.asarray(eng.finite(logits))            # (B, k1)
        grid = np.asarray(eng.sample_grid(logits, keys, temps))
        vocab = eng.cfg.vocab_size
        counts = [0] * eng.num_slots
        quarantined: List[Tuple[int, NonFiniteLogits]] = []
        for i, slot in enumerate(self._slots):
            if not self._decoding(slot):
                continue
            draft = drafts[i]
            committed = accepted = 0
            for j in range(k1):
                # the always-on production gates run per committed
                # position, never on the grid tail beyond the walk —
                # those rows condition on rejected drafts and are
                # garbage a plain tick would never have computed
                if not bool(finite[i, j]):
                    self.stats.nan_events += 1
                    quarantined.append((i, NonFiniteLogits(
                        f"slot {i} (request {slot.request_id}): "
                        "non-finite verify logits")))
                    break
                tok = int(grid[i, j])
                if not 0 <= tok < vocab:
                    self.stats.bad_samples += 1
                    quarantined.append((i, NonFiniteLogits(
                        f"slot {i} (request {slot.request_id}): "
                        f"sampled token {tok} outside [0, {vocab})")))
                    break
                slot.generated.append(tok)
                slot.pos += 1
                self._tokens_emitted += 1
                self._note_token(slot.request_id, i)
                committed += 1
                matched = j < len(draft) and draft[j] == tok
                if matched:
                    accepted += 1
                if tok == self.eos_id or len(slot.generated) \
                        >= slot.request.max_new_tokens:
                    break
                if not matched:
                    # the non-matching sample IS the committed token
                    # (the residual-distribution resample; see
                    # serving.sampling) — the walk ends here
                    break
            counts[i] = committed
            self.stats.tokens_drafted += len(draft)
            self.stats.tokens_accepted += accepted
            if trc.enabled and draft:
                trc.stream_acceptance(i, accepted / len(draft))
            if eng.adaptive_spec and draft:
                self._accept_ewma[i] = 0.5 * self._accept_ewma[i] \
                    + 0.5 * accepted / len(draft)
        if trc.enabled:
            trc.end("accept", committed=sum(counts))
        eng.commit(counts)
        # a tick that commits m tokens counts m toward deadlines: the
        # scheduler clock stays in decode-step equivalents across modes
        extra = max(counts) - 1
        if extra > 0:
            self._tick_no += extra
        qset = {i for i, _ in quarantined}
        for i, slot in enumerate(self._slots):
            if slot is not None and i not in qset and counts[i]:
                self._maybe_evict(i)
        # quarantine keeps any partially committed (plain-stream
        # bit-identical) tokens: the requeue resumes from them
        for i, err in sorted(
                quarantined,
                key=lambda t: self._slots[t[0]].request_id,
                reverse=True):
            self._quarantine(i, err)

    def _tree_tick(self, positions: Dict[int, int]) -> Optional[int]:
        """Tree-speculative tick: pack every slot's FORCED chain (the
        committed tokens past its cache length — at least the pending
        token) plus its draft tree into one tree-attention verify grid,
        sample every node with the plain stream's key for its depth,
        and commit along the accepted root-to-leaf path
        (:func:`~apex_tpu.serving.sampling.tree_speculative_accept`).
        Cache lengths only advance by the row-CONTIGUOUS committed
        prefix: tokens a path stranded off the leftmost chain are
        re-sent as next tick's forced chain (the forced-prefix rule —
        bounded by the tree depth, never compounding; see
        ``serving.decode``). Returns the tick's token charge (grid
        positions computed), 0 when every slot was preempted before
        the verify, or None — tick not taken — when every forced chain
        is trivial and no draft survived, so the caller runs the plain
        path instead."""
        eng = self.engine
        trc = self.tracer
        ks = self._spec_ks(positions)
        if trc.enabled:
            trc.begin("draft")
        trees = self._draft_trees(ks)
        if trc.enabled:
            trc.end("draft",
                    proposed=sum(len(t[0]) for t in trees
                                 if t is not None))
        forced: Dict[int, List[int]] = {}
        for i, s in enumerate(self._slots):
            if self._decoding(s):
                h = list(s.request.prompt) + list(s.generated)
                forced[i] = h[s.pos:]        # f >= 1: the pending token
        if all(len(f) == 1 for f in forced.values()) \
                and not any(trees[i] is not None for i in positions):
            return None
        # grid width: the widest forced-chain + tree, clamped to the
        # scarcest slot's cache headroom (a slot whose chain overflows
        # the clamped grid catches up across ticks, committing rows
        # but sampling nothing until its chain fits)
        avail = min(eng.max_len - pos for pos in positions.values())
        k1 = max(len(forced[i])
                 + (len(trees[i][0]) if trees[i] is not None else 0)
                 for i in positions)
        k1 = max(1, min(k1, avail))
        if trc.enabled:
            trc.begin("prepare_decode")
        preempted = eng.prepare_decode(positions, n_new=k1)
        if trc.enabled:
            trc.end("prepare_decode", preempted=len(preempted))
        for i in sorted(preempted,
                        key=lambda j: self._slots[j].request_id,
                        reverse=True):
            s = self._slots[i]
            if trc.enabled:
                trc.instant("preempted", request_id=s.request_id,
                            slot=i)
            self._queue.appendleft((s.request_id, s.request,
                                    list(s.generated)))
            self._slots[i] = None
            forced.pop(i, None)
        if not forced:
            return 0
        f_chain: List[List[int]] = []
        g_trees: List[Optional[Tuple[List[int], List[int]]]] = []
        for i, s in enumerate(self._slots):
            if not self._decoding(s):
                f_chain.append([0])
                g_trees.append(None)
                continue
            chain = forced[i][:k1]
            room = k1 - len(chain)
            tree = trees[i]
            if tree is not None and len(chain) == len(forced[i]) \
                    and room > 0:
                # truncating a topological tree keeps parent validity
                toks = [int(t) for t in tree[0][:room]]
                pars = [int(p) for p in tree[1][:room]]
                g_trees.append((toks, pars) if toks else None)
            else:
                g_trees.append(None)
            f_chain.append(chain)
        tok_np, dep_np, anc_np, val_np, par_np, start_np = tree_arrays(
            f_chain, g_trees, k1)
        temps = jnp.asarray(
            [s.request.temperature if self._decoding(s) else 0.0
             for s in self._slots], jnp.float32)
        base = jnp.stack(
            [jax.random.PRNGKey(s.request.seed) if self._decoding(s)
             else jax.random.PRNGKey(0) for s in self._slots])
        # column j samples the (n_generated - f + 1 + depth[j])-th
        # generated token — exactly the plain stream's key offset for
        # that position (forced columns before the walk root land on
        # already-committed offsets; their samples are never read)
        offs = np.zeros((eng.num_slots, k1), np.int32)
        for i, s in enumerate(self._slots):
            if self._decoding(s):
                offs[i] = (len(s.generated) - len(f_chain[i]) + 1
                           + dep_np[i])
        keys = self._fold_grid(base, jnp.asarray(offs))
        logits = eng.tree_verify(jnp.asarray(tok_np),
                                 jnp.asarray(dep_np),
                                 jnp.asarray(anc_np))
        if trc.enabled:
            trc.begin("accept")
        finite = np.asarray(eng.finite(logits))            # (B, k1)
        grid = np.asarray(eng.sample_grid(logits, keys, temps))
        cnts, path = self._tree_accept(
            jnp.asarray(grid), jnp.asarray(tok_np), jnp.asarray(par_np),
            jnp.asarray(val_np), jnp.asarray(start_np))
        cnts, path = np.asarray(cnts), np.asarray(path)
        vocab = eng.cfg.vocab_size
        counts = [0] * eng.num_slots          # cache ROWS to commit
        new_tok_max = 0
        quarantined: List[Tuple[int, NonFiniteLogits]] = []
        for i, slot in enumerate(self._slots):
            if not self._decoding(slot):
                continue
            f = len(f_chain[i])
            if f < len(forced[i]):
                # catch-up-only: the truncated chain's rows commit,
                # nothing is sampled for this slot this tick
                counts[i] = f
                slot.pos += f
                continue
            nodes = len(g_trees[i][0]) if g_trees[i] is not None else 0
            committed = accepted = g = 0
            bad = None
            for v in range(int(cnts[i])):
                col = int(path[i, v])
                # the always-on production gates run per VISITED node
                # only — unvisited grid columns condition on rejected
                # branches a plain tick would never have computed
                if not bool(finite[i, col]):
                    self.stats.nan_events += 1
                    bad = NonFiniteLogits(
                        f"slot {i} (request {slot.request_id}): "
                        "non-finite tree-verify logits")
                    break
                tok = int(grid[i, col])
                if not 0 <= tok < vocab:
                    self.stats.bad_samples += 1
                    bad = NonFiniteLogits(
                        f"slot {i} (request {slot.request_id}): "
                        f"sampled token {tok} outside [0, {vocab})")
                    break
                slot.generated.append(tok)
                self._tokens_emitted += 1
                self._note_token(slot.request_id, i)
                committed += 1
                if v:
                    accepted += 1
                    if g == v - 1 and col == f - 1 + v:
                        g += 1    # the walk stayed on the leftmost chain
                if tok == self.eos_id or len(slot.generated) \
                        >= slot.request.max_new_tokens:
                    break
            # rows: the forced chain plus the contiguous accepted run
            # (the final committed sample never has a row — it is the
            # next pending token, exactly as in the linear walk)
            counts[i] = f + g
            slot.pos += f + g
            new_tok_max = max(new_tok_max, committed)
            self.stats.tokens_drafted += nodes
            self.stats.tokens_accepted += accepted
            if trc.enabled and nodes:
                trc.stream_acceptance(i, accepted / nodes)
            if eng.adaptive_spec and nodes:
                self._accept_ewma[i] = 0.5 * self._accept_ewma[i] \
                    + 0.5 * accepted / nodes
            if bad is not None:
                quarantined.append((i, bad))
        if trc.enabled:
            trc.end("accept", committed=sum(counts))
        eng.commit(counts)
        self.stats.spec_ticks += 1
        # a tick that commits m tokens counts m toward deadlines: the
        # scheduler clock stays in decode-step equivalents across modes
        if new_tok_max > 1:
            self._tick_no += new_tok_max - 1
        qset = {i for i, _ in quarantined}
        for i, slot in enumerate(self._slots):
            if slot is not None and i not in qset and counts[i]:
                self._maybe_evict(i)
        for i, err in sorted(
                quarantined,
                key=lambda t: self._slots[t[0]].request_id,
                reverse=True):
            self._quarantine(i, err)
        return k1 * len(forced)

    # -- drive loop --------------------------------------------------------

    def _raise_livelock(self, stalled: int) -> None:
        stuck = {"queued": [rid for rid, _, _ in self._queue],
                 "slots": {i: s.request_id
                           for i, s in enumerate(self._slots)
                           if s is not None}}
        err = LivelockError(
            f"no progress (token committed, request terminated, or "
            f"retry consumed) in {stalled} consecutive scheduler "
            f"ticks; stuck requests: queued={stuck['queued']} "
            f"slots={stuck['slots']}; pool={self.engine.pool_snapshot()}",
            stuck=stuck, pool=self.engine.pool_snapshot())
        if self.tracer.enabled:
            self.tracer.attach(err)  # the stuck slots' last events
        raise err

    @property
    def busy(self) -> bool:
        """Work pending: queued requests or occupied slots."""
        return bool(self._queue) or any(s is not None
                                        for s in self._slots)

    def step(self) -> None:
        """One scheduler tick: expire deadlines, admit, decode (and,
        when chunked prefill is on, run prompt chunks with the budget
        the decode phase left). Public so external load generators —
        the Poisson scenario bench — can interleave ``submit`` calls
        with ticks; :meth:`run` is just the drain loop over this. The
        progress watchdog spans steps: a chunk forward counts as
        progress (a long prompt prefilling is converging), so its
        counter joins tokens/completions/retries in the snapshot."""
        trc = self.tracer
        self._tick_no += 1
        if trc.enabled:
            trc.set_tick(self._tick_no)
        before = self._tokens_emitted
        self._expire_deadlines()
        self._admit()
        self._tick()
        if self.streams is not None:
            # end-of-tick delivery: every stream gets exactly the
            # tokens this tick committed for it (1..k+1 under
            # speculation), one stream_emit draw per delivering stream
            self.streams.flush()
        if trc.enabled:
            trc.tick_metrics(self._tokens_emitted - before,
                             len(self._queue),
                             self.engine.pool_gauges())
            if self.tenancy is not None:
                trc.tenant_gauges(self.tenancy.gauge_snapshot())
        if self.audit:
            self.engine.check_invariants()
        snap = (self._tokens_emitted, len(self.outcomes),
                self.stats.retries, self.stats.prefill_chunks)
        if snap == self._watch_snap:
            self._stalled += 1
            if self._stalled >= self.watchdog_limit:
                self._raise_livelock(self._stalled)
        else:
            self._stalled, self._watch_snap = 0, snap

    def run(self) -> List[List[int]]:
        """Drain the queue; returns generated tokens (EOS included when
        emitted) per request, in submission order. Typed outcomes —
        including degraded terminations, whose token lists are a prefix
        of their fault-free streams — live in ``self.outcomes``. Raises
        :class:`LivelockError` after ``watchdog_limit`` consecutive
        ticks without progress instead of spinning."""
        while self.busy:
            self.step()
        return [list(self.outcomes[rid].tokens)
                for rid in sorted(self.outcomes)]

"""KV cache: preallocated per-layer key/value buffers + slot lengths.

Layout: ``k``/``v`` are ``(num_layers, num_slots, num_heads, S_max,
head_dim)`` — the per-layer ``[B, H, S, d]`` buffers of the design doc,
stacked on a leading layer axis to match the model's stacked-layer
``lax.scan`` (the depth loop slices one layer's cache per iteration with
no re-plumbing). ``lengths`` is ``(num_slots,)`` int32 — how many
positions of each slot hold real tokens; it is simultaneously the next
write offset and the attention-mask bound (decode masks scores to
``s <= pos`` AFTER writing the new row, so stale rows past the length
are unreachable).

The cache is updated with ``lax.dynamic_update_slice`` inside a jit
whose cache argument is DONATED: XLA reuses the input buffer for the
output and a decode step is one in-place write per layer, not a fresh
``O(L·B·H·S·d)`` allocation. The trace-tier linter (APX512) pins the
donation — see ``apex_tpu/lint/traced/aliases.py`` and the
``gpt_decode_step`` registry entries.

dtype: bf16 halves cache HBM and decode is score-bound, not
precision-bound (scores/softmax stay fp32 in ``_decode_attention``);
fp32 is for parity tests. Under TP the head axis (2) shards over the
``model`` mesh axis — each rank holds its local heads' cache, matching
the head-major qkv column shard.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig


class KVCache(NamedTuple):
    k: jax.Array        # (L, num_slots, num_heads, S_max, head_dim)
    v: jax.Array        # (L, num_slots, num_heads, S_max, head_dim)
    lengths: jax.Array  # (num_slots,) int32, valid positions per slot


def init_cache(cfg: GPTConfig, num_slots: int, max_len: int,
               dtype=jnp.bfloat16) -> KVCache:
    """Zero-filled cache for ``num_slots`` concurrent sequences of up to
    ``max_len`` tokens each (prompt + generated)."""
    if max_len < 1 or num_slots < 1:
        raise ValueError(
            f"need positive num_slots/max_len, got {num_slots}/{max_len}")
    if not cfg.use_rope and max_len > cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {max_len} exceeds the learned position table "
            f"({cfg.max_position_embeddings}); raise "
            "max_position_embeddings or use rope")
    shape = (cfg.num_layers, num_slots, cfg.num_heads, max_len,
             cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((num_slots,), jnp.int32))


def cache_partition_specs(rules=None) -> KVCache:
    """TP layout: heads (axis 2) shard over the ``model`` mesh axis —
    the cache shard each rank sees inside shard_map holds exactly the
    heads its qkv column shard produces. Lengths are replicated.

    Derived from the partition-rule table (``partition.kv_cache_rules``
    by default, or any table covering the ``k``/``v``/``lengths``
    paths), so serving stays consistent with whatever table shards the
    model — APX702 checks the head axis against the qkv weights' ``tp``
    axis."""
    from apex_tpu.partition import kv_cache_rules, match_partition_rules

    if rules is None:
        rules = kv_cache_rules()
    # Rank-faithful abstract template: matching only reads paths/ranks.
    template = KVCache(
        k=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        v=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        lengths=jax.ShapeDtypeStruct((1,), "int32"))
    return match_partition_rules(rules, template)


# ---------------------------------------------------------------------------
# paged cache: fixed page pool + per-slot block tables
# ---------------------------------------------------------------------------

# Physical page ids below this are reserved and never allocated:
NULL_PAGE = 0     # parks unmapped block-table entries; never written
SCRATCH_PAGE = 1  # write dump for redirected rows; never attended
RESERVED_PAGES = 2


class PagedKVCache(NamedTuple):
    """Paged layout: ``k``/``v`` hold a POOL of fixed-size pages shared
    by every slot — ``(L, num_pages, num_heads, page_size, head_dim)``
    — and ``block_tables`` (``(num_slots, max_pages)`` int32) maps each
    slot's logical page index to a physical page. HBM for K/V history
    scales with pages actually allocated (Σ ceil(len/page_size)), not
    ``slots x S_max``; the host-side allocator
    (:class:`apex_tpu.serving.paging.PagePool`) owns which pages are
    live, shared (prefix caching) or free. Heads (axis 2) still shard
    over ``model`` under TP; lengths and block tables are replicated.

    ``kv_dtype=int8`` mode: the pool stores round-to-nearest symmetric
    int8 with PER-PAGE-PER-HEAD fp32 scales in the trailing
    ``k_scale``/``v_scale`` leaves (``(L, num_pages, num_heads)``,
    amax/127 of each head's page — ``apex_tpu.quant.kv_quantize``).
    The scales ride the same donated cache tuple as the block tables
    (6 alias pairs instead of 4, pinned by APX512), shard their head
    axis over ``model`` like the pool, and are cloned together with
    their pages on copy-on-write. bf16/fp32 caches leave both fields
    ``None`` — an optional trailing NamedTuple field vanishes from the
    pytree, so every existing 4-leaf construction and donation site is
    unchanged.
    """
    k: jax.Array             # (L, num_pages, num_heads, page_size, hd)
    v: jax.Array             # (L, num_pages, num_heads, page_size, hd)
    lengths: jax.Array       # (num_slots,) int32, valid positions
    block_tables: jax.Array  # (num_slots, max_pages) int32 page ids
    k_scale: Optional[jax.Array] = None  # (L, num_pages, num_heads) f32
    v_scale: Optional[jax.Array] = None  # (L, num_pages, num_heads) f32


def max_pages_per_slot(max_len: int, page_size: int) -> int:
    return -(-max_len // page_size)


def init_paged_cache(cfg: GPTConfig, num_slots: int, max_len: int,
                     num_pages: int, page_size: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    """Zero page pool + block tables parked on ``SCRATCH_PAGE`` (writes
    of unoccupied slots land in scratch, reads of it are masked)."""
    if max_len < 1 or num_slots < 1 or page_size < 1:
        raise ValueError(
            f"need positive num_slots/max_len/page_size, got "
            f"{num_slots}/{max_len}/{page_size}")
    if num_pages <= RESERVED_PAGES:
        raise ValueError(
            f"num_pages {num_pages} must exceed the {RESERVED_PAGES} "
            f"reserved pages (null + scratch)")
    if not cfg.use_rope and max_len > cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {max_len} exceeds the learned position table "
            f"({cfg.max_position_embeddings}); raise "
            "max_position_embeddings or use rope")
    shape = (cfg.num_layers, num_pages, cfg.num_heads, page_size,
             cfg.head_dim)
    bt = jnp.full((num_slots, max_pages_per_slot(max_len, page_size)),
                  SCRATCH_PAGE, jnp.int32)
    if jnp.dtype(dtype) == jnp.int8:
        # quantized pool: zero int8 pages + zero fp32 scales (a
        # 0-scale page dequantizes to exact zeros, so NULL stays
        # pristine before its first real write)
        sscale = (cfg.num_layers, num_pages, cfg.num_heads)
        return PagedKVCache(k=jnp.zeros(shape, jnp.int8),
                            v=jnp.zeros(shape, jnp.int8),
                            lengths=jnp.zeros((num_slots,), jnp.int32),
                            block_tables=bt,
                            k_scale=jnp.zeros(sscale, jnp.float32),
                            v_scale=jnp.zeros(sscale, jnp.float32))
    return PagedKVCache(k=jnp.zeros(shape, dtype),
                        v=jnp.zeros(shape, dtype),
                        lengths=jnp.zeros((num_slots,), jnp.int32),
                        block_tables=bt)


def audit_block_tables(block_tables, slot_pages) -> bool:
    """Cross-check the DEVICE block tables against the HOST allocator's
    per-slot page lists: row ``i`` must map exactly ``slot_pages[i]``
    followed by a NULL/SCRATCH-parked tail. This is the device half of
    the pool invariant audit (``PagePool.check_invariants`` covers the
    host half); a divergence means a ``prepare_decode``/``free_slot``
    path updated one side and not the other. Raises
    :class:`~apex_tpu.serving.health.PoolInvariantError`."""
    import numpy as np

    from apex_tpu.serving.health import PoolInvariantError

    bt = np.asarray(block_tables)
    if bt.shape[0] != len(slot_pages):
        raise PoolInvariantError(
            f"block table has {bt.shape[0]} rows but the host tracks "
            f"{len(slot_pages)} slots")
    for i, pages in enumerate(slot_pages):
        if len(pages) > bt.shape[1]:
            raise PoolInvariantError(
                f"slot {i}: host maps {len(pages)} pages but the table "
                f"row holds {bt.shape[1]}")
        mapped = bt[i, :len(pages)].tolist()
        if mapped != list(pages):
            raise PoolInvariantError(
                f"slot {i}: device row maps {mapped}, host allocator "
                f"says {list(pages)}")
        tail = bt[i, len(pages):]
        stray = tail[(tail != NULL_PAGE) & (tail != SCRATCH_PAGE)]
        if stray.size:
            raise PoolInvariantError(
                f"slot {i}: unmapped tail holds live page ids "
                f"{sorted(set(stray.tolist()))} (must be NULL/SCRATCH)")
    return True


def paged_cache_partition_specs(rules=None,
                                quantized: bool = False) -> PagedKVCache:
    """Same table-derived TP layout as :func:`cache_partition_specs`:
    the pool's head axis (still axis 2) shards over ``model``; lengths
    AND block tables are replicated — every rank walks the same
    logical-to-physical mapping over its local heads. With
    ``quantized`` the template grows the ``k_scale``/``v_scale``
    leaves, matched against ``kv_cache_quant_rules()`` (head axis — now
    axis 2 of the 3-d scales — sharded over ``model`` like the pool's)."""
    from apex_tpu.partition import kv_cache_rules, match_partition_rules

    if rules is None:
        if quantized:
            from apex_tpu.partition import kv_cache_quant_rules

            rules = kv_cache_quant_rules()
        else:
            rules = kv_cache_rules()
    template = PagedKVCache(
        k=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        v=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        lengths=jax.ShapeDtypeStruct((1,), "int32"),
        block_tables=jax.ShapeDtypeStruct((1, 1), "int32"))
    if quantized:
        template = template._replace(
            k_scale=jax.ShapeDtypeStruct((1, 1, 1), "float32"),
            v_scale=jax.ShapeDtypeStruct((1, 1, 1), "float32"))
    return match_partition_rules(rules, template)

"""KV cache: preallocated per-layer key/value buffers + slot lengths.

Layout: ``k``/``v`` are ``(num_layers, num_slots, num_heads, S_max,
head_dim)`` — the per-layer ``[B, H, S, d]`` buffers of the design doc,
stacked on a leading layer axis to match the model's stacked-layer
``lax.scan`` (the depth loop slices one layer's cache per iteration with
no re-plumbing). ``lengths`` is ``(num_slots,)`` int32 — how many
positions of each slot hold real tokens; it is simultaneously the next
write offset and the attention-mask bound (decode masks scores to
``s <= pos`` AFTER writing the new row, so stale rows past the length
are unreachable).

The cache is updated with ``lax.dynamic_update_slice`` inside a jit
whose cache argument is DONATED: XLA reuses the input buffer for the
output and a decode step is one in-place write per layer, not a fresh
``O(L·B·H·S·d)`` allocation. The trace-tier linter (APX512) pins the
donation — see ``apex_tpu/lint/traced/aliases.py`` and the
``gpt_decode_step`` registry entries.

dtype: bf16 halves cache HBM and decode is score-bound, not
precision-bound (scores/softmax stay fp32 in ``_decode_attention``);
fp32 is for parity tests. Under TP the head axis (2) shards over the
``model`` mesh axis — each rank holds its local heads' cache, matching
the head-major qkv column shard.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig


class KVCache(NamedTuple):
    k: jax.Array        # (L, num_slots, num_heads, S_max, head_dim)
    v: jax.Array        # (L, num_slots, num_heads, S_max, head_dim)
    lengths: jax.Array  # (num_slots,) int32, valid positions per slot


def init_cache(cfg: GPTConfig, num_slots: int, max_len: int,
               dtype=jnp.bfloat16) -> KVCache:
    """Zero-filled cache for ``num_slots`` concurrent sequences of up to
    ``max_len`` tokens each (prompt + generated)."""
    if max_len < 1 or num_slots < 1:
        raise ValueError(
            f"need positive num_slots/max_len, got {num_slots}/{max_len}")
    if not cfg.use_rope and max_len > cfg.max_position_embeddings:
        raise ValueError(
            f"max_len {max_len} exceeds the learned position table "
            f"({cfg.max_position_embeddings}); raise "
            "max_position_embeddings or use rope")
    shape = (cfg.num_layers, num_slots, cfg.num_heads, max_len,
             cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((num_slots,), jnp.int32))


def cache_partition_specs(rules=None) -> KVCache:
    """TP layout: heads (axis 2) shard over the ``model`` mesh axis —
    the cache shard each rank sees inside shard_map holds exactly the
    heads its qkv column shard produces. Lengths are replicated.

    Derived from the partition-rule table (``partition.kv_cache_rules``
    by default, or any table covering the ``k``/``v``/``lengths``
    paths), so serving stays consistent with whatever table shards the
    model — APX702 checks the head axis against the qkv weights' ``tp``
    axis."""
    import jax

    from apex_tpu.partition import kv_cache_rules, match_partition_rules

    if rules is None:
        rules = kv_cache_rules()
    # Rank-faithful abstract template: matching only reads paths/ranks.
    template = KVCache(
        k=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        v=jax.ShapeDtypeStruct((1,) * 5, "bfloat16"),
        lengths=jax.ShapeDtypeStruct((1,), "int32"))
    return match_partition_rules(rules, template)

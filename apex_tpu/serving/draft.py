"""Host-side n-gram / prompt-lookup drafting for self-speculative decode.

No draft model: candidate continuations come from the request's OWN
token history (prompt + generated so far) — the prompt-lookup scheme.
The current n-gram suffix of the history is matched against earlier
occurrences; the tokens that followed the most recent earlier match
become the draft. This is a pure function of the token-id sequence:
deterministic, slot-placement-independent, and free (no device work) —
exactly the properties the serving bit-identity contract needs, since
a WRONG draft only costs verify throughput, never correctness (the
verify + accept path resamples with the plain decode stream's keys).

The drafter may return fewer than ``k`` tokens (including zero, when
the suffix never recurred); the scheduler pads the verify bucket and
bounds acceptance by the true draft length.
"""

from typing import List, Sequence

__all__ = ["ngram_draft"]


def ngram_draft(history: Sequence[int], k: int, *, max_ngram: int = 3,
                min_ngram: int = 1) -> List[int]:
    """Propose up to ``k`` draft tokens from ``history``.

    Tries suffix n-grams longest-first (``max_ngram`` down to
    ``min_ngram``): for each n, find the MOST RECENT earlier occurrence
    of ``history[-n:]`` that has at least one continuation token
    (the terminal self-match is excluded), and return the up-to-``k``
    tokens that followed it. Longer suffixes are stronger evidence, so
    the first hit wins; recency breaks ties within a length (repeated
    phrases drift, and the latest occurrence tracks the current one
    best). Returns ``[]`` when ``k <= 0``, the history is shorter than
    ``min_ngram + 1``, or no suffix recurs.
    """
    if k <= 0 or min_ngram < 1 or max_ngram < min_ngram:
        return []
    hist = list(history)
    n_hist = len(hist)
    for n in range(min(max_ngram, n_hist - 1), min_ngram - 1, -1):
        suffix = hist[n_hist - n:]
        # latest start i with a continuation: i + n <= n_hist - 1, and
        # i < n_hist - n excludes the suffix matching itself
        for i in range(n_hist - n - 1, -1, -1):
            if hist[i:i + n] == suffix:
                return hist[i + n:i + n + k]
    return []

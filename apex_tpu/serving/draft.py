"""Host-side n-gram / prompt-lookup drafting for self-speculative decode.

No draft model: candidate continuations come from the request's OWN
token history (prompt + generated so far) — the prompt-lookup scheme.
The current n-gram suffix of the history is matched against earlier
occurrences; the tokens that followed the most recent earlier match
become the draft. This is a pure function of the token-id sequence:
deterministic, slot-placement-independent, and free (no device work) —
exactly the properties the serving bit-identity contract needs, since
a WRONG draft only costs verify throughput, never correctness (the
verify + accept path resamples with the plain decode stream's keys).

The drafter may return fewer than ``k`` tokens (including zero, when
the suffix never recurred); the scheduler pads the verify bucket and
bounds acceptance by the true draft length.

``tree_arrays`` is the grid packer shared by the tree-speculation
paths (scheduler, bench, tests): it lowers per-slot draft trees —
``(tokens, parents)`` lists, parent ``-1`` = child of the walk root —
plus each slot's FORCED token chain (committed tokens whose cache rows
must be re-sent; at least the pending token) into the padded
``(tokens, depth, anc, valid, start)`` arrays
``decode.make_tree_verify_fn`` and ``sampling.tree_speculative_accept``
consume.
"""

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["ngram_draft", "tree_arrays"]


def tree_arrays(forced: Sequence[Sequence[int]],
                trees: Sequence[Tuple[Sequence[int], Sequence[int]]],
                k1: int):
    """Pack B slots' forced chains + draft trees into one verify grid.

    ``forced[b]`` (length f_b >= 1, f_b + len(tree tokens) <= k1) are
    tokens re-sent as a linear chain occupying grid columns 0..f_b-1
    (the last one is the walk root / pending token); ``trees[b]`` is
    ``(tokens, parents)`` in topological order (``parents[i] < i``;
    ``-1`` roots attach to the walk root). Returns numpy arrays:
    tokens (B, k1) int32 (0-padded), depth (B, k1) int32 (pad columns
    0 — their rows are garbage by the write-then-attend contract),
    anc (B, k1, k1) bool (anc[i, j]: column i visible to query column
    j; pads see only themselves), valid (B, k1) bool (True on draft
    -node columns — the accept walk's candidate set), parents (B, k1)
    int32 (each column's parent GRID column; -1 on pads and the first
    forced column, which never match a walk position), start (B,)
    int32 (= f_b - 1, the walk root column)."""
    b = len(forced)
    tokens = np.zeros((b, k1), np.int32)
    depth = np.zeros((b, k1), np.int32)
    anc = np.zeros((b, k1, k1), bool)
    valid = np.zeros((b, k1), bool)
    parents = np.full((b, k1), -1, np.int32)
    start = np.zeros((b,), np.int32)
    np.einsum("bii->bi", anc)[:] = True          # self-visibility, pads too
    for i in range(b):
        chain = list(forced[i])
        t_toks, t_par = trees[i] if trees[i] is not None else ([], [])
        f = len(chain)
        if f < 1:
            raise ValueError("forced chain needs at least the pending "
                             "token")
        if f + len(t_toks) > k1:
            raise ValueError(f"forced ({f}) + tree ({len(t_toks)}) "
                             f"exceeds grid width {k1}")
        tokens[i, :f] = chain
        depth[i, :f] = np.arange(f)
        for j in range(f):
            anc[i, : j + 1, j] = True
            if j:
                parents[i, j] = j - 1
        start[i] = f - 1
        for n, (tok, par) in enumerate(zip(t_toks, t_par)):
            col = f + n
            if not (-1 <= par < n):
                raise ValueError(f"parent {par} of tree node {n} is not "
                                 f"an earlier node")
            pcol = f - 1 if par == -1 else f + par
            tokens[i, col] = tok
            depth[i, col] = depth[i, pcol] + 1
            anc[i, :, col] = anc[i, :, pcol]
            anc[i, col, col] = True
            valid[i, col] = True
            parents[i, col] = pcol
    return tokens, depth, anc, valid, parents, start


def ngram_draft(history: Sequence[int], k: int, *, max_ngram: int = 3,
                min_ngram: int = 1) -> List[int]:
    """Propose up to ``k`` draft tokens from ``history``.

    Tries suffix n-grams longest-first (``max_ngram`` down to
    ``min_ngram``): for each n, find the MOST RECENT earlier occurrence
    of ``history[-n:]`` that has at least one continuation token
    (the terminal self-match is excluded), and return the up-to-``k``
    tokens that followed it. Longer suffixes are stronger evidence, so
    the first hit wins; recency breaks ties within a length (repeated
    phrases drift, and the latest occurrence tracks the current one
    best). Returns ``[]`` when ``k <= 0``, the history is shorter than
    ``min_ngram + 1``, or no suffix recurs.
    """
    if k <= 0 or min_ngram < 1 or max_ngram < min_ngram:
        return []
    hist = list(history)
    n_hist = len(hist)
    for n in range(min(max_ngram, n_hist - 1), min_ngram - 1, -1):
        suffix = hist[n_hist - n:]
        # latest start i with a continuation: i + n <= n_hist - 1, and
        # i < n_hist - n excludes the suffix matching itself
        for i in range(n_hist - n - 1, -1, -1):
            if hist[i:i + n] == suffix:
                return hist[i + n:i + n + k]
    return []

"""Disaggregated prefill/decode serving: two engines, one scheduler.

Prefill is MXU-bound and decode is HBM-bound (BASELINE r8/r9), so the
"millions of users" topology runs them on SEPARATE replicas — prompt
forwards on a prefill engine, decode ticks on a decode engine — with
the finished prompt pages shipped between them by the fault-tolerant
:class:`~apex_tpu.serving.transfer.PageTransfer` channel, keyed and
deduped by the chained content hashes of
:func:`~apex_tpu.serving.paging.prefix_page_keys`.

The design reuses the whole serving stack instead of forking it: the
:class:`DisaggregatedRouter` IS a
:class:`~apex_tpu.serving.scheduler.ContinuousBatchingScheduler` whose
engine is a composite (:class:`_DisaggEngine`) presenting the standard
``DecodeEngine`` interface. Every decode-path method delegates to the
ACTIVE replica (the one backing the slots); only ``prefill`` routes:

1. remote replica ``routable`` → run the prompt forward there, ship
   the non-shared pages across, install them into pages the active
   pool allocated (same order a local prefill would), register the
   prefix chain, return the logits. The slot's cache row ends up
   BITWISE identical to a colocated prefill — same jitted program,
   same inputs, pages copied verbatim — which is why fault-free
   disaggregated streams are integer-identical to the colocated
   scheduler's.
2. remote down, transfer budget exhausted, payload quarantined, or
   the remote pool refused the prompt → typed error
   (:class:`~apex_tpu.serving.health.TransferFailed` /
   :class:`~apex_tpu.serving.health.TransferCorrupt` /
   :class:`~apex_tpu.serving.health.ReplicaUnavailable`), caught here,
   and the admission is served COLOCATED on the active engine — the
   request never observes the degradation (graceful ladder: remote →
   colocated → scheduler retry budget → typed outcome).

Health and failover: the router draws the ``replica_health`` fault
site once per replica per tick (fixed order — replay-exact) and folds
the probes into each replica's
:class:`~apex_tpu.serving.health.ReplicaHealth` ladder alongside real
transfer/prefill outcomes. A DOWN remote just stops receiving
prefills. A DOWN *active* replica triggers mid-stream failover: every
occupied slot is drained back to the queue front (the preemption
resume path — re-prefill from prompt + generated, sampling keys fold
``(seed, n_generated)``, so committed streams stay bit-identical) and
the replicas swap roles; the recovered ex-active replica later rejoins
as the remote prefill target. Admission, deadlines, retry budgets, the
progress watchdog, and flight-recorder attachment all come from the
base scheduler unchanged — a dead replica produces typed outcomes,
never a hang.

Clock accounting: a remote prefill runs CONCURRENTLY with the active
replica's decode ticks, so the router does not charge its sequential
depth to the work-charged tick clock the way colocated admission does
— it charges the deterministic handoff cost instead
(``handoff_ticks_per_page`` per shipped page, plus one backoff tick
per retry attempt, observed in the ``serving_transfer_ticks``
histogram). That unblocked-decode gap is exactly the p99 ITL win the
``serving_disagg_vs_colocated`` A/B pair measures; sampling keys never
see the clock, so streams are unaffected.

Scope: both replicas must be PAGED engines with identical model
config/geometry and SHARED injector+tracer (one deterministic fault
and event sequence). Chunked prefill, model drafters/tree speculation,
and int8 page pools stay colocated-only for now — the constructor
refuses them typed.

Pool scale: :class:`PoolRouter` generalizes the pair to N prefill x M
decode replicas behind the same single admission queue (the DistServe
/ Mooncake production shape — PAPERS.md). Prefill admissions route by
measured load (health rung, link ticks already routed this pass,
pages-free headroom, fixed order — the ``pool_route`` fault site can
degrade the pick to fixed order, never the stream); ONE decode replica
backs the scheduler slots while its siblings are failover targets
chosen by pages-free headroom, with the ladder decode sibling →
borrowed prefill replica → last-replica-standing, and a ``rebalance``
move home once a decode replica recovers. Handoffs default to the
device-to-device :class:`~apex_tpu.serving.transfer.PageReshard`
(spec-to-spec over the replica pair's mesh placement, priced
``ici_ticks_per_page`` within a slice / ``dcn_ticks_per_page`` across,
both cheaper than ``handoff_ticks_per_page``), degrading to the
host-staged channel on
:class:`~apex_tpu.serving.health.ReshardFailed`. The admission clock
uses a link-overlap model: handoffs routed to distinct prefill
replicas within one pass are charged the busy-horizon increase, not
the sum — with one prefill replica this reduces exactly to the pair's
serial charge, and with several it is the goodput win the
``serving_pool_scaling`` bench measures. The validation contract
(``_validate_replicas``) applies pairwise across ALL N+M replicas,
and the shared-``PrefixRegistry``-or-none rule is pool-wide.

This module is host state (router bookkeeping, health ladders) —
APX401 registers it like ``serving.health``/``serving.faults``.
"""

from typing import Dict, List, Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.cache import NULL_PAGE, max_pages_per_slot
from apex_tpu.serving.faults import FaultInjector, InjectedFault
from apex_tpu.serving.health import (HEALTH_STATES, PoolExhausted,
                                     ReplicaHealth, ReplicaUnavailable,
                                     ReshardFailed, TransferCorrupt,
                                     TransferFailed)
from apex_tpu.serving.paging import prefix_page_keys
from apex_tpu.serving.scheduler import ContinuousBatchingScheduler
from apex_tpu.serving.transfer import (PageReshard, PageTransfer,
                                       make_insert_pages_fn)

#: The remote replica prefills every admission into this slot, then
#: frees it once the pages have shipped — admissions are sequential,
#: so one staging slot suffices and the remote pool's prefix registry
#: (not its slots) carries its cross-request dedup.
_STAGING_SLOT = 0

#: Fixed health-probe order per tick (initial role names — replay
#: depends on draw ORDER, not on which replica currently serves).
_REPLICA_ORDER = ("prefill", "decode")


#: Engine attributes every replica in a pool must agree on: the page
#: geometry the handoff relies on, plus everything that shapes a
#: committed stream (a mixed pool could route the same request to a
#: replica that samples differently).
_PAIRED_ATTRS = ("cfg", "num_slots", "max_len", "page_size", "buckets",
                 "spec_k", "top_k", "top_p", "adaptive_spec",
                 "prefix_sharing")


def _as_pool(engines) -> List:
    """Normalize an engine-or-sequence argument to a list (the 1x1
    router passes bare engines; the pool router passes sequences)."""
    if isinstance(engines, (list, tuple)):
        return list(engines)
    return [engines]


def _pool_names(n_prefill: int, n_decode: int):
    """Replica names by role and pool index. The 1x1 pair keeps the
    historical bare names (``prefill``/``decode`` — metric labels and
    chaos replays depend on them); pools index (``prefill0``...)."""
    if n_prefill == 1 and n_decode == 1:
        return ("prefill",), ("decode",)
    return (tuple(f"prefill{i}" for i in range(n_prefill)),
            tuple(f"decode{i}" for i in range(n_decode)))


def _validate_replicas(prefill_engines, decode_engines) -> None:
    """The pool pairing contract, applied pairwise across ALL N+M
    replicas (the 1x1 pair is the degenerate case): every replica is a
    distinct paged engine, every geometry/sampling attribute matches
    the first replica's (transitively: pairwise), and the host tier /
    injector / tracer are each ONE shared instance pool-wide — a
    per-pair check would admit a 2x2 pool whose halves fork the prefix
    namespace or the fault-draw sequence."""
    prefills = _as_pool(prefill_engines)
    decodes = _as_pool(decode_engines)
    if not prefills or not decodes:
        raise ValueError(
            "a replica pool needs at least one prefill and one decode "
            "engine")
    pnames, dnames = _pool_names(len(prefills), len(decodes))
    named = list(zip(pnames, prefills)) + list(zip(dnames, decodes))
    engines = [e for _, e in named]
    if len({id(e) for e in engines}) != len(engines):
        raise ValueError(
            "disaggregation needs two engine instances per pair: every "
            "pool replica must be a DISTINCT engine (a shared instance "
            "would alias slots and page pools)")
    for role, eng in named:
        if not getattr(eng, "paged", False):
            raise ValueError(
                f"the {role} replica must be a paged engine: the "
                "handoff ships page tiles keyed by prefix_page_keys")
        if getattr(eng.cache, "k_scale", None) is not None:
            raise ValueError(
                "disaggregated serving is not offered over the int8 "
                "page pool: shipped pages would carry page-local "
                "scales quantized against the SENDER's amax sweep; "
                "kv8 keeps colocated serving")
        if eng.draft_model is not None or eng.tree_spec:
            raise ValueError(
                "model drafters / tree speculation stay colocated: "
                "the drafter's lockstep cache would need its own "
                "cross-replica handoff (n-gram spec_k works "
                "disaggregated)")
    ref_name, ref = named[0]
    for attr in _PAIRED_ATTRS:
        for name, eng in named[1:]:
            va, vb = getattr(ref, attr), getattr(eng, attr)
            if va != vb:
                raise ValueError(
                    f"disaggregated replicas must agree on {attr}: "
                    f"{ref_name}={va!r} vs {name}={vb!r}")
    if len({id(eng.host_tier) for eng in engines}) > 1:
        raise ValueError(
            "all replicas must share ONE PrefixRegistry host tier "
            "(or none of them): the registry is the global content-"
            "addressed map — split tiers would fork the prefix "
            "namespace (construct every engine with the same "
            "host_tier=)")
    if len({id(eng.injector) for eng in engines}) > 1:
        raise ValueError(
            "all replicas must share ONE FaultInjector: fault draws "
            "form a single deterministic sequence (construct every "
            "engine with the same injector=)")
    if len({id(eng.tracer) for eng in engines}) > 1:
        raise ValueError(
            "all replicas must share ONE Tracer: events, metrics and "
            "the stats view live in a single registry (construct "
            "every engine with the same tracer=)")


class _DisaggEngine:
    """The composite engine behind :class:`DisaggregatedRouter`:
    presents the ``DecodeEngine`` interface over two paged replicas.
    Attribute/method access falls through to the ACTIVE replica (the
    one whose slots the scheduler drives); ``prefill`` routes per the
    module doc. Swappable: :meth:`switch_active` exchanges the roles
    on failover."""

    paged = True

    def __init__(self, prefill_engine, decode_engine,
                 transfer: PageTransfer,
                 health: Dict[str, ReplicaHealth],
                 handoff_ticks_per_page: float,
                 backoff_ticks: int):
        # set the delegation table FIRST: __getattr__ consults it
        self._replicas = {"prefill": prefill_engine,
                          "decode": decode_engine}
        self._active_name = "decode"
        self._remote_name = "prefill"
        self._order = _REPLICA_ORDER
        self.transfer = transfer
        self.health = health
        self.handoff_ticks_per_page = float(handoff_ticks_per_page)
        self.backoff_ticks = int(backoff_ticks)
        self.injector = decode_engine.injector
        self.tracer = decode_engine.tracer
        self.stats = decode_engine.stats
        self._insert = make_insert_pages_fn()
        self._admit_charge: Optional[int] = None

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_replicas"][
            self.__dict__["_active_name"]], name)

    @property
    def active(self):
        return self._replicas[self._active_name]

    @property
    def remote(self):
        return self._replicas[self._remote_name]

    @property
    def active_name(self) -> str:
        return self._active_name

    @property
    def remote_name(self) -> str:
        return self._remote_name

    # -- health / failover ----------------------------------------------

    def health_tick(self) -> None:
        """One ``replica_health`` probe per replica, fixed order
        (``self._order`` — all prefill names then all decode names,
        never the current role assignment) — the router calls this at
        the top of every admission pass, so probe draw indices are a
        pure function of the tick count and the POOL SHAPE, not of
        which replica currently serves."""
        for name in self._order:
            fired, _ = self.injector.draw("replica_health")
            self.health[name].probe(not fired)

    @property
    def active_down(self) -> bool:
        return not self.health[self._active_name].routable

    @property
    def remote_routable(self) -> bool:
        return self.health[self._remote_name].routable

    def switch_active(self) -> None:
        self._active_name, self._remote_name = (self._remote_name,
                                                self._active_name)

    # -- admission-charge handshake with the router ---------------------

    def pop_admit_charge(self, default: int) -> int:
        # a remote prefill staged its handoff (+ promote) cost here; a
        # colocated one staged on the active engine — delegate so its
        # host-tier repricing (suffix depth + promote ticks) survives
        charge, self._admit_charge = self._admit_charge, None
        if charge is not None:
            return charge
        return self.active.pop_admit_charge(default)

    # -- routed prefill -------------------------------------------------

    def prefill(self, slot: int, prompt: Sequence[int]):
        trc = self.tracer
        if self.remote_routable:
            try:
                return self._remote_prefill(slot, prompt,
                                            self._remote_name)
            except (TransferFailed, TransferCorrupt,
                    ReplicaUnavailable) as e:
                # degrade, don't fail: the admission is served
                # colocated on the active engine; the request never
                # sees the transfer/replica fault
                if trc.enabled:
                    trc.instant("failover", slot=slot,
                                cause=type(e).__name__,
                                replica=self._remote_name)
        self.stats.colocated_prefills += 1
        return self.active.prefill(slot, prompt)

    def _remote_prefill(self, slot: int, prompt: Sequence[int],
                        rname: str):
        act, rem = self.active, self._replicas[rname]
        rhealth = self.health[rname]
        toks = [int(t) for t in prompt]
        try:
            logits = rem.prefill(_STAGING_SLOT, toks)
        except PoolExhausted as e:
            # remote CAPACITY, not remote failure: no health demerit,
            # but the admission cannot be staged there right now
            raise ReplicaUnavailable(
                f"remote replica {rname!r} page pool "
                f"refused the prompt: {e}",
                replica=rname) from e
        except InjectedFault:
            # a transient device fault on the remote replica: the
            # remote engine rolled its page references back; propagate
            # so the scheduler charges the retry budget exactly like a
            # colocated prefill fault — and let repeated faults walk
            # the replica down the ladder toward colocated routing
            rhealth.probe(False)
            raise
        # the remote prefill staged its OWN admission repricing (it may
        # carry a host tier); the router charges handoff ticks instead
        rem.pop_admit_charge(0)
        # allocate the destination pages in the SAME order a colocated
        # prefill would: longest registered prefix run shared, host-
        # tier promotions extending it, the remainder fresh from the
        # active pool
        keys = prefix_page_keys(toks, act.page_size)
        n_pages = max_pages_per_slot(len(toks), act.page_size)
        shared = act.pool.match_prefix(keys) if act.prefix_sharing \
            else []
        promoted: List[int] = []
        promote_ticks = 0
        if act.host_tier is not None and act.prefix_sharing \
                and len(shared) < n_pages:
            promoted, promote_ticks = act._promote_chain(
                keys, len(shared))
        covered = len(shared) + len(promoted)
        private: List[int] = []
        for _ in range(n_pages - covered):
            p = act.pool.alloc()
            if p is None:
                for q in shared + promoted + private:
                    act.pool.release(q)
                rem.free_slot(_STAGING_SLOT)
                raise PoolExhausted(
                    f"prompt needs {n_pages} pages; pool has "
                    f"{act.pool.num_free} free and nothing left to "
                    "evict", need=n_pages, free=act.pool.num_free,
                    cached=act.pool.num_cached)
            private.append(p)
        src_pages = rem._slot_pages[_STAGING_SLOT][covered:n_pages]
        self.stats.transfer_pages_deduped += covered
        try:
            k_tile, v_tile, attempts, tpp, tier = self._ship_pages(
                rem, toks, src_pages, rname, rhealth)
        except (TransferFailed, TransferCorrupt):
            for q in shared + promoted + private:
                act.pool.release(q)
            rem.free_slot(_STAGING_SLOT)
            raise
        pages = shared + promoted + private
        row = np.full((act.max_pages,), NULL_PAGE, np.int32)
        row[:n_pages] = pages
        # install: block-table row + true prompt length (exactly what
        # the jitted colocated prefill writes), then scatter the
        # verified tiles into the private pages
        act.cache = act.cache._replace(
            block_tables=act.cache.block_tables.at[slot].set(
                jnp.asarray(row)),
            lengths=act.cache.lengths.at[slot].set(
                jnp.int32(len(toks))))
        if private:
            k_dev, v_dev = tier.shard_fn(k_tile, v_tile)
            act.cache = self._insert(
                act.cache, jnp.asarray(private, jnp.int32), k_dev,
                v_dev)
        act._slot_pages[slot] = list(pages)
        if act.prefix_sharing:
            act.pool.register_prefix(keys, pages)
        rem.free_slot(_STAGING_SLOT)
        self.stats.remote_prefills += 1
        ticks = self._handoff_ticks(len(private), attempts, tpp)
        self._stage_charge(ticks, promote_ticks, rname)
        tier.observe_ticks(rname, ticks + promote_ticks)
        # the logits hop replicas with the pages (a 1 x vocab row —
        # noise next to the tiles); values survive the host round-trip
        # bit-for-bit
        return jnp.asarray(np.asarray(logits))

    def _ship_pages(self, rem, toks, src_pages, rname: str, rhealth):
        """Move the private pages over the channel and return
        ``(k_tile, v_tile, attempts, ticks_per_page, tier)`` — the
        pool engine overrides this to try the device-to-device reshard
        first and degrade to this host-staged path on
        :class:`ReshardFailed`."""
        k_tile, v_tile, attempts = self.transfer.ship(
            rem, toks, src_pages, replica=rname, health=rhealth)
        return (k_tile, v_tile, attempts, self.handoff_ticks_per_page,
                self.transfer)

    def _handoff_ticks(self, shipped_pages: int, attempts: int,
                       tpp: Optional[float] = None) -> int:
        """Deterministic clock cost of a delivered handoff: the shipped
        bytes at ``tpp`` ticks per page (the link's rate —
        ``handoff_ticks_per_page`` for the host bounce; the pool's
        per-link ICI/DCN rates are cheaper; a page is a small fraction
        of a decode step's HBM read and the cost-tier entries pin the
        ratios), floored at one control tick, plus one backoff tick per
        failed attempt."""
        if tpp is None:
            tpp = self.handoff_ticks_per_page
        moved = int(np.ceil(shipped_pages * tpp))
        return max(1, moved) + (attempts - 1) * self.backoff_ticks

    def _stage_charge(self, ticks: int, promote_ticks: int,
                      rname: str) -> None:
        """Stage the admission's deterministic clock charge for the
        router's ``pop_admit_charge`` handshake. The pair charges the
        handoff serially; the pool engine overrides this with the
        link-overlap model (concurrent handoffs on distinct links
        share the same wall ticks)."""
        self._admit_charge = ticks + promote_ticks

    # -- audit / diagnostics over BOTH replicas -------------------------

    def check_invariants(self) -> bool:
        self.active.check_invariants()
        self.remote.check_invariants()
        return True

    def pool_snapshot(self) -> Dict:
        return {"active": {"replica": self._active_name,
                           **self.active.pool_snapshot()},
                "remote": {"replica": self._remote_name,
                           **self.remote.pool_snapshot()}}

    def pool_gauges(self) -> Dict[str, float]:
        # the tick gauges track the pool the slots live in; the remote
        # pool's story is told by the per-replica transfer metrics
        return self.active.pool_gauges()


class _PoolEngine(_DisaggEngine):
    """The N x M composite behind :class:`PoolRouter`: the pair
    engine's machinery generalized to per-role replica pools. One
    decode replica is ACTIVE (its slots back the scheduler); the other
    decode replicas are idle failover targets chosen by pages-free
    headroom; prefill admissions route across the prefill pool by
    measured load. Handoffs try the device-to-device
    :class:`~apex_tpu.serving.transfer.PageReshard` first (per-link
    ICI/DCN tick pricing from the replica pair's mesh placement) and
    degrade to the host-staged :class:`PageTransfer` on
    :class:`ReshardFailed`. The admission clock uses the link-overlap
    model: handoffs routed to DISTINCT prefill replicas within one
    admission pass overlap on the wall clock, so the pass is charged
    the horizon increase, not the sum — with one prefill replica this
    reduces exactly to the pair's serial charge."""

    def __init__(self, prefills: Sequence, decodes: Sequence,
                 transfer: PageTransfer,
                 reshard: Optional[PageReshard],
                 handoff_ticks_per_page: float,
                 ici_ticks_per_page: float,
                 dcn_ticks_per_page: float,
                 backoff_ticks: int,
                 recover_after: int,
                 placement: Optional[Mapping[str, int]]):
        # delegation table FIRST (__getattr__ consults it)
        pnames, dnames = _pool_names(len(prefills), len(decodes))
        self._replicas = dict(zip(pnames + dnames,
                                  list(prefills) + list(decodes)))
        self.prefill_names = pnames
        self.decode_names = dnames
        self._order = pnames + dnames
        self._active_name = dnames[0]
        self._remote_name = pnames[0]  # base-class seam; pool routing
        self.transfer = transfer       # picks per admission instead
        self.reshard = reshard
        self.handoff_ticks_per_page = float(handoff_ticks_per_page)
        self.ici_ticks_per_page = float(ici_ticks_per_page)
        self.dcn_ticks_per_page = float(dcn_ticks_per_page)
        self.backoff_ticks = int(backoff_ticks)
        self.placement = dict(placement or {})
        eng0 = self._replicas[self._active_name]
        self.injector = eng0.injector
        self.tracer = eng0.tracer
        self.stats = eng0.stats
        self.health = {
            name: ReplicaHealth(name, registry=self.tracer.registry,
                                recover_after=recover_after)
            for name in self._order}
        self._insert = make_insert_pages_fn()
        self._admit_charge: Optional[int] = None
        self._pass_busy: Dict[str, int] = {}
        self._route_hot: Dict[str, object] = {}
        self._load_hot: Dict[str, object] = {}
        # tenancy threading: last prefill replica routed per tenant —
        # a deterministic affinity tiebreak in the routing score
        # (prefix locality for a tenant's traffic), consulted only
        # when the scheduler stamps admission_tenant (tenancy mode)
        self._tenant_affinity: Dict[str, str] = {}

    # -- pool observability ---------------------------------------------

    def _route_mark(self, reason: str) -> None:
        c = self._route_hot.get(reason)
        if c is None:
            c = self._route_hot[reason] = self.tracer.registry.counter(
                "serving_pool_routing_total",
                help="prefill routing decisions by reason (load = "
                     "least-loaded pick, fallback = pool_route fault "
                     "degraded to fixed order, colocated = no "
                     "routable prefill replica, degraded = "
                     "transfer/replica fault forced colocated)",
                labels={"reason": reason})
        c.inc()

    def _load_gauge(self, name: str):
        g = self._load_hot.get(name)
        if g is None:
            g = self._load_hot[name] = self.tracer.registry.gauge(
                "serving_pool_replica_load",
                help="link ticks routed to this prefill replica in "
                     "the current admission pass (the routing score's "
                     "queue-depth term)",
                labels={"replica": name})
        return g

    # -- admission pass state -------------------------------------------

    def begin_admission_pass(self) -> None:
        """Reset the per-pass link-busy horizon — the router calls
        this at the top of every admission pass (tick), before the
        health probes, so charge staging is replay-exact."""
        self._pass_busy.clear()
        for name in self.prefill_names:
            self._load_gauge(name).set(0.0)

    # -- load-based prefill routing -------------------------------------

    def _load_key(self, name: str):
        """Routing score, lower is better: health rung first (healthy
        before degraded), then link ticks already routed to the
        replica this pass (queue depth), then the admitting tenant's
        replica affinity (the replica that last served the tenant —
        prefix locality; a constant when tenancy is off, so the
        untenanted key is unchanged), then pages-free headroom, then
        fixed pool order. Placement may shift with tenancy, streams
        may not: committed tokens are placement-invariant."""
        tenant = self.admission_tenant
        affine = 0 if (tenant is not None
                       and self._tenant_affinity.get(tenant) == name) \
            else 1
        return (-HEALTH_STATES.index(self.health[name].state),
                self._pass_busy.get(name, 0),
                affine,
                -self._replicas[name].pool.num_free,
                self._order.index(name))

    def _note_route(self, name: str) -> str:
        """Record the pick as the admitting tenant's affinity replica
        for the next admission's tiebreak; returns the pick."""
        tenant = self.admission_tenant
        if tenant is not None:
            self._tenant_affinity[tenant] = name
        return name

    def _route_prefill(self) -> Optional[str]:
        """Pick the prefill replica for one remote admission, or None
        to serve colocated. Draws the ``pool_route`` fault site once
        per remote admission: a fired draw degrades the pick to the
        FIRST routable replica in fixed pool order (a routing-policy
        fault can shift placement, never a stream)."""
        cands = [n for n in self.prefill_names
                 if n != self._active_name and self.health[n].routable]
        if not cands:
            self._route_mark("colocated")
            return None
        for n in cands:
            self._load_gauge(n).set(self._pass_busy.get(n, 0))
        fired, _ = self.injector.draw("pool_route")
        if fired:
            self.stats.route_fallbacks += 1
            self._route_mark("fallback")
            return self._note_route(cands[0])
        self._route_mark("load")
        return self._note_route(min(cands, key=self._load_key))

    def prefill(self, slot: int, prompt: Sequence[int]):
        trc = self.tracer
        rname = self._route_prefill()
        if rname is not None:
            try:
                return self._remote_prefill(slot, prompt, rname)
            except (TransferFailed, TransferCorrupt,
                    ReplicaUnavailable) as e:
                # degrade, don't fail — exactly the pair's ladder
                if trc.enabled:
                    trc.instant("failover", slot=slot,
                                cause=type(e).__name__, replica=rname)
                self._route_mark("degraded")
        self.stats.colocated_prefills += 1
        return self.active.prefill(slot, prompt)

    # -- two-tier handoff -----------------------------------------------

    def _link_tpp(self, rname: str) -> float:
        """Ticks per page for the (source, active) link, from mesh
        placement: same slice id rides the ICI rate, different slices
        the DCN rate. No reshard channel -> the host-staged rate."""
        if self.reshard is None:
            return self.handoff_ticks_per_page
        src = self.placement.get(rname, 0)
        dst = self.placement.get(self._active_name, 0)
        return (self.ici_ticks_per_page if src == dst
                else self.dcn_ticks_per_page)

    def _ship_pages(self, rem, toks, src_pages, rname: str, rhealth):
        if self.reshard is None:
            return super()._ship_pages(rem, toks, src_pages, rname,
                                       rhealth)
        try:
            k_tile, v_tile, attempts = self.reshard.ship(
                rem, toks, src_pages, replica=rname, health=rhealth)
            return (k_tile, v_tile, attempts, self._link_tpp(rname),
                    self.reshard)
        except ReshardFailed as e:
            # the d2d link lost its whole budget: degrade to the
            # host-staged tier, carrying the burned attempts into the
            # backoff charge (each failed reshard attempt cost real
            # wall time). A host-tier exhaustion after this propagates
            # and the admission falls back colocated as usual.
            if self.tracer.enabled:
                self.tracer.instant("failover", cause="ReshardFailed",
                                    replica=rname, tier="host_staged",
                                    corrupt=e.corrupt)
            burned = e.attempts
        k_tile, v_tile, attempts = self.transfer.ship(
            rem, toks, src_pages, replica=rname, health=rhealth)
        return (k_tile, v_tile, burned + attempts,
                self.handoff_ticks_per_page, self.transfer)

    # -- link-overlap clock charging ------------------------------------

    def _stage_charge(self, ticks: int, promote_ticks: int,
                      rname: str) -> None:
        """Charge this admission the HORIZON INCREASE of the per-pass
        link-busy model, not the serial handoff cost: handoffs routed
        to distinct prefill replicas in one pass overlap on the wall
        clock (distinct source links), so only the pass's critical
        path costs ticks. Floored at one control tick per admission;
        promote ticks are active-engine work and stay serial. With a
        single prefill replica every handoff extends the same link, so
        the charge is exactly the pair router's."""
        old_h = max(self._pass_busy.values(), default=0)
        self._pass_busy[rname] = self._pass_busy.get(rname, 0) + ticks
        new_h = max(self._pass_busy.values())
        self._admit_charge = max(1, new_h - old_h) + promote_ticks
        self._load_gauge(rname).set(self._pass_busy[rname])

    # -- N-way failover / placement -------------------------------------

    @property
    def active_borrowed(self) -> bool:
        """True when a prefill replica is serving as the active decode
        engine (the last rung of the failover ladder before
        last-replica-standing)."""
        return self._active_name in self.prefill_names

    def pick_active_target(self) -> Optional[str]:
        """Where the slots should move when the active replica goes
        down: the routable replica with the most pages-free headroom,
        decode siblings before prefill borrows, fixed order breaking
        ties. None = nobody routable — last replica standing keeps
        serving on the incumbent."""
        cands = [n for n in self._order
                 if n != self._active_name and self.health[n].routable]
        if not cands:
            return None
        return max(cands, key=lambda n: (n in self.decode_names,
                                         self._replicas[n].pool.num_free,
                                         -self._order.index(n)))

    def pick_home_decode(self) -> Optional[str]:
        """The decode replica to rebalance back onto once one is
        routable again (only consulted while the active is a borrowed
        prefill replica)."""
        cands = [n for n in self.decode_names
                 if n != self._active_name and self.health[n].routable]
        if not cands:
            return None
        return max(cands, key=lambda n: (self._replicas[n].pool.num_free,
                                         -self._order.index(n)))

    def set_active(self, name: str) -> None:
        """Move the decode placement (the router drained the slots
        first) — every move emits the ``rebalance`` lifecycle instant
        and counts in ``stats.rebalances``."""
        old = self._active_name
        self._active_name = name
        self.stats.rebalances += 1
        if self.tracer.enabled:
            self.tracer.instant("rebalance", replica=old, target=name)

    # -- audit over the WHOLE pool --------------------------------------

    def check_invariants(self) -> bool:
        for eng in self._replicas.values():
            eng.check_invariants()
        return True

    def pool_snapshot(self) -> Dict:
        return {name: {"active": name == self._active_name,
                       **eng.pool_snapshot()}
                for name, eng in self._replicas.items()}


def _preempt_drain(router, cause: str) -> int:
    """Drain every occupied slot back to the queue FRONT in submission
    order (the preemption resume path — re-prefill from prompt +
    generated, sampling keys fold ``(seed, n_generated)``, so committed
    streams stay bit-identical) and free the slots on the CURRENT
    active replica. Shared by the pair's failover and the pool's
    failover/rebalance moves; returns the drained slot count."""
    eng = router.engine
    trc = router.tracer
    old = eng.active
    occupied = [(i, s) for i, s in enumerate(router._slots)
                if s is not None]
    for i, s in sorted(occupied, key=lambda t: t[1].request_id,
                       reverse=True):
        if trc.enabled:
            trc.instant("preempted", request_id=s.request_id,
                        slot=i, cause=cause)
        router._queue.appendleft((s.request_id, s.request,
                                  list(s.generated)))
        router._slots[i] = None
        old.free_slot(i)
    return len(occupied)


class DisaggregatedRouter(ContinuousBatchingScheduler):
    """The two-replica serving tier (see module doc): a
    ``ContinuousBatchingScheduler`` over a :class:`_DisaggEngine`
    composite, plus per-tick health probes and mid-stream failover.

    ``transfer_max_retries`` bounds re-attempts per page handoff;
    ``handoff_ticks_per_page`` / ``backoff_ticks`` set the
    deterministic clock cost of a delivered handoff (see
    ``_handoff_ticks``); ``recover_after`` is each replica's
    consecutive-success hysteresis on the way back up the health
    ladder. All remaining keywords are the base scheduler's
    (``chunk_tokens`` excepted — chunked prefill stays colocated)."""

    def __init__(self, prefill_engine, decode_engine, eos_id: int, *,
                 transfer_max_retries: int = 2,
                 handoff_ticks_per_page: float = 0.125,
                 backoff_ticks: int = 1,
                 recover_after: int = 2,
                 transfer: Optional[PageTransfer] = None,
                 **kwargs):
        _validate_replicas(prefill_engine, decode_engine)
        if kwargs.get("chunk_tokens") is not None:
            raise ValueError(
                "chunked prefill stays colocated: the disaggregated "
                "router runs monolithic admission prefill on the "
                "remote replica (the chunks would serialize against "
                "the very decode ticks disaggregation unblocks)")
        tracer = decode_engine.tracer
        registry = tracer.registry
        health = {name: ReplicaHealth(name, registry=registry,
                                      recover_after=recover_after)
                  for name in _REPLICA_ORDER}
        if transfer is None:
            transfer = PageTransfer(injector=decode_engine.injector,
                                    tracer=tracer,
                                    stats=decode_engine.stats,
                                    max_retries=transfer_max_retries)
        engine = _DisaggEngine(prefill_engine, decode_engine, transfer,
                               health, handoff_ticks_per_page,
                               backoff_ticks)
        super().__init__(engine, eos_id, **kwargs)

    @property
    def health(self) -> Dict[str, ReplicaHealth]:
        return self.engine.health

    def _admit(self) -> None:
        eng = self.engine
        eng.health_tick()
        if eng.active_down and eng.remote_routable:
            self._failover()
        super()._admit()

    def _failover(self) -> None:
        """The ACTIVE replica went down mid-stream: drain every
        occupied slot back to the queue FRONT in submission order (the
        preemption resume path — bit-identical continuation) and swap
        roles; admission continues this same tick on the survivor.
        When BOTH replicas are down the router keeps serving on the
        incumbent instead (last replica standing: health gates
        routing, not survival)."""
        eng = self.engine
        trc = self.tracer
        if trc.enabled:
            trc.instant("failover",
                        slots=sum(s is not None for s in self._slots),
                        replica=eng.active_name)
        _preempt_drain(self, "failover")
        eng.switch_active()
        self.stats.failovers += 1


class PoolRouter(ContinuousBatchingScheduler):
    """The pool-scale serving tier: N prefill x M decode replicas
    behind ONE admission queue (see module doc) — a
    ``ContinuousBatchingScheduler`` over a :class:`_PoolEngine`
    composite. Prefill admissions route by measured load (health rung,
    per-pass link busy, pages-free headroom); one decode replica backs
    the slots and its siblings are failover targets picked by
    pages-free headroom; page handoffs ride the device-to-device
    :class:`~apex_tpu.serving.transfer.PageReshard` by default, priced
    per link from ``placement`` (same slice id -> ``ici_ticks_per_page``,
    different -> ``dcn_ticks_per_page``), degrading to the host-staged
    :class:`~apex_tpu.serving.transfer.PageTransfer` at
    ``handoff_ticks_per_page`` on :class:`ReshardFailed`.

    ``prefill_engines`` / ``decode_engines`` are sequences of paged
    engines (a bare engine works too — the 1x1 pool); ALL replicas
    must share one injector, one tracer, and one PrefixRegistry host
    tier (or none), with identical geometry — validated pairwise
    across the whole pool. ``placement`` maps replica name
    (``prefill0``.. / ``decode0``..; the 1x1 pool keeps the bare
    ``prefill``/``decode`` names) to a mesh slice id; unmapped
    replicas sit on slice 0. ``use_reshard=False`` (or
    ``reshard=None`` with it) pins the pool to host staging.

    Committed streams are bit-identical to the 1x1
    :class:`DisaggregatedRouter` (and to colocated) through every
    routing, resharding, failover, and fault path: placement never
    touches sampling keys, drains resume bit-exactly, and fault
    ladders only ever degrade WHERE work runs, never what commits."""

    def __init__(self, prefill_engines, decode_engines, eos_id: int, *,
                 transfer_max_retries: int = 2,
                 handoff_ticks_per_page: float = 0.125,
                 ici_ticks_per_page: float = 0.03125,
                 dcn_ticks_per_page: float = 0.0625,
                 backoff_ticks: int = 1,
                 recover_after: int = 2,
                 placement: Optional[Mapping[str, int]] = None,
                 transfer: Optional[PageTransfer] = None,
                 reshard: Optional[PageReshard] = None,
                 use_reshard: bool = True,
                 **kwargs):
        prefills = _as_pool(prefill_engines)
        decodes = _as_pool(decode_engines)
        _validate_replicas(prefills, decodes)
        if kwargs.get("chunk_tokens") is not None:
            raise ValueError(
                "chunked prefill stays colocated: the pool router "
                "runs monolithic admission prefill on the remote "
                "replicas (the chunks would serialize against the "
                "very decode ticks disaggregation unblocks)")
        known = set(_pool_names(len(prefills), len(decodes))[0]) \
            | set(_pool_names(len(prefills), len(decodes))[1])
        unknown = set(placement or {}) - known
        if unknown:
            raise ValueError(
                f"placement names unknown replicas {sorted(unknown)}; "
                f"pool replicas are {sorted(known)}")
        eng0 = decodes[0]
        if transfer is None:
            transfer = PageTransfer(injector=eng0.injector,
                                    tracer=eng0.tracer,
                                    stats=eng0.stats,
                                    max_retries=transfer_max_retries)
        if reshard is None and use_reshard:
            reshard = PageReshard(injector=eng0.injector,
                                  tracer=eng0.tracer,
                                  stats=eng0.stats,
                                  max_retries=transfer_max_retries)
        if not use_reshard:
            reshard = None
        engine = _PoolEngine(prefills, decodes, transfer, reshard,
                             handoff_ticks_per_page,
                             ici_ticks_per_page, dcn_ticks_per_page,
                             backoff_ticks, recover_after, placement)
        super().__init__(engine, eos_id, **kwargs)

    @property
    def health(self) -> Dict[str, ReplicaHealth]:
        return self.engine.health

    def _admit(self) -> None:
        eng = self.engine
        eng.begin_admission_pass()
        eng.health_tick()
        if eng.active_down:
            target = eng.pick_active_target()
            if target is not None:
                self._move_active(target, cause="failover")
                self.stats.failovers += 1
            # else: last replica standing — keep serving on the
            # incumbent (health gates routing, not survival)
        elif eng.active_borrowed:
            target = eng.pick_home_decode()
            if target is not None:
                # a decode replica recovered: move the slots home so
                # the borrowed prefill replica rejoins its pool
                self._move_active(target, cause="rebalance")
        super()._admit()

    def _move_active(self, target: str, cause: str) -> None:
        """Drain the occupied slots (bit-identical preempt-resume) and
        move the decode placement to ``target``; admission continues
        this same tick on the new active replica."""
        eng = self.engine
        trc = self.tracer
        if trc.enabled and cause == "failover":
            trc.instant("failover",
                        slots=sum(s is not None for s in self._slots),
                        replica=eng.active_name, target=target)
        _preempt_drain(self, cause)
        eng.set_active(target)

"""Disaggregated prefill/decode serving: two engines, one scheduler.

Prefill is MXU-bound and decode is HBM-bound (BASELINE r8/r9), so the
"millions of users" topology runs them on SEPARATE replicas — prompt
forwards on a prefill engine, decode ticks on a decode engine — with
the finished prompt pages shipped between them by the fault-tolerant
:class:`~apex_tpu.serving.transfer.PageTransfer` channel, keyed and
deduped by the chained content hashes of
:func:`~apex_tpu.serving.paging.prefix_page_keys`.

The design reuses the whole serving stack instead of forking it: the
:class:`DisaggregatedRouter` IS a
:class:`~apex_tpu.serving.scheduler.ContinuousBatchingScheduler` whose
engine is a composite (:class:`_DisaggEngine`) presenting the standard
``DecodeEngine`` interface. Every decode-path method delegates to the
ACTIVE replica (the one backing the slots); only ``prefill`` routes:

1. remote replica ``routable`` → run the prompt forward there, ship
   the non-shared pages across, install them into pages the active
   pool allocated (same order a local prefill would), register the
   prefix chain, return the logits. The slot's cache row ends up
   BITWISE identical to a colocated prefill — same jitted program,
   same inputs, pages copied verbatim — which is why fault-free
   disaggregated streams are integer-identical to the colocated
   scheduler's.
2. remote down, transfer budget exhausted, payload quarantined, or
   the remote pool refused the prompt → typed error
   (:class:`~apex_tpu.serving.health.TransferFailed` /
   :class:`~apex_tpu.serving.health.TransferCorrupt` /
   :class:`~apex_tpu.serving.health.ReplicaUnavailable`), caught here,
   and the admission is served COLOCATED on the active engine — the
   request never observes the degradation (graceful ladder: remote →
   colocated → scheduler retry budget → typed outcome).

Health and failover: the router draws the ``replica_health`` fault
site once per replica per tick (fixed order — replay-exact) and folds
the probes into each replica's
:class:`~apex_tpu.serving.health.ReplicaHealth` ladder alongside real
transfer/prefill outcomes. A DOWN remote just stops receiving
prefills. A DOWN *active* replica triggers mid-stream failover: every
occupied slot is drained back to the queue front (the preemption
resume path — re-prefill from prompt + generated, sampling keys fold
``(seed, n_generated)``, so committed streams stay bit-identical) and
the replicas swap roles; the recovered ex-active replica later rejoins
as the remote prefill target. Admission, deadlines, retry budgets, the
progress watchdog, and flight-recorder attachment all come from the
base scheduler unchanged — a dead replica produces typed outcomes,
never a hang.

Clock accounting: a remote prefill runs CONCURRENTLY with the active
replica's decode ticks, so the router does not charge its sequential
depth to the work-charged tick clock the way colocated admission does
— it charges the deterministic handoff cost instead
(``handoff_ticks_per_page`` per shipped page, plus one backoff tick
per retry attempt, observed in the ``serving_transfer_ticks``
histogram). That unblocked-decode gap is exactly the p99 ITL win the
``serving_disagg_vs_colocated`` A/B pair measures; sampling keys never
see the clock, so streams are unaffected.

Scope: both replicas must be PAGED engines with identical model
config/geometry and SHARED injector+tracer (one deterministic fault
and event sequence). Chunked prefill, model drafters/tree speculation,
and int8 page pools stay colocated-only for now — the constructor
refuses them typed.

This module is host state (router bookkeeping, health ladders) —
APX401 registers it like ``serving.health``/``serving.faults``.
"""

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.cache import NULL_PAGE, max_pages_per_slot
from apex_tpu.serving.faults import FaultInjector, InjectedFault
from apex_tpu.serving.health import (PoolExhausted, ReplicaHealth,
                                     ReplicaUnavailable, TransferCorrupt,
                                     TransferFailed)
from apex_tpu.serving.paging import prefix_page_keys
from apex_tpu.serving.scheduler import ContinuousBatchingScheduler
from apex_tpu.serving.transfer import PageTransfer, make_insert_pages_fn

#: The remote replica prefills every admission into this slot, then
#: frees it once the pages have shipped — admissions are sequential,
#: so one staging slot suffices and the remote pool's prefix registry
#: (not its slots) carries its cross-request dedup.
_STAGING_SLOT = 0

#: Fixed health-probe order per tick (initial role names — replay
#: depends on draw ORDER, not on which replica currently serves).
_REPLICA_ORDER = ("prefill", "decode")


def _require_same(a, b, attr: str) -> None:
    va, vb = getattr(a, attr), getattr(b, attr)
    if va != vb:
        raise ValueError(
            f"disaggregated replicas must agree on {attr}: "
            f"prefill={va!r} vs decode={vb!r}")


def _validate_replicas(prefill_engine, decode_engine) -> None:
    if prefill_engine is decode_engine:
        raise ValueError("disaggregation needs two engine instances")
    for eng, role in ((prefill_engine, "prefill"),
                      (decode_engine, "decode")):
        if not getattr(eng, "paged", False):
            raise ValueError(
                f"the {role} replica must be a paged engine: the "
                "handoff ships page tiles keyed by prefix_page_keys")
        if getattr(eng.cache, "k_scale", None) is not None:
            raise ValueError(
                "disaggregated serving is not offered over the int8 "
                "page pool: shipped pages would carry page-local "
                "scales quantized against the SENDER's amax sweep; "
                "kv8 keeps colocated serving")
        if eng.draft_model is not None or eng.tree_spec:
            raise ValueError(
                "model drafters / tree speculation stay colocated: "
                "the drafter's lockstep cache would need its own "
                "cross-replica handoff (n-gram spec_k works "
                "disaggregated)")
    for attr in ("cfg", "num_slots", "max_len", "page_size", "buckets",
                 "spec_k", "top_k", "top_p", "adaptive_spec",
                 "prefix_sharing"):
        _require_same(prefill_engine, decode_engine, attr)
    if prefill_engine.host_tier is not decode_engine.host_tier:
        raise ValueError(
            "both replicas must share ONE PrefixRegistry host tier "
            "(or neither): the registry is the global content-"
            "addressed map — split tiers would fork the prefix "
            "namespace (construct both engines with the same "
            "host_tier=)")
    if prefill_engine.injector is not decode_engine.injector:
        raise ValueError(
            "both replicas must share ONE FaultInjector: fault draws "
            "form a single deterministic sequence (construct both "
            "engines with the same injector=)")
    if prefill_engine.tracer is not decode_engine.tracer:
        raise ValueError(
            "both replicas must share ONE Tracer: events, metrics and "
            "the stats view live in a single registry (construct both "
            "engines with the same tracer=)")


class _DisaggEngine:
    """The composite engine behind :class:`DisaggregatedRouter`:
    presents the ``DecodeEngine`` interface over two paged replicas.
    Attribute/method access falls through to the ACTIVE replica (the
    one whose slots the scheduler drives); ``prefill`` routes per the
    module doc. Swappable: :meth:`switch_active` exchanges the roles
    on failover."""

    paged = True

    def __init__(self, prefill_engine, decode_engine,
                 transfer: PageTransfer,
                 health: Dict[str, ReplicaHealth],
                 handoff_ticks_per_page: float,
                 backoff_ticks: int):
        # set the delegation table FIRST: __getattr__ consults it
        self._replicas = {"prefill": prefill_engine,
                          "decode": decode_engine}
        self._active_name = "decode"
        self._remote_name = "prefill"
        self.transfer = transfer
        self.health = health
        self.handoff_ticks_per_page = float(handoff_ticks_per_page)
        self.backoff_ticks = int(backoff_ticks)
        self.injector = decode_engine.injector
        self.tracer = decode_engine.tracer
        self.stats = decode_engine.stats
        self._insert = make_insert_pages_fn()
        self._admit_charge: Optional[int] = None

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_replicas"][
            self.__dict__["_active_name"]], name)

    @property
    def active(self):
        return self._replicas[self._active_name]

    @property
    def remote(self):
        return self._replicas[self._remote_name]

    @property
    def active_name(self) -> str:
        return self._active_name

    @property
    def remote_name(self) -> str:
        return self._remote_name

    # -- health / failover ----------------------------------------------

    def health_tick(self) -> None:
        """One ``replica_health`` probe per replica, fixed order —
        the router calls this at the top of every admission pass, so
        probe draw indices are a pure function of the tick count."""
        for name in _REPLICA_ORDER:
            fired, _ = self.injector.draw("replica_health")
            self.health[name].probe(not fired)

    @property
    def active_down(self) -> bool:
        return not self.health[self._active_name].routable

    @property
    def remote_routable(self) -> bool:
        return self.health[self._remote_name].routable

    def switch_active(self) -> None:
        self._active_name, self._remote_name = (self._remote_name,
                                                self._active_name)

    # -- admission-charge handshake with the router ---------------------

    def pop_admit_charge(self, default: int) -> int:
        # a remote prefill staged its handoff (+ promote) cost here; a
        # colocated one staged on the active engine — delegate so its
        # host-tier repricing (suffix depth + promote ticks) survives
        charge, self._admit_charge = self._admit_charge, None
        if charge is not None:
            return charge
        return self.active.pop_admit_charge(default)

    # -- routed prefill -------------------------------------------------

    def prefill(self, slot: int, prompt: Sequence[int]):
        trc = self.tracer
        if self.remote_routable:
            try:
                return self._remote_prefill(slot, prompt)
            except (TransferFailed, TransferCorrupt,
                    ReplicaUnavailable) as e:
                # degrade, don't fail: the admission is served
                # colocated on the active engine; the request never
                # sees the transfer/replica fault
                if trc.enabled:
                    trc.instant("failover", slot=slot,
                                cause=type(e).__name__,
                                replica=self._remote_name)
        self.stats.colocated_prefills += 1
        return self.active.prefill(slot, prompt)

    def _remote_prefill(self, slot: int, prompt: Sequence[int]):
        act, rem = self.active, self.remote
        rhealth = self.health[self._remote_name]
        toks = [int(t) for t in prompt]
        try:
            logits = rem.prefill(_STAGING_SLOT, toks)
        except PoolExhausted as e:
            # remote CAPACITY, not remote failure: no health demerit,
            # but the admission cannot be staged there right now
            raise ReplicaUnavailable(
                f"remote replica {self._remote_name!r} page pool "
                f"refused the prompt: {e}",
                replica=self._remote_name) from e
        except InjectedFault:
            # a transient device fault on the remote replica: the
            # remote engine rolled its page references back; propagate
            # so the scheduler charges the retry budget exactly like a
            # colocated prefill fault — and let repeated faults walk
            # the replica down the ladder toward colocated routing
            rhealth.probe(False)
            raise
        # the remote prefill staged its OWN admission repricing (it may
        # carry a host tier); the router charges handoff ticks instead
        rem.pop_admit_charge(0)
        # allocate the destination pages in the SAME order a colocated
        # prefill would: longest registered prefix run shared, host-
        # tier promotions extending it, the remainder fresh from the
        # active pool
        keys = prefix_page_keys(toks, act.page_size)
        n_pages = max_pages_per_slot(len(toks), act.page_size)
        shared = act.pool.match_prefix(keys) if act.prefix_sharing \
            else []
        promoted: List[int] = []
        promote_ticks = 0
        if act.host_tier is not None and act.prefix_sharing \
                and len(shared) < n_pages:
            promoted, promote_ticks = act._promote_chain(
                keys, len(shared))
        covered = len(shared) + len(promoted)
        private: List[int] = []
        for _ in range(n_pages - covered):
            p = act.pool.alloc()
            if p is None:
                for q in shared + promoted + private:
                    act.pool.release(q)
                rem.free_slot(_STAGING_SLOT)
                raise PoolExhausted(
                    f"prompt needs {n_pages} pages; pool has "
                    f"{act.pool.num_free} free and nothing left to "
                    "evict", need=n_pages, free=act.pool.num_free,
                    cached=act.pool.num_cached)
            private.append(p)
        src_pages = rem._slot_pages[_STAGING_SLOT][covered:n_pages]
        self.stats.transfer_pages_deduped += covered
        try:
            k_tile, v_tile, attempts = self.transfer.ship(
                rem, toks, src_pages, replica=self._remote_name,
                health=rhealth)
        except (TransferFailed, TransferCorrupt):
            for q in shared + promoted + private:
                act.pool.release(q)
            rem.free_slot(_STAGING_SLOT)
            raise
        pages = shared + promoted + private
        row = np.full((act.max_pages,), NULL_PAGE, np.int32)
        row[:n_pages] = pages
        # install: block-table row + true prompt length (exactly what
        # the jitted colocated prefill writes), then scatter the
        # verified tiles into the private pages
        act.cache = act.cache._replace(
            block_tables=act.cache.block_tables.at[slot].set(
                jnp.asarray(row)),
            lengths=act.cache.lengths.at[slot].set(
                jnp.int32(len(toks))))
        if private:
            k_dev, v_dev = self.transfer.shard_fn(k_tile, v_tile)
            act.cache = self._insert(
                act.cache, jnp.asarray(private, jnp.int32), k_dev,
                v_dev)
        act._slot_pages[slot] = list(pages)
        if act.prefix_sharing:
            act.pool.register_prefix(keys, pages)
        rem.free_slot(_STAGING_SLOT)
        self.stats.remote_prefills += 1
        ticks = self._handoff_ticks(len(private), attempts) \
            + promote_ticks
        self._admit_charge = ticks
        self.transfer.observe_ticks(self._remote_name, ticks)
        # the logits hop replicas with the pages (a 1 x vocab row —
        # noise next to the tiles); values survive the host round-trip
        # bit-for-bit
        return jnp.asarray(np.asarray(logits))

    def _handoff_ticks(self, shipped_pages: int, attempts: int) -> int:
        """Deterministic clock cost of a delivered handoff: the shipped
        bytes at ``handoff_ticks_per_page`` (a page is a small fraction
        of a decode step's HBM read — the cost-tier entry pins the
        ratio), floored at one control tick, plus one backoff tick per
        failed attempt."""
        moved = int(np.ceil(shipped_pages * self.handoff_ticks_per_page))
        return max(1, moved) + (attempts - 1) * self.backoff_ticks

    # -- audit / diagnostics over BOTH replicas -------------------------

    def check_invariants(self) -> bool:
        self.active.check_invariants()
        self.remote.check_invariants()
        return True

    def pool_snapshot(self) -> Dict:
        return {"active": {"replica": self._active_name,
                           **self.active.pool_snapshot()},
                "remote": {"replica": self._remote_name,
                           **self.remote.pool_snapshot()}}

    def pool_gauges(self) -> Dict[str, float]:
        # the tick gauges track the pool the slots live in; the remote
        # pool's story is told by the per-replica transfer metrics
        return self.active.pool_gauges()


class DisaggregatedRouter(ContinuousBatchingScheduler):
    """The two-replica serving tier (see module doc): a
    ``ContinuousBatchingScheduler`` over a :class:`_DisaggEngine`
    composite, plus per-tick health probes and mid-stream failover.

    ``transfer_max_retries`` bounds re-attempts per page handoff;
    ``handoff_ticks_per_page`` / ``backoff_ticks`` set the
    deterministic clock cost of a delivered handoff (see
    ``_handoff_ticks``); ``recover_after`` is each replica's
    consecutive-success hysteresis on the way back up the health
    ladder. All remaining keywords are the base scheduler's
    (``chunk_tokens`` excepted — chunked prefill stays colocated)."""

    def __init__(self, prefill_engine, decode_engine, eos_id: int, *,
                 transfer_max_retries: int = 2,
                 handoff_ticks_per_page: float = 0.125,
                 backoff_ticks: int = 1,
                 recover_after: int = 2,
                 transfer: Optional[PageTransfer] = None,
                 **kwargs):
        _validate_replicas(prefill_engine, decode_engine)
        if kwargs.get("chunk_tokens") is not None:
            raise ValueError(
                "chunked prefill stays colocated: the disaggregated "
                "router runs monolithic admission prefill on the "
                "remote replica (the chunks would serialize against "
                "the very decode ticks disaggregation unblocks)")
        tracer = decode_engine.tracer
        registry = tracer.registry
        health = {name: ReplicaHealth(name, registry=registry,
                                      recover_after=recover_after)
                  for name in _REPLICA_ORDER}
        if transfer is None:
            transfer = PageTransfer(injector=decode_engine.injector,
                                    tracer=tracer,
                                    stats=decode_engine.stats,
                                    max_retries=transfer_max_retries)
        engine = _DisaggEngine(prefill_engine, decode_engine, transfer,
                               health, handoff_ticks_per_page,
                               backoff_ticks)
        super().__init__(engine, eos_id, **kwargs)

    @property
    def health(self) -> Dict[str, ReplicaHealth]:
        return self.engine.health

    def _admit(self) -> None:
        eng = self.engine
        eng.health_tick()
        if eng.active_down and eng.remote_routable:
            self._failover()
        super()._admit()

    def _failover(self) -> None:
        """The ACTIVE replica went down mid-stream: drain every
        occupied slot back to the queue FRONT in submission order (the
        preemption resume path — bit-identical continuation) and swap
        roles; admission continues this same tick on the survivor.
        When BOTH replicas are down the router keeps serving on the
        incumbent instead (last replica standing: health gates
        routing, not survival)."""
        eng = self.engine
        trc = self.tracer
        occupied = [(i, s) for i, s in enumerate(self._slots)
                    if s is not None]
        if trc.enabled:
            trc.instant("failover", slots=len(occupied),
                        replica=eng.active_name)
        old = eng.active
        for i, s in sorted(occupied, key=lambda t: t[1].request_id,
                           reverse=True):
            if trc.enabled:
                trc.instant("preempted", request_id=s.request_id,
                            slot=i, cause="failover")
            self._queue.appendleft((s.request_id, s.request,
                                    list(s.generated)))
            self._slots[i] = None
            old.free_slot(i)
        eng.switch_active()
        self.stats.failovers += 1

"""Fault-tolerant cross-replica page handoff for disaggregated serving.

The disaggregated tier (``serving.router``) runs prefill and decode on
separate engines; what moves between them is the prompt's completed KV
pages — page-sized ``(layers, heads, page_size, head_dim)`` tiles
gathered from the prefill replica's pool and scattered into pages the
decode replica's :class:`~apex_tpu.serving.paging.PagePool` allocated.
This module owns that channel, and its design goal is the robustness
contract, not the copy itself:

- **content addressing** — every shipped batch is identified by the
  prompt's chained sha256 prefix keys
  (:func:`~apex_tpu.serving.paging.prefix_page_keys`, canonical
  ``struct.pack`` encoding). The receiver already holding a key's page
  skips the bytes entirely (cross-replica dedup — the same sharing the
  local prefix cache provides), and the final chain key is folded into
  the transfer checksum so a payload can never be installed under the
  wrong prompt.
- **integrity** — the sender checksums the staged tile bytes plus the
  chain key (sha256); the receiver recomputes before installing.
  A mismatch (the ``page_recv`` fault site flips one staged byte,
  payload-selected) QUARANTINES the payload: the tiles are discarded
  without touching the receiving cache, so corrupt KV rows are never
  attended. Typed: :class:`~apex_tpu.serving.health.TransferCorrupt`.
- **retry budget** — each handoff gets ``max_retries`` re-attempts
  (``page_send`` drops count too); exhaustion raises
  :class:`~apex_tpu.serving.health.TransferFailed` /
  ``TransferCorrupt`` and the router serves the admission colocated.
  Every outcome is also an observation for the remote replica's
  :class:`~apex_tpu.serving.health.ReplicaHealth` ladder.
- **observability** — one ``page_transfer`` tracer span per handoff
  (retries inside the span), per-replica labeled counters
  (``serving_transfer_src_bytes_total`` etc.), and the
  ``serving_transfer_ticks`` histogram of the deterministic tick cost
  the router charges per handoff.

Device mechanics: the jitted :func:`make_extract_pages_fn` /
:func:`make_insert_pages_fn` pair gathers/scatters tiles by page id
(one executable per distinct page count — prompts are bucketed, so the
count set is small), staged through the host. On a real two-slice
topology the staging hop is the ``device_get``/``device_put`` pair of
``partition.rules.make_shard_and_gather_fns`` over the two sub-meshes
of ``partition.mesh.make_mesh`` — :func:`make_tile_transfer_fns` builds
exactly that pair from the pool's TP layout (heads over ``model``);
the single-device default degenerates to a host round-trip, which is
also what keeps CPU chaos tests byte-faithful.

The :class:`PageTransfer` object itself is host state (attempt
counters, metric handles) — APX401 registers this module accordingly;
the jitted extract/insert closures touch none of it.
"""

import hashlib
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.faults import FaultInjector
from apex_tpu.serving.health import (ServingStats, TransferCorrupt,
                                     TransferFailed)
from apex_tpu.serving.observe import Tracer

#: ``serving_transfer_ticks`` histogram buckets: handoffs are charged
#: a handful of decode-step equivalents, not hundreds.
TRANSFER_TICK_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                         24.0, 32.0)


def make_extract_pages_fn() -> Callable:
    """Jitted ``(cache, page_ids) -> (k_tile, v_tile)``: gather the
    identified pages out of a paged cache's pool — the sender half of
    the handoff. Tiles are ``(layers, n_pages, heads, page_size,
    head_dim)`` in the pool dtype. Read-only (no donation): the source
    cache keeps serving its own slots."""

    def extract(cache, page_ids):
        return cache.k[:, page_ids], cache.v[:, page_ids]

    return jax.jit(extract)


def make_insert_pages_fn() -> Callable:
    """Jitted ``(cache, page_ids, k_tile, v_tile) -> cache``: scatter
    received tiles into the identified pages of the receiving pool —
    the receiver half of the handoff, and the cost-tier entry that
    prices the handoff bytes (``gpt_page_handoff_medium``). The cache
    is donated: the scatter is an in-place page write, exactly like a
    decode step's row append."""

    def insert(cache, page_ids, k_tile, v_tile):
        return cache._replace(k=cache.k.at[:, page_ids].set(k_tile),
                              v=cache.v.at[:, page_ids].set(v_tile))

    return jax.jit(insert, donate_argnums=(0,))


def make_extract_pages_quant_fn() -> Callable:
    """:func:`make_extract_pages_fn` for the int8 pool: gathers the
    per-page-per-head fp32 scale planes ``(layers, n_pages, heads)``
    TOGETHER with the int8 tiles — ``(cache, page_ids) -> (k_tile,
    v_tile, k_scale, v_scale)``. A page's rows are meaningless without
    the scales they were quantized against, so the spill/promote wire
    payload always carries all four (and still comes out at roughly
    half a bf16 payload's bytes — the capacity argument for the int8
    host tier)."""

    def extract(cache, page_ids):
        return (cache.k[:, page_ids], cache.v[:, page_ids],
                cache.k_scale[:, page_ids], cache.v_scale[:, page_ids])

    return jax.jit(extract)


def make_insert_pages_quant_fn() -> Callable:
    """:func:`make_insert_pages_fn` for the int8 pool: scatters int8
    tiles AND their fp32 scale planes into the identified pages —
    ``(cache, page_ids, k_tile, v_tile, k_scale, v_scale) -> cache``,
    cache donated (in-place page writes, like a decode step's row
    append). The promoted page is bit-identical to the spilled one:
    same int8 rows, same scales — the quantized analogue of the COW
    clone guarantee."""

    def insert(cache, page_ids, k_tile, v_tile, k_scale, v_scale):
        return cache._replace(
            k=cache.k.at[:, page_ids].set(k_tile),
            v=cache.v.at[:, page_ids].set(v_tile),
            k_scale=cache.k_scale.at[:, page_ids].set(k_scale),
            v_scale=cache.v_scale.at[:, page_ids].set(v_scale))

    return jax.jit(insert, donate_argnums=(0,))


def make_tile_transfer_fns(mesh=None, rules=None) -> Tuple[Callable,
                                                           Callable]:
    """``(gather_fn, shard_fn)`` for page tiles on a real multi-device
    topology: ``gather_fn`` pulls a (possibly TP-sharded) tile pair to
    replicated host arrays on the source sub-mesh, ``shard_fn`` places
    host tiles under the pool's TP spec (heads over ``model``) on the
    destination sub-mesh — the ``make_shard_and_gather_fns`` device_put
    /device_get pair from the partition engine, applied to the tile's
    head axis (axis 2, same as the pool's). Build one pair per sub-mesh
    of ``partition.mesh.make_mesh`` and hand them to
    :class:`PageTransfer`; without them the transfer stages through
    ``np.asarray`` — correct on any topology, optimal on one device."""
    from jax.sharding import PartitionSpec

    from apex_tpu.partition.rules import make_shard_and_gather_fns

    del rules  # the tile layout is fixed by the pool's: heads sharded
    spec = PartitionSpec(None, None, "model")
    shard_fns, gather_fns = make_shard_and_gather_fns(
        {"k": spec, "v": spec}, mesh)

    def gather_fn(k_tile, v_tile):
        return (np.asarray(gather_fns["k"](k_tile)),
                np.asarray(gather_fns["v"](v_tile)))

    def shard_fn(k_tile, v_tile):
        return shard_fns["k"](k_tile), shard_fns["v"](v_tile)

    return gather_fn, shard_fn


def _default_gather(k_tile, v_tile):
    return np.asarray(k_tile), np.asarray(v_tile)


def _default_shard(k_tile, v_tile):
    return k_tile, v_tile


def transfer_checksum(k_tile: np.ndarray, v_tile: np.ndarray,
                      chain_key: bytes) -> bytes:
    """sha256 over the staged tile bytes plus the prompt's final
    chained page key: integrity (bit flips) and identity (a payload
    can only verify against the prompt whose pages it carries) in one
    digest."""
    h = hashlib.sha256()
    h.update(chain_key)
    h.update(np.ascontiguousarray(k_tile).tobytes())
    h.update(np.ascontiguousarray(v_tile).tobytes())
    return h.digest()


class PageTransfer:
    """The fault-tolerant handoff channel (see module doc). One
    instance per router; both replicas' engines share its injector and
    tracer, so fault draws and spans land in a single deterministic
    sequence.

    ``max_retries`` bounds RE-attempts per handoff (total attempts =
    ``max_retries + 1``). ``gather_fn``/``shard_fn`` override the host
    staging hop for real two-mesh topologies
    (:func:`make_tile_transfer_fns`)."""

    def __init__(self, injector: Optional[FaultInjector] = None,
                 tracer: Optional[Tracer] = None,
                 stats: Optional[ServingStats] = None,
                 max_retries: int = 2,
                 gather_fn: Callable = _default_gather,
                 shard_fn: Callable = _default_shard):
        if max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {max_retries}")
        self.injector = injector or FaultInjector()
        self.tracer = tracer if tracer is not None \
            else Tracer(enabled=False)
        self.stats = stats if stats is not None \
            else ServingStats(registry=self.tracer.registry)
        self.max_retries = max_retries
        self.gather_fn = gather_fn
        self.shard_fn = shard_fn
        self._extract = make_extract_pages_fn()
        self._hot = {}

    # -- per-replica labeled metrics ------------------------------------

    def _counters(self, replica: str):
        c = self._hot.get(replica)
        if c is None:
            r = self.tracer.registry
            labels = {"replica": replica}
            c = self._hot[replica] = (
                r.counter("serving_transfer_src_bytes_total",
                          help="page-handoff bytes shipped from this "
                               "replica (verified payloads only)",
                          labels=labels),
                r.counter("serving_transfer_src_retries_total",
                          help="handoff attempts retried against this "
                               "replica", labels=labels),
                r.counter("serving_transfer_src_failures_total",
                          help="handoffs abandoned against this "
                               "replica (budget exhausted)",
                          labels=labels),
                r.histogram("serving_transfer_ticks",
                            buckets=TRANSFER_TICK_BUCKETS,
                            help="deterministic tick cost charged per "
                                 "delivered handoff",
                            labels=labels),
            )
        return c

    def observe_ticks(self, replica: str, ticks: int) -> None:
        """Record the tick cost the router charged for a delivered
        handoff (the clock side lives in the router — transfer only
        prices it)."""
        self._counters(replica)[3].observe(ticks)

    # -- the handoff ----------------------------------------------------

    def ship(self, src_engine, tokens: Sequence[int],
             src_pages: Sequence[int], *, replica: str = "remote",
             health=None) -> Tuple[Optional[np.ndarray],
                                   Optional[np.ndarray], int]:
        """Move ``src_pages`` (page ids in the SOURCE pool, in prompt
        order) of the prompt ``tokens`` out of ``src_engine``'s cache,
        verified: returns host ``(k_tile, v_tile, attempts)`` with the
        tiles ready for :func:`make_insert_pages_fn` on the receiver
        (``(None, None, attempts)`` for an empty batch — a fully-
        deduped handoff still runs the control round-trip, so it can
        still fault). ``attempts`` > 1 means retries happened; the
        router prices each as one backoff tick on its work-charged
        clock (deterministic backoff — no wall-clock sleeps in a
        replay-exact scheduler). Raises :class:`TransferFailed` /
        :class:`TransferCorrupt` when the retry budget is gone; every
        attempt outcome feeds ``health`` (the remote replica's ladder)
        when given."""
        from apex_tpu.serving.paging import prefix_page_keys

        inj = self.injector
        trc = self.tracer
        c_bytes, c_retries, c_failures, _ = self._counters(replica)
        chain_key = prefix_page_keys(
            [int(t) for t in tokens], src_engine.page_size)[-1]
        n_pages = len(src_pages)
        if trc.enabled:
            trc.begin("page_transfer")
        corrupt_last = False
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.stats.transfer_retries += 1
                c_retries.inc()
            if inj.fire("page_send"):
                # the send was dropped before any bytes moved
                if health is not None:
                    health.probe(False)
                continue
            if n_pages:
                k_tile, v_tile = self.gather_fn(*self._extract(
                    src_engine.cache, jnp.asarray(src_pages, jnp.int32)))
                digest = transfer_checksum(k_tile, v_tile, chain_key)
                fired, payload = inj.draw("page_recv")
                if fired:
                    # in-flight corruption: flip one staged byte, the
                    # payload picks which — deterministic per (seed,
                    # site, index)
                    k_tile = np.array(k_tile, copy=True)
                    flat = k_tile.reshape(-1).view(np.uint8)
                    flat[payload % flat.size] ^= 0xFF
                if transfer_checksum(k_tile, v_tile,
                                     chain_key) != digest:
                    # quarantine: the tiles never reach the receiving
                    # cache; retry re-extracts from the source of truth
                    self.stats.transfer_corrupt += 1
                    corrupt_last = True
                    if health is not None:
                        health.probe(False)
                    continue
                corrupt_last = False
            else:
                k_tile = v_tile = None
                inj.draw("page_recv")  # handshake keeps draw order
            self.stats.transfers += 1
            if n_pages:
                c_bytes.inc(int(k_tile.nbytes) + int(v_tile.nbytes))
            if health is not None:
                health.probe(True)
            if trc.enabled:
                trc.end("page_transfer", pages=n_pages,
                        attempts=attempt + 1, replica=replica)
            return k_tile, v_tile, attempt + 1
        self.stats.transfer_failures += 1
        c_failures.inc()
        if trc.enabled:
            trc.end("page_transfer", pages=n_pages,
                    attempts=self.max_retries + 1, replica=replica,
                    failed=True)
        attempts = self.max_retries + 1
        cls = TransferCorrupt if corrupt_last else TransferFailed
        err = cls(
            f"page handoff from replica {replica!r} lost all "
            f"{attempts} attempts ({n_pages} pages"
            f"{'; last payload corrupt' if corrupt_last else ''})",
            attempts=attempts, pages=n_pages)
        raise self.tracer.attach(err) if trc.enabled else err

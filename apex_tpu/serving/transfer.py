"""Fault-tolerant cross-replica page handoff for disaggregated serving.

The disaggregated tier (``serving.router``) runs prefill and decode on
separate engines; what moves between them is the prompt's completed KV
pages — page-sized ``(layers, heads, page_size, head_dim)`` tiles
gathered from the prefill replica's pool and scattered into pages the
decode replica's :class:`~apex_tpu.serving.paging.PagePool` allocated.
This module owns that channel, and its design goal is the robustness
contract, not the copy itself:

- **content addressing** — every shipped batch is identified by the
  prompt's chained sha256 prefix keys
  (:func:`~apex_tpu.serving.paging.prefix_page_keys`, canonical
  ``struct.pack`` encoding). The receiver already holding a key's page
  skips the bytes entirely (cross-replica dedup — the same sharing the
  local prefix cache provides), and the final chain key is folded into
  the transfer checksum so a payload can never be installed under the
  wrong prompt.
- **integrity** — the sender checksums the staged tile bytes plus the
  chain key (sha256); the receiver recomputes before installing.
  A mismatch (the ``page_recv`` fault site flips one staged byte,
  payload-selected) QUARANTINES the payload: the tiles are discarded
  without touching the receiving cache, so corrupt KV rows are never
  attended. Typed: :class:`~apex_tpu.serving.health.TransferCorrupt`.
- **retry budget** — each handoff gets ``max_retries`` re-attempts
  (``page_send`` drops count too); exhaustion raises
  :class:`~apex_tpu.serving.health.TransferFailed` /
  ``TransferCorrupt`` and the router serves the admission colocated.
  Every outcome is also an observation for the remote replica's
  :class:`~apex_tpu.serving.health.ReplicaHealth` ladder.
- **observability** — one ``page_transfer`` tracer span per handoff
  (retries inside the span), per-replica labeled counters
  (``serving_transfer_src_bytes_total`` etc.), and the
  ``serving_transfer_ticks`` histogram of the deterministic tick cost
  the router charges per handoff.

Device mechanics: the jitted :func:`make_extract_pages_fn` /
:func:`make_insert_pages_fn` pair gathers/scatters tiles by page id
(one executable per distinct page count — prompts are bucketed, so the
count set is small), staged through the host. On a real two-slice
topology the staging hop is the ``device_get``/``device_put`` pair of
``partition.rules.make_shard_and_gather_fns`` over the two sub-meshes
of ``partition.mesh.make_mesh`` — :func:`make_tile_transfer_fns` builds
exactly that pair from the pool's TP layout (heads over ``model``);
the single-device default degenerates to a host round-trip, which is
also what keeps CPU chaos tests byte-faithful.

Two channel tiers share that contract (same chain keys, same checksum,
same quarantine, same retry discipline — only the link and the fault
sites differ):

- :class:`PageTransfer` — the HOST-STAGED bounce (gather to host,
  checksum, place on the destination), priced by the router at
  ``handoff_ticks_per_page``. Fault sites ``page_send``/``page_recv``.
- :class:`PageReshard` — the DEVICE-TO-DEVICE spec-to-spec reshard
  (the alpa-style ShardingSpec-to-ShardingSpec transfer of SNIPPETS.md
  [3]): page tiles move between the source and destination engines'
  sub-meshes without the host bounce, priced per link
  (``ici_ticks_per_page`` within a slice, ``dcn_ticks_per_page``
  across slices — both cheaper than the host staging they replace).
  Fault sites ``reshard_send``/``reshard_recv``; budget exhaustion
  raises the typed :class:`~apex_tpu.serving.health.ReshardFailed`
  and the pool router re-ships the same pages host-staged — the
  reshard tier may lose performance, never a request.
  :func:`make_reshard_extract_fn` is its traced sender half: a
  ``shard_map`` whose explicit ``all_gather`` materializes the wire
  tile from the TP-sharded pool, so the APX511 per-rank simulator and
  the APX6xx cost interpreter see (and budget) the collective volume
  the reshard moves (``gpt_page_reshard_medium``).

The :class:`PageTransfer` object itself is host state (attempt
counters, metric handles) — APX401 registers this module accordingly;
the jitted extract/insert closures touch none of it.
"""

import hashlib
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.faults import FaultInjector
from apex_tpu.serving.health import (ReshardFailed, ServingStats,
                                     TransferCorrupt, TransferFailed)
from apex_tpu.serving.observe import Tracer

#: ``serving_transfer_ticks`` histogram buckets: handoffs are charged
#: a handful of decode-step equivalents, not hundreds.
TRANSFER_TICK_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                         24.0, 32.0)


def make_extract_pages_fn() -> Callable:
    """Jitted ``(cache, page_ids) -> (k_tile, v_tile)``: gather the
    identified pages out of a paged cache's pool — the sender half of
    the handoff. Tiles are ``(layers, n_pages, heads, page_size,
    head_dim)`` in the pool dtype. Read-only (no donation): the source
    cache keeps serving its own slots."""

    def extract(cache, page_ids):
        return cache.k[:, page_ids], cache.v[:, page_ids]

    return jax.jit(extract)


def make_insert_pages_fn() -> Callable:
    """Jitted ``(cache, page_ids, k_tile, v_tile) -> cache``: scatter
    received tiles into the identified pages of the receiving pool —
    the receiver half of the handoff, and the cost-tier entry that
    prices the handoff bytes (``gpt_page_handoff_medium``). The cache
    is donated: the scatter is an in-place page write, exactly like a
    decode step's row append."""

    def insert(cache, page_ids, k_tile, v_tile):
        return cache._replace(k=cache.k.at[:, page_ids].set(k_tile),
                              v=cache.v.at[:, page_ids].set(v_tile))

    return jax.jit(insert, donate_argnums=(0,))


def make_extract_pages_quant_fn() -> Callable:
    """:func:`make_extract_pages_fn` for the int8 pool: gathers the
    per-page-per-head fp32 scale planes ``(layers, n_pages, heads)``
    TOGETHER with the int8 tiles — ``(cache, page_ids) -> (k_tile,
    v_tile, k_scale, v_scale)``. A page's rows are meaningless without
    the scales they were quantized against, so the spill/promote wire
    payload always carries all four (and still comes out at roughly
    half a bf16 payload's bytes — the capacity argument for the int8
    host tier)."""

    def extract(cache, page_ids):
        return (cache.k[:, page_ids], cache.v[:, page_ids],
                cache.k_scale[:, page_ids], cache.v_scale[:, page_ids])

    return jax.jit(extract)


def make_insert_pages_quant_fn() -> Callable:
    """:func:`make_insert_pages_fn` for the int8 pool: scatters int8
    tiles AND their fp32 scale planes into the identified pages —
    ``(cache, page_ids, k_tile, v_tile, k_scale, v_scale) -> cache``,
    cache donated (in-place page writes, like a decode step's row
    append). The promoted page is bit-identical to the spilled one:
    same int8 rows, same scales — the quantized analogue of the COW
    clone guarantee."""

    def insert(cache, page_ids, k_tile, v_tile, k_scale, v_scale):
        return cache._replace(
            k=cache.k.at[:, page_ids].set(k_tile),
            v=cache.v.at[:, page_ids].set(v_tile),
            k_scale=cache.k_scale.at[:, page_ids].set(k_scale),
            v_scale=cache.v_scale.at[:, page_ids].set(v_scale))

    return jax.jit(insert, donate_argnums=(0,))


def make_tile_transfer_fns(mesh=None, rules=None) -> Tuple[Callable,
                                                           Callable]:
    """``(gather_fn, shard_fn)`` for page tiles on a real multi-device
    topology: ``gather_fn`` pulls a (possibly TP-sharded) tile pair to
    replicated host arrays on the source sub-mesh, ``shard_fn`` places
    host tiles under the pool's TP spec (heads over ``model``) on the
    destination sub-mesh — the ``make_shard_and_gather_fns`` device_put
    /device_get pair from the partition engine, applied to the tile's
    head axis (axis 2, same as the pool's). Build one pair per sub-mesh
    of ``partition.mesh.make_mesh`` and hand them to
    :class:`PageTransfer`; without them the transfer stages through
    ``np.asarray`` — correct on any topology, optimal on one device."""
    from jax.sharding import PartitionSpec

    from apex_tpu.partition.rules import make_shard_and_gather_fns

    del rules  # the tile layout is fixed by the pool's: heads sharded
    spec = PartitionSpec(None, None, "model")
    shard_fns, gather_fns = make_shard_and_gather_fns(
        {"k": spec, "v": spec}, mesh)

    def gather_fn(k_tile, v_tile):
        return (np.asarray(gather_fns["k"](k_tile)),
                np.asarray(gather_fns["v"](v_tile)))

    def shard_fn(k_tile, v_tile):
        return shard_fns["k"](k_tile), shard_fns["v"](v_tile)

    return gather_fn, shard_fn


def make_reshard_extract_fn(mesh=None) -> Callable:
    """The traced sender half of a device-to-device reshard:
    ``jit(shard_map((cache, page_ids) -> (k_tile, v_tile)))`` over the
    source sub-mesh, where the pool's head axis shards over ``model``
    and an explicit ``all_gather`` (tiled, rank order — the same order
    the pool lays heads out in) materializes the full replicated wire
    tile from the local head shards. Functionally this equals
    :func:`make_extract_pages_fn` on the gathered cache — the reshard
    stays bitwise-faithful — but tracing the collective explicitly is
    the point: the APX511 per-rank simulator verifies every rank runs
    the same gather, and the cost tier's ``gpt_page_reshard_medium``
    budgets the collective volume the reshard puts on the ICI/DCN wire
    (per rank: (tp-1)/tp of the tile bytes, vs the host bounce's full
    gather + re-placement)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.serving.cache import paged_cache_partition_specs
    from apex_tpu.transformer import parallel_state as ps

    cspecs = paged_cache_partition_specs()

    def extract(cache, page_ids):
        k = jax.lax.all_gather(cache.k[:, page_ids], "model", axis=2,
                               tiled=True)
        v = jax.lax.all_gather(cache.v[:, page_ids], "model", axis=2,
                               tiled=True)
        return k, v

    sharded = ps.shard_map(extract, mesh=mesh,
                           in_specs=(cspecs, P()),
                           out_specs=(P(), P()))
    return jax.jit(sharded)


def _default_gather(k_tile, v_tile):
    return np.asarray(k_tile), np.asarray(v_tile)


def _default_shard(k_tile, v_tile):
    return k_tile, v_tile


def transfer_checksum(k_tile: np.ndarray, v_tile: np.ndarray,
                      chain_key: bytes) -> bytes:
    """sha256 over the staged tile bytes plus the prompt's final
    chained page key: integrity (bit flips) and identity (a payload
    can only verify against the prompt whose pages it carries) in one
    digest."""
    h = hashlib.sha256()
    h.update(chain_key)
    h.update(np.ascontiguousarray(k_tile).tobytes())
    h.update(np.ascontiguousarray(v_tile).tobytes())
    return h.digest()


class PageTransfer:
    """The fault-tolerant handoff channel (see module doc). One
    instance per router; both replicas' engines share its injector and
    tracer, so fault draws and spans land in a single deterministic
    sequence.

    ``max_retries`` bounds RE-attempts per handoff (total attempts =
    ``max_retries + 1``). ``gather_fn``/``shard_fn`` override the host
    staging hop for real two-mesh topologies
    (:func:`make_tile_transfer_fns`).

    The class attributes below are the channel's identity — the fault
    sites it draws, the tracer span it opens, the stat/metric families
    it bumps, and the typed errors budget exhaustion raises.
    :class:`PageReshard` overrides exactly these to become the
    device-to-device tier; the ``ship`` loop (extract → checksum →
    quarantine → retry) is shared verbatim, which is what keeps the
    two tiers' robustness contracts identical."""

    #: fault sites drawn per attempt (drop before bytes move / corrupt
    #: the staged payload in flight)
    send_site = "page_send"
    recv_site = "page_recv"
    #: tracer span name, one per handoff (retries inside the span)
    span = "page_transfer"
    #: ``ServingStats`` field family: <prefix>_retries / _corrupt /
    #: _failures, plus ``delivered_stat`` for verified deliveries
    stat_prefix = "transfer"
    delivered_stat = "transfers"
    #: per-replica labeled metric family in the registry
    metric_prefix = "serving_transfer"

    def __init__(self, injector: Optional[FaultInjector] = None,
                 tracer: Optional[Tracer] = None,
                 stats: Optional[ServingStats] = None,
                 max_retries: int = 2,
                 gather_fn: Callable = _default_gather,
                 shard_fn: Callable = _default_shard):
        if max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {max_retries}")
        self.injector = injector or FaultInjector()
        self.tracer = tracer if tracer is not None \
            else Tracer(enabled=False)
        self.stats = stats if stats is not None \
            else ServingStats(registry=self.tracer.registry)
        self.max_retries = max_retries
        self.gather_fn = gather_fn
        self.shard_fn = shard_fn
        self._extract = make_extract_pages_fn()
        self._hot = {}

    # -- per-replica labeled metrics ------------------------------------

    def _counters(self, replica: str):
        c = self._hot.get(replica)
        if c is None:
            r = self.tracer.registry
            p = self.metric_prefix
            labels = {"replica": replica}
            c = self._hot[replica] = (
                r.counter(f"{p}_src_bytes_total",
                          help="page-handoff bytes shipped from this "
                               "replica (verified payloads only)",
                          labels=labels),
                r.counter(f"{p}_src_retries_total",
                          help="handoff attempts retried against this "
                               "replica", labels=labels),
                r.counter(f"{p}_src_failures_total",
                          help="handoffs abandoned against this "
                               "replica (budget exhausted)",
                          labels=labels),
                r.histogram(f"{p}_ticks",
                            buckets=TRANSFER_TICK_BUCKETS,
                            help="deterministic tick cost charged per "
                                 "delivered handoff",
                            labels=labels),
            )
        return c

    def _bump(self, field: str, n: int = 1) -> None:
        """Increment one of the channel's ``ServingStats`` fields
        (``<stat_prefix>_retries`` etc. — the view resolves to the
        shared registry counter)."""
        name = f"{self.stat_prefix}_{field}"
        setattr(self.stats, name, getattr(self.stats, name) + n)

    def observe_ticks(self, replica: str, ticks: int) -> None:
        """Record the tick cost the router charged for a delivered
        handoff (the clock side lives in the router — transfer only
        prices it)."""
        self._counters(replica)[3].observe(ticks)

    # -- the handoff ----------------------------------------------------

    def ship(self, src_engine, tokens: Sequence[int],
             src_pages: Sequence[int], *, replica: str = "remote",
             health=None) -> Tuple[Optional[np.ndarray],
                                   Optional[np.ndarray], int]:
        """Move ``src_pages`` (page ids in the SOURCE pool, in prompt
        order) of the prompt ``tokens`` out of ``src_engine``'s cache,
        verified: returns host ``(k_tile, v_tile, attempts)`` with the
        tiles ready for :func:`make_insert_pages_fn` on the receiver
        (``(None, None, attempts)`` for an empty batch — a fully-
        deduped handoff still runs the control round-trip, so it can
        still fault). ``attempts`` > 1 means retries happened; the
        router prices each as one backoff tick on its work-charged
        clock (deterministic backoff — no wall-clock sleeps in a
        replay-exact scheduler). Raises :class:`TransferFailed` /
        :class:`TransferCorrupt` when the retry budget is gone; every
        attempt outcome feeds ``health`` (the remote replica's ladder)
        when given."""
        from apex_tpu.serving.paging import prefix_page_keys

        inj = self.injector
        trc = self.tracer
        c_bytes, c_retries, c_failures, _ = self._counters(replica)
        chain_key = prefix_page_keys(
            [int(t) for t in tokens], src_engine.page_size)[-1]
        n_pages = len(src_pages)
        if trc.enabled:
            trc.begin(self.span)
        corrupt_last = False
        for attempt in range(self.max_retries + 1):
            if attempt:
                self._bump("retries")
                c_retries.inc()
            if inj.fire(self.send_site):
                # the send was dropped before any bytes moved
                if health is not None:
                    health.probe(False)
                continue
            if n_pages:
                k_tile, v_tile = self.gather_fn(*self._extract(
                    src_engine.cache, jnp.asarray(src_pages, jnp.int32)))
                digest = transfer_checksum(k_tile, v_tile, chain_key)
                fired, payload = inj.draw(self.recv_site)
                if fired:
                    # in-flight corruption: flip one staged byte, the
                    # payload picks which — deterministic per (seed,
                    # site, index)
                    k_tile = np.array(k_tile, copy=True)
                    flat = k_tile.reshape(-1).view(np.uint8)
                    flat[payload % flat.size] ^= 0xFF
                if transfer_checksum(k_tile, v_tile,
                                     chain_key) != digest:
                    # quarantine: the tiles never reach the receiving
                    # cache; retry re-extracts from the source of truth
                    self._bump("corrupt")
                    corrupt_last = True
                    if health is not None:
                        health.probe(False)
                    continue
                corrupt_last = False
            else:
                k_tile = v_tile = None
                inj.draw(self.recv_site)  # handshake keeps draw order
            setattr(self.stats, self.delivered_stat,
                    getattr(self.stats, self.delivered_stat) + 1)
            if n_pages:
                c_bytes.inc(int(k_tile.nbytes) + int(v_tile.nbytes))
            if health is not None:
                health.probe(True)
            if trc.enabled:
                trc.end(self.span, pages=n_pages,
                        attempts=attempt + 1, replica=replica)
            return k_tile, v_tile, attempt + 1
        self._bump("failures")
        c_failures.inc()
        if trc.enabled:
            trc.end(self.span, pages=n_pages,
                    attempts=self.max_retries + 1, replica=replica,
                    failed=True)
        err = self._budget_error(replica, self.max_retries + 1, n_pages,
                                 corrupt_last)
        raise self.tracer.attach(err) if trc.enabled else err

    def _budget_error(self, replica: str, attempts: int, n_pages: int,
                      corrupt_last: bool):
        """The typed error a lost budget raises — the one seam the
        reshard tier's taxonomy differs on."""
        cls = TransferCorrupt if corrupt_last else TransferFailed
        return cls(
            f"page handoff from replica {replica!r} lost all "
            f"{attempts} attempts ({n_pages} pages"
            f"{'; last payload corrupt' if corrupt_last else ''})",
            attempts=attempts, pages=n_pages)


class PageReshard(PageTransfer):
    """The device-to-device handoff tier: the same verified channel as
    :class:`PageTransfer` but over the spec-to-spec ICI/DCN link
    instead of the host bounce. Pass the source/destination sub-meshes
    (``partition.mesh.make_mesh`` slices) and the tile pair moves
    through :func:`make_tile_transfer_fns` on each side — gather under
    the source mesh's TP spec, place under the destination's; on the
    single-process rig both default to the degenerate host round-trip,
    which keeps CPU chaos tests byte-faithful while exercising every
    fault path. Budget exhaustion raises the typed
    :class:`~apex_tpu.serving.health.ReshardFailed` (corrupt or
    dropped — ``corrupt`` tells which); the pool router catches it and
    re-ships the same pages through its host-staged
    :class:`PageTransfer`, so the reshard tier degrades to the r15
    contract instead of failing a request."""

    send_site = "reshard_send"
    recv_site = "reshard_recv"
    span = "reshard"
    stat_prefix = "reshard"
    delivered_stat = "reshards"
    metric_prefix = "serving_reshard"

    def __init__(self, injector: Optional[FaultInjector] = None,
                 tracer: Optional[Tracer] = None,
                 stats: Optional[ServingStats] = None,
                 max_retries: int = 2,
                 src_mesh=None, dst_mesh=None):
        gather_fn, shard_fn = _default_gather, _default_shard
        if src_mesh is not None:
            gather_fn, _ = make_tile_transfer_fns(src_mesh)
        if dst_mesh is not None:
            _, shard_fn = make_tile_transfer_fns(dst_mesh)
        super().__init__(injector=injector, tracer=tracer, stats=stats,
                         max_retries=max_retries, gather_fn=gather_fn,
                         shard_fn=shard_fn)

    def _budget_error(self, replica: str, attempts: int, n_pages: int,
                      corrupt_last: bool):
        return ReshardFailed(
            f"device-to-device reshard from replica {replica!r} lost "
            f"all {attempts} attempts ({n_pages} pages"
            f"{'; last payload corrupt' if corrupt_last else ''}) — "
            "degrading to host-staged handoff",
            attempts=attempts, pages=n_pages, corrupt=corrupt_last)

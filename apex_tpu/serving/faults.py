"""Deterministic fault injection for the serving engine.

Chaos testing is only useful when a failing run can be replayed
bit-for-bit. The :class:`FaultInjector` therefore owns NO random state:
whether site ``s`` faults on its ``i``-th call is a pure function of
``(seed, s, i)`` — a sha256 hash mapped to a uniform draw — so a fault
schedule is fully determined by the seed and the (deterministic) order
in which the scheduler visits the sites. Re-running the same request
stream with the same seed replays the exact same faults, and a single
``(site, index)`` can be pinned via ``schedule=`` for surgical
regression tests.

Sites (consulted through injected hooks — the jitted programs
themselves are never perturbed, so donation/APX512 and the compiled
executables stay fault-free):

=================  ======================================================
``pool_alloc``     ``PagePool.alloc`` reports exhaustion (returns None)
                   without sweeping the LRU registry — a transient
                   allocation refusal
``cow_clone``      the copy-on-write clone allocation in
                   ``PagedDecodeEngine.prepare_decode`` fails — the slot
                   is preempted and requeued
``prefill_exec``   ``prefill`` raises :class:`InjectedFault` before
                   touching the cache (page references are rolled back
                   first) — a simulated transient device failure
``chunk_prefill_exec``
                   one prompt CHUNK raises :class:`InjectedFault`
                   before touching the cache — a mid-prefill device
                   failure. The scheduler frees the slot (releasing
                   every held page), charges the retry budget, and
                   requeues the request at the head; the retried
                   prefill restarts from the prompt start, so the
                   recovered stream is bit-identical to golden
``decode_exec``    one slot's decode logits row is overwritten with NaN
                   AFTER the jitted step — exercises the scheduler's
                   always-on non-finite quarantine path
``sample``         one slot's sampled token is replaced with an
                   out-of-vocabulary id — exercises token validation
``draft_exec``     drafting fails. N-gram engines draw once per slot and
                   degrade that slot to an empty draft (plain decode
                   pace) for the tick. Engines with a model drafter
                   degrade down a LADDER: the first fired draw falls
                   back from the model draft to the n-gram draft for the
                   whole batch, and a second fired draw on the SAME tick
                   raises :class:`InjectedFault` — the scheduler empties
                   every draft (plain tick). No rung charges retry
                   budget; the stream stays bit-identical throughout
``page_send``      one cross-replica page-handoff attempt fails before
                   any bytes move (``serving.transfer.PageTransfer``) —
                   a dropped/late send. The transfer retries under its
                   per-transfer budget; exhaustion raises
                   :class:`~apex_tpu.serving.health.TransferFailed` and
                   the router falls back to colocated prefill
``page_recv``      the received page payload is corrupted in flight
                   (one staged byte flipped, payload-selected). The
                   receiver's checksum verification catches it, the
                   corrupt tiles are QUARANTINED (never installed, never
                   attended), and the attempt counts against the same
                   retry budget as ``page_send``
``replica_health`` one replica health probe fails
                   (``serving.router.DisaggregatedRouter`` draws once
                   per replica per tick, in fixed replica order).
                   Consecutive failures walk the replica down the
                   healthy -> degraded -> down ladder
                   (``serving.health.ReplicaHealth``); a down remote
                   stops receiving prefills, a down ACTIVE replica
                   triggers mid-stream failover
``host_spill``     one HBM->host page spill is dropped before any bytes
                   move (``PagedDecodeEngine._spill_page``, typed
                   :class:`~apex_tpu.serving.health.SpillFailed`). The
                   evicted prefix simply leaves both tiers — a later
                   admission re-prefills it; nothing is retried and the
                   committed streams are untouched
``host_promote``   one host->HBM promotion fails mid-chain
                   (``PagedDecodeEngine._promote_chain``, typed
                   :class:`~apex_tpu.serving.health.PromoteFailed`).
                   The admission degrades gracefully: pages promoted so
                   far are kept, the remainder of the prompt is
                   re-prefilled — the recovered stream is bit-identical
                   to golden
``reshard_send``   one device-to-device reshard attempt fails before
                   any bytes move (``serving.transfer.PageReshard``) —
                   a dropped spec-to-spec send over the ICI/DCN link.
                   Retried under the reshard's own budget; exhaustion
                   raises :class:`~apex_tpu.serving.health.ReshardFailed`
                   and the pool router degrades the handoff to the
                   HOST-STAGED ``PageTransfer`` path (which draws
                   ``page_send``/``page_recv`` as usual)
``reshard_recv``   the resharded page payload is corrupted in flight
                   (one staged byte flipped, payload-selected) — the
                   chain-key-bound checksum catches it, the tiles are
                   QUARANTINED, and the attempt counts against the
                   reshard budget exactly like ``reshard_send``
``pool_route``     one load-based routing decision is degraded
                   (``serving.router.PoolRouter`` draws once per
                   remote-prefill admission): the load snapshot is
                   treated as unavailable and the router falls back to
                   the FIRST routable prefill replica in fixed pool
                   order — a routing-policy fault, never a stream
                   fault (placement cannot move committed tokens)
``stream_emit``    one per-token stream delivery batch is dropped
                   (``serving.streaming.StreamMux`` draws once per
                   request with staged tokens at each end-of-tick
                   flush, in sorted request order, typed
                   :class:`~apex_tpu.serving.health.StreamFailed`).
                   The batch is discarded and the stream CLOSES — the
                   delivered tokens stay a strict prefix of the
                   committed outcome; the request itself keeps
                   decoding, so committed streams are untouched
=================  ======================================================

This module is host state (counters + schedules); reading it from
inside a traced function would freeze the values at trace time.
apxlint APX401 registers it accordingly (``apex_tpu/lint/hygiene.py``).
"""

import hashlib
from typing import Dict, Iterable, Mapping, Optional, Tuple

#: The named fault sites, in the order the docs list them.
SITES = ("pool_alloc", "cow_clone", "prefill_exec", "chunk_prefill_exec",
         "decode_exec", "sample", "draft_exec", "page_send", "page_recv",
         "replica_health", "host_spill", "host_promote", "reshard_send",
         "reshard_recv", "pool_route", "stream_emit")

#: Per-site contract: ``site -> (typed degrade error | None,
#: CI chaos-matrix sweep env | None)``. The error is the
#: ``ServingError`` subclass (or :class:`InjectedFault`) the site's
#: degrade path raises when its budget/ladder is exhausted — ``None``
#: for policy-only faults that alter a decision instead of raising
#: (``pool_route`` falls back to fixed-order routing). The sweep env
#: is the seed variable a CI chaos-matrix leg fans for the site's
#: family — ``None`` for sites exercised by the default deterministic
#: schedules in every leg. apxlint APX802 cross-checks this table
#: against the consultation call sites, the taxonomy, the chaos
#: tests, and ``ci.yml`` in both directions; keep it in lockstep with
#: :data:`SITES` and the table above.
SITE_CONTRACTS = {
    "pool_alloc": ("PoolExhausted", None),
    "cow_clone": ("PoolExhausted", None),
    "prefill_exec": ("InjectedFault", None),
    "chunk_prefill_exec": ("InjectedFault", None),
    "decode_exec": ("NonFiniteLogits", None),
    "sample": ("NonFiniteLogits", None),
    "draft_exec": ("InjectedFault", None),
    "page_send": ("TransferFailed", "APEX_CHAOS_TRANSFER_SEED"),
    "page_recv": ("TransferCorrupt", "APEX_CHAOS_TRANSFER_SEED"),
    "replica_health": ("ReplicaUnavailable", "APEX_CHAOS_TRANSFER_SEED"),
    "host_spill": ("SpillFailed", "APEX_CHAOS_SPILL_SEED"),
    "host_promote": ("PromoteFailed", "APEX_CHAOS_SPILL_SEED"),
    "reshard_send": ("ReshardFailed", "APEX_CHAOS_POOL_SEED"),
    "reshard_recv": ("ReshardFailed", "APEX_CHAOS_POOL_SEED"),
    "pool_route": (None, "APEX_CHAOS_POOL_SEED"),
    "stream_emit": ("StreamFailed", "APEX_CHAOS_TENANT_SEED"),
}


class InjectedFault(RuntimeError):
    """A simulated transient failure (site ``prefill_exec``). The
    scheduler treats it exactly like a real device fault: charge the
    retry budget, back off, try again."""

    def __init__(self, site: str, index: int):
        super().__init__(f"injected fault at {site}[{index}]")
        self.site = site
        self.index = index


def fault_draw(seed: int, site: str, index: int) -> Tuple[float, int]:
    """The pure schedule function: ``(u01, payload)`` for call
    ``index`` at ``site`` under ``seed``. ``u01`` decides whether the
    call faults (compare against the site's rate); ``payload`` is a
    deterministic uint32 the caller may use to pick a victim slot."""
    h = hashlib.sha256(f"{seed}:{site}:{index}".encode()).digest()
    u01 = int.from_bytes(h[:8], "big") / 2.0**64
    return u01, int.from_bytes(h[8:12], "big")


class FaultInjector:
    """Seedable per-site fault schedule (see module doc). With neither
    ``rates`` nor ``schedule`` the injector is inert — the default
    every engine carries, so production paths pay one dict lookup and
    an integer increment per site visit.

    ``rates`` maps site -> fault probability in [0, 1] (evaluated
    against the pure hash draw, NOT a stateful RNG). ``schedule`` maps
    site -> iterable of call indices that fault unconditionally —
    the single-fault chaos tests pin exact (site, index) pairs with it.
    """

    def __init__(self, seed: int = 0,
                 rates: Optional[Mapping[str, float]] = None,
                 schedule: Optional[Mapping[str, Iterable[int]]] = None):
        for name, table in (("rates", rates), ("schedule", schedule)):
            unknown = set(table or ()) - set(SITES)
            if unknown:
                raise ValueError(
                    f"{name} names unknown fault sites {sorted(unknown)}"
                    f"; sites are {SITES}")
        self.seed = seed
        self.rates: Dict[str, float] = dict(rates or {})
        self.schedule: Dict[str, frozenset] = {
            site: frozenset(int(i) for i in ixs)
            for site, ixs in (schedule or {}).items()}
        self._calls: Dict[str, int] = {s: 0 for s in SITES}
        self._fired: Dict[str, int] = {s: 0 for s in SITES}

    @property
    def armed(self) -> bool:
        """True when any site can ever fault."""
        return bool(self.rates or self.schedule)

    def draw(self, site: str) -> Tuple[bool, int]:
        """Advance ``site``'s call counter and return ``(fired,
        payload)``. Pure replay: the outcome depends only on (seed,
        site, call index)."""
        index = self._calls[site]  # KeyError on unknown site is wanted
        self._calls[site] = index + 1
        if not self.armed:
            return False, 0
        u01, payload = fault_draw(self.seed, site, index)
        fired = (index in self.schedule.get(site, ())
                 or u01 < self.rates.get(site, 0.0))
        if fired:
            self._fired[site] += 1
        return fired, payload

    def fire(self, site: str) -> bool:
        """``draw`` for callers that only need the fault bit."""
        return self.draw(site)[0]

    def calls(self, site: str) -> int:
        """How many times ``site`` has been consulted."""
        return self._calls[site]

    @property
    def counts(self) -> Dict[str, int]:
        """Faults actually fired, per site."""
        return dict(self._fired)

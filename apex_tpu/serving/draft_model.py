"""Model-based drafting: a tiny GPT advanced in lockstep with the
target's slots.

The n-gram drafter (``serving.draft``) is free but collapses toward
m̄ = 1 on non-repetitive text. This module runs a LEARNED draft model
— a 2–4 layer GPT sharing the target's vocab (``models.draft_gpt_tiny``
pairs ``gpt_tiny``) — whose forward costs a few percent of the
target's parameter read (the ``gpt_draft_forward_step`` budget pins
<3%), so even modest acceptance amortizes (BASELINE r13's adjusted
break-even m̄ > 1.017 + draft_bytes/target_bytes).

Lockstep + resync contract
--------------------------
The draft keeps its OWN dense KV cache, one row stream per target
slot. ``_tokens[slot]`` records exactly which tokens' K/V rows the
draft cache holds (rows ``0..len-1``). Each ``draft()`` call re-syncs
every slot to the target's committed history by COMMON PREFIX: rows
whose recorded token still matches the committed stream are kept;
``lengths`` is rolled back to the first divergence and the backlog
(newly committed tokens, plus anything past a divergence) is re-fed in
verify-shaped chunks. This is the target's own write-then-attend
rollback reused verbatim: a rolled-back row is overwritten before any
later mask admits it, so rejected-draft rows never need cleanup, and a
rejected TREE branch (or a fault-skipped tick) is handled by the same
prefix computation — there is no separate rollback path.

Chunked catch-up doubles as prefill: a fresh slot's whole prompt
streams through the same verify-fn chunks (pad columns repeat token 0;
their rows are garbage beyond the recorded length and are overwritten
by the next catch-up). The LAST chunk's logits row at the final real
token is the draft distribution for the next stream token — the root
of both the linear chain (greedy argmax, then batched single-token
decode steps) and the draft tree (top-``branch`` root children,
greedy-extended leftmost chain).

TP: pass a ``GPTModel(draft_cfg, tp_size)`` — the drafter then builds
``make_tp_verify_fn``/``make_tp_decode_fn`` over the same mesh the
target shards on (the draft partition table is
``partition.tables.draft_gpt_rules``).
"""

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.serving.cache import init_cache
from apex_tpu.serving.decode import (
    make_decode_fn, make_tp_decode_fn, make_tp_verify_fn, make_verify_fn,
)

__all__ = ["DraftModel"]


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class DraftModel:
    """Host-side drafter wrapping a tiny GPT + its lockstep KV cache.

    ``params``/``cfg`` are the draft net (same vocab as the target);
    ``num_slots`` mirrors the target engine's slot count; ``max_len``
    is the TARGET's max_len — the draft cache adds ``chunk`` rows of
    slack so pad columns of the last catch-up chunk stay in bounds.
    ``model``/``mesh`` switch the forwards to the TP builders.
    """

    def __init__(self, params, cfg: GPTConfig, num_slots: int,
                 max_len: int, *, chunk: int = 5, compute_dtype=None,
                 model=None, mesh=None, cache_dtype=jnp.bfloat16):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.chunk = chunk
        self.cache = init_cache(cfg, num_slots, max_len + chunk,
                                dtype=cache_dtype)
        from apex_tpu.quant.params import is_quantized_tree
        quantized = is_quantized_tree(params)
        if model is not None:
            if model.cfg is not cfg and model.cfg != cfg:
                raise ValueError("TP draft model config mismatch")
            self._verify = make_tp_verify_fn(model, mesh,
                                             quantized=quantized)
            self._decode = make_tp_decode_fn(model, mesh,
                                             quantized=quantized)
        else:
            self._verify = make_verify_fn(cfg, compute_dtype, quantized)
            self._decode = make_decode_fn(cfg, compute_dtype, quantized)
        # per-slot record of which tokens' K/V rows the cache holds
        self._tokens: List[List[int]] = [[] for _ in range(num_slots)]

    def free_slot(self, slot: int) -> None:
        """Forget a slot (target slot freed/preempted): its rows become
        garbage beyond length 0 and are overwritten on reuse."""
        self._tokens[slot] = []
        self.cache = self.cache._replace(
            lengths=self.cache.lengths.at[slot].set(0))

    # -- sync ------------------------------------------------------------

    def _sync(self, histories: Sequence[Optional[Sequence[int]]]):
        """Catch every active slot up to its committed history and
        return the root logits (np (B, V)): the draft distribution for
        the token after ``history[-1]``. Inactive slots (None) idle on
        pad feeds at length 0."""
        hists = [list(h) if h else None for h in histories]
        # roll back to the common prefix, held strictly below len(h) so
        # the final chunk always re-feeds history[-1] and yields fresh
        # root logits
        cp = []
        for s in range(self.num_slots):
            h = hists[s]
            if h is None:
                cp.append(0)
                continue
            keep = min(_common_prefix(self._tokens[s], h), len(h) - 1)
            self._tokens[s] = self._tokens[s][:keep]
            cp.append(keep)
        root = np.zeros((self.num_slots, self.cfg.vocab_size), np.float32)
        while True:
            backlog = [len(h) - cp[s] if h is not None else 0
                       for s, h in enumerate(hists)]
            if not any(backlog):
                break
            last_round = max(backlog) <= self.chunk
            grid = np.zeros((self.num_slots, self.chunk), np.int32)
            fed = [0] * self.num_slots
            for s, h in enumerate(hists):
                if h is None:
                    continue
                # hold a slot's final partial chunk for the last round
                # so every active slot's root logits come from one call
                if not last_round and backlog[s] <= self.chunk:
                    continue
                n = min(backlog[s], self.chunk)
                grid[s, :n] = h[cp[s]:cp[s] + n]
                fed[s] = n
            self.cache = self.cache._replace(
                lengths=jnp.asarray(cp, jnp.int32))
            self.cache, logits = self._verify(
                self.params, self.cache, jnp.asarray(grid))
            if last_round:
                lg = np.asarray(logits)
                for s in range(self.num_slots):
                    if fed[s]:
                        root[s] = lg[s, fed[s] - 1]
            for s in range(self.num_slots):
                if fed[s]:
                    self._tokens[s].extend(hists[s][cp[s]:cp[s] + fed[s]])
                    cp[s] += fed[s]
            if last_round:
                break
        self.cache = self.cache._replace(lengths=jnp.asarray(cp, jnp.int32))
        return root

    def _greedy_steps(self, first: np.ndarray, ks: Sequence[int]):
        """Extend each slot's chain greedily: ``first`` (B,) is the
        chain's first token (already chosen from the root logits);
        returns per-slot chains of length ``ks[s]`` (0 -> []). Feeding
        a chain token writes its row and records it — the next sync's
        common prefix decides whether it survives."""
        chains = [[int(first[s])] if ks[s] >= 1 else []
                  for s in range(self.num_slots)]
        steps = max((k - 1 for k in ks), default=0)
        cur = np.array([c[0] if c else 0 for c in chains], np.int32)
        for i in range(steps):
            active = np.array([ks[s] - 1 > i for s in range(self.num_slots)])
            if not active.any():
                break
            self.cache, logits = self._decode(
                self.params, self.cache, jnp.asarray(cur),
                jnp.asarray(active))
            nxt = np.asarray(jnp.argmax(logits, axis=-1)).astype(np.int32)
            for s in range(self.num_slots):
                if active[s]:
                    self._tokens[s].append(int(cur[s]))
                    chains[s].append(int(nxt[s]))
                    cur[s] = nxt[s]
        return chains

    # -- drafting --------------------------------------------------------

    def draft(self, histories: Sequence[Optional[Sequence[int]]],
              ks: Sequence[int]) -> List[List[int]]:
        """Linear drafts: for each active slot, up to ``ks[s]`` greedy
        continuation tokens of ``histories[s]``. The last chain token
        is never fed (its row would be pure waste), so the recorded
        rows are ``history + chain[:-1]``."""
        root = self._sync(histories)
        ks = [k if histories[s] is not None else 0
              for s, k in enumerate(ks)]
        first = root.argmax(axis=1).astype(np.int32)
        return self._greedy_steps(first, ks)

    def draft_tree(self, histories: Sequence[Optional[Sequence[int]]],
                   ks: Sequence[int]
                   ) -> List[Optional[Tuple[List[int], List[int]]]]:
        """Tree drafts of up to ``ks[s]`` nodes: a greedy leftmost
        chain of ``k - 1`` tokens plus the SECOND-best root child as an
        alternate branch (both roots are children of the walk root;
        top-2 of one distribution are distinct, the accept walk's
        distinct-children contract). Returns per-slot ``(tokens,
        parents)`` with parent ``-1`` = walk root — ``None`` for
        inactive slots or ``k == 0``. Only the leftmost chain is fed
        (and recorded): an accepted alternate branch simply diverges
        the next sync's common prefix."""
        root = self._sync(histories)
        ks = [k if histories[s] is not None else 0
              for s, k in enumerate(ks)]
        order = np.argsort(-root, axis=1)
        chains = self._greedy_steps(order[:, 0].astype(np.int32),
                                    [max(k - 1, min(k, 1)) for k in ks])
        out: List[Optional[Tuple[List[int], List[int]]]] = []
        for s in range(self.num_slots):
            k = ks[s]
            if k <= 0:
                out.append(None)
                continue
            tokens = list(chains[s])
            parents = [-1] + list(range(len(tokens) - 1))
            if k >= 2 and len(tokens) == k - 1:
                tokens.append(int(order[s, 1]))
                parents.append(-1)
            out.append((tokens, parents))
        return out

"""Typed failure taxonomy + runtime counters for the serving engine.

Apex's signature robustness move is the dynamic loss scaler: overflow
is an EXPECTED state — detect it, skip the step, back off, keep
training. This module gives the serving stack the same discipline.
Instead of ``None`` returns and bare ``RuntimeError``\\ s, every way a
request can fail is a named exception the scheduler either *recovers
from* (retry/requeue) or *reports* (a :class:`RequestOutcome` with a
typed reason), and every degradation event increments a counter in
:class:`ServingStats` so a chaos run — or a production dashboard — can
see exactly how the engine bent instead of broke. The counters are a
view over the ``serving.observe`` :class:`MetricsRegistry`, so the
same numbers come out of the Prometheus/JSON exports.

Everything here is plain host-side Python: no jax imports, no device
state, no clocks. Counters and exceptions must NEVER be consulted from
inside a traced function (their values would be frozen into the
compiled program at trace time) — apxlint APX401 registers this module
as host state and flags any such read (see
``apex_tpu/lint/hygiene.py``).

Taxonomy (all subclass :class:`ServingError`):

==========================  ===============================================
:class:`PoolExhausted`      the page pool cannot cover an allocation even
                            after LRU prefix eviction (transient: retried
                            after evictions free pages)
:class:`NonFiniteLogits`    a decode/prefill step produced NaN/Inf logits
                            or an out-of-range sampled token; the slot is
                            quarantined and the request retried
:class:`RetryBudgetExhausted`  a request burned through its per-request
                            retry budget; it terminates with the tokens
                            committed so far
:class:`DeadlineExceeded`   a request overran its ``deadline_ticks``
                            budget (scheduler ticks, deterministic — no
                            wall clocks)
:class:`AdmissionRejected`  backpressure: the bounded admission queue is
                            full at ``submit()``
:class:`LivelockError`      the scheduler's progress watchdog fired —
                            carries the stuck request set and a pool
                            snapshot instead of spinning forever
:class:`PoolInvariantError` the runtime audit
                            (``PagePool.check_invariants``) found the
                            allocator's books inconsistent
:class:`TransferFailed`     a cross-replica page handoff exhausted its
                            per-transfer retry budget (every attempt
                            dropped at the ``page_send`` site); the
                            router falls back to colocated prefill
:class:`TransferCorrupt`    the received page payload failed checksum /
                            page-key verification — the tiles are
                            quarantined (never installed, never
                            attended) and the attempt retried
:class:`ReplicaUnavailable` a routing target is unusable: its health
                            state is ``down``, or its own page pool
                            refused the prompt — the router serves the
                            request colocated on the surviving engine
:class:`ReshardFailed`      a device-to-device page reshard exhausted
                            its retry budget (``reshard_send`` drops or
                            ``reshard_recv`` corruption); the pool
                            router degrades the handoff to the
                            host-staged ``PageTransfer`` path — a
                            subclass of :class:`TransferFailed`, so
                            single-pair callers keep their ladder
:class:`SpillFailed`        an HBM→host page spill was dropped (the
                            ``host_spill`` fault site, or a payload the
                            host tier rejected); the evicted prefix
                            leaves both tiers and a later admission
                            re-prefills it — never retried, never fatal
:class:`PromoteFailed`      a host→HBM promotion failed (fault, checksum
                            mismatch, wrong-chain header, geometry
                            drift); the stale host-tier entry is dropped
                            and the admission degrades to re-prefilling
                            the uncovered remainder of the prompt
:class:`StreamFailed`       a per-token stream delivery batch was dropped
                            at the ``stream_emit`` fault site; the stream
                            closes and its delivered tokens remain a
                            STRICT PREFIX of the committed outcome — the
                            request itself is never perturbed
:class:`QuotaExhausted`     a tenant's page quota cannot cover a request's
                            worst-case page reservation — raised at
                            ``submit()`` (the tenancy analogue of
                            :class:`AdmissionRejected` backpressure)
:class:`SloViolation`       a finished request broke its tenant's declared
                            TTFT/ITL tick bound; attached to
                            ``RequestOutcome.slo`` as a diagnostic (the
                            outcome itself stays healthy)
==========================  ===============================================

The disaggregated tier adds one piece of host-side *state* here too:
:class:`ReplicaHealth`, the per-replica probe-driven
healthy → degraded → down ladder the
:class:`~apex_tpu.serving.router.DisaggregatedRouter` consults before
routing a prefill to the remote replica (and to decide mid-stream
failover when the ACTIVE replica goes down). Like the counters it is
plain Python — APX401 host state.
"""

import dataclasses
from typing import Any, Dict, Optional, Tuple

from apex_tpu.serving.observe import MetricsRegistry

#: ``RequestOutcome.reason`` values — the full set of ways a request
#: terminates. Healthy: ``eos`` / ``length`` / ``cache_full``; degraded
#: (``error`` carries the typed exception): ``retry_budget`` /
#: ``deadline``.
FINISH_REASONS = ("eos", "length", "cache_full", "retry_budget",
                  "deadline")


class ServingError(RuntimeError):
    """Base of the serving failure taxonomy. Every instance carries a
    ``payload`` dict of host-side diagnostics; when tracing is enabled
    the scheduler attaches the flight-recorder ring under
    ``payload["flight"]`` (``serving.observe``), so the error ships its
    own last-N-events post-mortem."""

    def __init__(self, *args):
        super().__init__(*args)
        self.payload: Dict[str, Any] = {}


class PoolExhausted(ServingError):
    """The page pool cannot cover an allocation even after LRU prefix
    eviction. Transient under load: evictions free pages and the
    scheduler retries the admission."""

    def __init__(self, msg: str, *, need: int = 0, free: int = 0,
                 cached: int = 0):
        super().__init__(msg)
        self.need = need
        self.free = free
        self.cached = cached


class NonFiniteLogits(ServingError):
    """A decode/prefill step produced NaN/Inf logits (or the sampler
    returned a token outside the vocabulary) for a slot. The slot is
    quarantined: freed, its request requeued at the front — the retry
    re-prefills from committed tokens, so the recovered stream is
    bit-identical to the fault-free one."""


class RetryBudgetExhausted(ServingError):
    """A request consumed its whole retry budget; it terminates with a
    ``retry_budget`` outcome carrying the tokens committed so far."""

    def __init__(self, msg: str, *, request_id: int = -1,
                 retries: int = 0):
        super().__init__(msg)
        self.request_id = request_id
        self.retries = retries


class DeadlineExceeded(ServingError):
    """A request overran its ``deadline_ticks`` budget. Deadlines are
    measured in scheduler ticks since submission — deterministic, so
    chaos runs replay bit-for-bit (a wall-clock deadline would not)."""


class AdmissionRejected(ServingError):
    """Backpressure: ``submit()`` refused a request because the bounded
    admission queue is full. The caller sheds load instead of growing
    an unbounded queue."""


class LivelockError(ServingError):
    """The scheduler made no progress (no token, no completion, no
    retry consumed) for ``watchdog_limit`` consecutive ticks. Carries
    the stuck request set and a pool snapshot — the diagnostic the
    PR-8 COW livelock needed, raised instead of spinning."""

    def __init__(self, msg: str, *, stuck: Optional[Dict] = None,
                 pool: Optional[Dict] = None):
        super().__init__(msg)
        self.stuck = stuck or {}
        self.pool = pool or {}
        self.payload.update(stuck=self.stuck, pool=self.pool)


class PoolInvariantError(ServingError):
    """The page allocator's books are inconsistent (refcounts vs. free
    list vs. prefix registry vs. block tables) — raised by the runtime
    audit, ``PagePool.check_invariants``."""


class TransferFailed(ServingError):
    """A cross-replica page handoff exhausted its per-transfer retry
    budget (every attempt lost at the ``page_send`` site). Carries the
    attempt count and the page batch size; the router catches it and
    serves the admission colocated — the request never sees it."""

    def __init__(self, msg: str, *, attempts: int = 0, pages: int = 0):
        super().__init__(msg)
        self.attempts = attempts
        self.pages = pages
        self.payload.update(attempts=attempts, pages=pages)


class TransferCorrupt(ServingError):
    """A received page payload failed verification: the transfer
    checksum (sha256 over the staged K/V tile bytes + the chained
    prefix page key) did not match what the sender computed. The tiles
    are QUARANTINED — discarded without ever being installed into the
    receiving pool, so corrupt KV rows are never attended. Raised out
    of the transfer only when corruption also exhausted the retry
    budget; the router then falls back colocated."""

    def __init__(self, msg: str, *, attempts: int = 0, pages: int = 0):
        super().__init__(msg)
        self.attempts = attempts
        self.pages = pages
        self.payload.update(attempts=attempts, pages=pages)


class ReshardFailed(TransferFailed):
    """A device-to-device page reshard (``serving.transfer.PageReshard``,
    the spec-to-spec ICI/DCN tier) exhausted its per-handoff retry
    budget — every attempt dropped at ``reshard_send`` or quarantined at
    the ``reshard_recv`` checksum (``corrupt`` tells which ended the
    run). The pool router catches it and re-ships the SAME pages over
    the host-staged ``PageTransfer`` channel: the reshard tier may only
    lose performance, never a request. Subclasses
    :class:`TransferFailed` so any caller handling the single-pair
    taxonomy keeps its ladder unchanged."""

    def __init__(self, msg: str, *, attempts: int = 0, pages: int = 0,
                 corrupt: bool = False):
        super().__init__(msg, attempts=attempts, pages=pages)
        self.corrupt = corrupt
        self.payload.update(corrupt=corrupt)


class ReplicaUnavailable(ServingError):
    """A routing target cannot serve: its :class:`ReplicaHealth` is
    ``down``, or its own page pool refused the prompt's pages. The
    router catches it and degrades to colocated prefill+decode on the
    surviving engine — a dead replica yields this typed diagnostic,
    never a hang."""

    def __init__(self, msg: str, *, replica: str = ""):
        super().__init__(msg)
        self.replica = replica
        self.payload.update(replica=replica)


class SpillFailed(ServingError):
    """An HBM→host page spill was dropped before the payload reached
    the host tier (the ``host_spill`` fault site fired, or the
    :class:`~apex_tpu.serving.paging.PrefixRegistry` rejected the
    record). Purely a cache-efficiency loss: the evicted prefix simply
    leaves both tiers and a later admission re-prefills it — the spill
    path never retries and never fails a request."""

    def __init__(self, msg: str, *, key: str = ""):
        super().__init__(msg)
        self.key = key
        self.payload.update(key=key)


class PromoteFailed(ServingError):
    """A host→HBM page promotion failed verification or faulted: the
    record's checksum did not recompute, its versioned header named a
    different prompt chain or pool geometry, or the ``host_promote``
    fault site fired. The stale host-tier entry is dropped (checksum /
    header mismatches only) and the admission DEGRADES GRACEFULLY —
    pages promoted so far are kept, the uncovered remainder of the
    prompt re-prefills, and the committed stream stays bit-identical to
    the spill-disabled scheduler."""

    def __init__(self, msg: str, *, key: str = "", pages: int = 0):
        super().__init__(msg)
        self.key = key
        self.pages = pages
        self.payload.update(key=key, pages=pages)


class StreamFailed(ServingError):
    """A per-token stream delivery batch was dropped: the
    ``stream_emit`` fault site fired while the
    :class:`~apex_tpu.serving.streaming.StreamMux` was flushing a
    request's staged tokens. The batch is discarded and the stream
    CLOSES — its ``delivered`` tokens stay a strict prefix of the
    committed ``RequestOutcome.tokens`` — while the request itself
    keeps decoding untouched (stream delivery is host-side fan-out,
    never part of the committed-stream contract)."""

    def __init__(self, msg: str, *, request_id: int = -1,
                 delivered: int = 0, dropped: int = 0):
        super().__init__(msg)
        self.request_id = request_id
        self.delivered = delivered
        self.dropped = dropped
        self.payload.update(request_id=request_id, delivered=delivered,
                            dropped=dropped)


class QuotaExhausted(ServingError):
    """A tenant's page quota cannot cover a request's worst-case page
    reservation (prompt + ``max_new_tokens`` + speculative headroom,
    priced by the paged engine's geometry). Raised by ``submit()`` when
    the request could NEVER fit its tenant's quota — the tenancy
    analogue of :class:`AdmissionRejected` backpressure. Transient
    quota pressure (the tenant's other live requests hold the pages)
    never raises: admission simply defers the request until a
    completion credits the reservation back."""

    def __init__(self, msg: str, *, tenant: str = "", need: int = 0,
                 quota: int = 0, charged: int = 0):
        super().__init__(msg)
        self.tenant = tenant
        self.need = need
        self.quota = quota
        self.charged = charged
        self.payload.update(tenant=tenant, need=need, quota=quota,
                            charged=charged)


class SloViolation(ServingError):
    """A finished request broke its tenant's declared service-level
    objective: TTFT or worst-case inter-token latency exceeded the
    tenant's tick bound. Never raised — the scheduler stamps it into
    ``RequestOutcome.slo`` as a typed diagnostic (the outcome's
    ``error``/``ok`` contract is untouched: an SLO miss is a latency
    fact, not a failure) and bumps the ``slo_violations`` counter."""

    def __init__(self, msg: str, *, tenant: str = "", metric: str = "",
                 observed: int = 0, bound: int = 0):
        super().__init__(msg)
        self.tenant = tenant
        self.metric = metric
        self.observed = observed
        self.bound = bound
        self.payload.update(tenant=tenant, metric=metric,
                            observed=observed, bound=bound)


#: ``ReplicaHealth`` states, worst first. The index doubles as the
#: ``serving_replica_health`` gauge value (0 = down .. 2 = healthy) so
#: dashboards can alert on ``< 2`` without string labels.
HEALTH_STATES = ("down", "degraded", "healthy")


class ReplicaHealth:
    """Per-replica probe-driven health ladder: ``healthy`` → ``degraded``
    → ``down``, one rung per failed observation, with hysteresis on the
    way back up (``recover_after`` CONSECUTIVE successes per rung — a
    flapping replica cannot oscillate straight back into the routing
    set). Observations come from two places, both deterministic: the
    router's per-tick ``replica_health`` fault-site probes, and real
    transfer/prefill outcomes against the replica (a failed handoff
    attempt is evidence exactly like a failed probe).

    ``routable`` gates routing: ``down`` replicas receive no prefills
    and trigger failover when they back the active slots. The state is
    exported as the ``serving_replica_health`` gauge (per-replica
    label) on every transition and probe.

    Host state (APX401): never read inside a traced function.
    """

    def __init__(self, name: str,
                 registry: Optional[MetricsRegistry] = None,
                 recover_after: int = 2):
        if recover_after < 1:
            raise ValueError(
                f"recover_after must be >= 1, got {recover_after}")
        self.name = name
        self.state = "healthy"
        self.recover_after = recover_after
        self._ok_streak = 0
        self.transitions = 0
        self._gauge = None if registry is None else registry.gauge(
            "serving_replica_health",
            help="replica health ladder (2 healthy / 1 degraded / "
                 "0 down)", labels={"replica": name})
        self._export()

    def _export(self) -> None:
        if self._gauge is not None:
            self._gauge.set(HEALTH_STATES.index(self.state))

    @property
    def routable(self) -> bool:
        """May receive new work (``down`` replicas may not; ``degraded``
        ones still serve — they are one failure from the exit, not out)."""
        return self.state != "down"

    def probe(self, ok: bool) -> str:
        """Fold one observation (probe result, transfer outcome, remote
        prefill outcome) into the ladder and return the new state."""
        prev = self.state
        if ok:
            self._ok_streak += 1
            if self._ok_streak >= self.recover_after \
                    and self.state != "healthy":
                self.state = ("degraded" if self.state == "down"
                              else "healthy")
                self._ok_streak = 0
        else:
            self._ok_streak = 0
            if self.state == "healthy":
                self.state = "degraded"
            elif self.state == "degraded":
                self.state = "down"
        if self.state != prev:
            self.transitions += 1
            self._export()
        elif self._gauge is not None and self._gauge.value \
                != HEALTH_STATES.index(self.state):
            self._export()
        return self.state

    def __repr__(self):
        return (f"ReplicaHealth({self.name!r}, state={self.state!r}, "
                f"ok_streak={self._ok_streak})")


#: ``ServingStats`` counter fields -> help text. Order defines the
#: ``as_dict`` / Prometheus export order; each field is backed by a
#: ``serving_<field>_total`` counter in the stats' MetricsRegistry.
STAT_FIELDS = {
    "admission_rejections": "submit() refused: queue full",
    "pool_exhausted": "admissions parked waiting for pages",
    "preemptions": "slots requeued on page pressure",
    "cow_copies": "shared pages cloned before append",
    "retries": "fault-path requeues (budgeted)",
    "nan_events": "non-finite logits quarantines",
    "bad_samples": "out-of-vocab sampled tokens",
    "deadline_expired": "requests cut at deadline_ticks",
    "evictions": "healthy completions freeing a slot",
    "tokens_drafted": "speculative candidates proposed",
    "tokens_accepted": "drafted candidates that committed",
    "draft_faults": "draft_exec faults (degraded ticks)",
    "spec_ticks": "verify-step ticks (linear or tree)",
    "plain_ticks": "single-token decode ticks",
    "prefill_chunks": "chunked-prefill chunk forwards run",
    "remote_prefills": "admissions prefilled on the remote replica",
    "colocated_prefills": "admissions served colocated (fallback)",
    "transfers": "page handoffs delivered and verified",
    "transfer_pages_deduped": "handoff pages skipped: receiver held them",
    "transfer_retries": "page-handoff attempts retried",
    "transfer_corrupt": "handoff payloads quarantined on checksum",
    "transfer_failures": "handoffs abandoned (budget exhausted)",
    "reshards": "device-to-device page reshards delivered and verified",
    "reshard_retries": "reshard attempts retried over the ICI/DCN link",
    "reshard_corrupt": "reshard payloads quarantined on checksum",
    "reshard_failures": "reshards abandoned (degraded to host staging)",
    "route_fallbacks": "pool_route faults: fixed-order routing used",
    "rebalances": "decode placement moved to a sibling replica",
    "failovers": "active-replica switches (slots drained + requeued)",
    "host_spills": "pages spilled HBM->host on LRU eviction",
    "host_spill_failures": "spills dropped (fault or tier rejection)",
    "host_spill_bytes": "payload bytes spilled to the host tier",
    "host_promotes": "pages promoted host->HBM on a prefix hit",
    "host_promote_failures": "promotions abandoned (fault/verification)",
    "host_promote_bytes": "payload bytes promoted from the host tier",
    "host_promote_ticks": "tick-clock cost charged for promotions",
    "stream_batches": "per-token stream batches delivered",
    "stream_tokens": "tokens delivered through token streams",
    "stream_failures": "stream_emit faults: streams closed early",
    "quota_exhausted": "submits refused on tenant page quota",
    "quota_deferrals": "admissions deferred on tenant quota pressure",
    "chunk_deferrals": "prefill chunks deferred on fair-share overrun",
    "tenant_preemptions": "slots requeued for a higher-priority tenant",
    "slo_violations": "finished requests that broke their tenant SLO",
}


class ServingStats:
    """Degradation counters, shared by an engine and its scheduler.
    Pure host-side ints (never read these inside a traced function —
    APX401). ``bench.py gpt_decode`` emits the non-zero subset so the
    driver tracks degradation behavior across rounds.

    Since the observability PR this is a *view* over a
    :class:`~apex_tpu.serving.observe.MetricsRegistry`: every field in
    :data:`STAT_FIELDS` is backed by the ``serving_<field>_total``
    counter in ``registry`` (attribute reads and ``+=`` writes go
    straight to the counter object), so the legacy counter block and
    the Prometheus/JSON exports share storage and cannot drift. The
    engine passes its tracer's registry; a bare ``ServingStats()``
    still works and owns a private registry.
    """

    FIELDS = tuple(STAT_FIELDS)

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 **counts: int):
        unknown = set(counts) - set(STAT_FIELDS)
        if unknown:
            raise TypeError(f"unknown ServingStats fields: {sorted(unknown)}")
        d = self.__dict__
        d["registry"] = registry if registry is not None else MetricsRegistry()
        d["_counters"] = {
            f: d["registry"].counter(f"serving_{f}_total", help=doc)
            for f, doc in STAT_FIELDS.items()}
        for f, v in counts.items():
            d["_counters"][f].value = int(v)

    def __getattr__(self, name):
        c = self.__dict__.get("_counters", {}).get(name)
        if c is None:
            raise AttributeError(name)
        return c.value

    def __setattr__(self, name, value):
        c = self.__dict__.get("_counters", {}).get(name)
        if c is None:
            raise AttributeError(f"ServingStats has no counter {name!r}")
        c.value = int(value)

    def __eq__(self, other):
        if not isinstance(other, ServingStats):
            return NotImplemented
        return ({f: c.value for f, c in self._counters.items()} ==
                {f: c.value for f, c in other._counters.items()})

    def __repr__(self):
        inner = ", ".join(f"{f}={c.value}"
                          for f, c in self._counters.items())
        return f"ServingStats({inner})"

    @property
    def acceptance_rate(self) -> float:
        """Accepted / drafted speculative candidates (0.0 before any
        draft). The number that prices the verify step: at depth k and
        acceptance rate a, the expected tokens per parameter read is
        the expected accepted-prefix length + 1."""
        if not self.tokens_drafted:
            return 0.0
        return self.tokens_accepted / self.tokens_drafted

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {f: c.value for f, c in self._counters.items()}
        d["acceptance_rate"] = round(self.acceptance_rate, 6)
        return d


@dataclasses.dataclass(frozen=True)
class RequestOutcome:
    """How one request ended: its committed token stream plus a typed
    reason (one of :data:`FINISH_REASONS`). Degraded terminations carry
    the :class:`ServingError` that ended them in ``error``; for those,
    ``tokens`` is a prefix of the fault-free stream (quarantine never
    commits a corrupt token).

    ``ttft_ticks`` / ``total_ticks`` are tick-clock latencies stamped
    by the scheduler's tracer bookkeeping: submit -> first committed
    token, and submit -> termination. ``ttft_ticks`` is ``None`` when
    the request died before emitting anything. ``prefill_ticks`` counts
    the ticks that ran prefill work for the request (1 on the
    monolithic path; the number of chunk-carrying ticks, across
    retries, when chunked prefill is on) — ``None`` when the request
    never reached prefill.

    ``tenant_id`` names the tenant the request was submitted under
    (``"default"`` when tenancy is off — byte-compatible with the
    untenanted scheduler). ``slo`` carries a typed
    :class:`SloViolation` when the request finished outside its
    tenant's declared TTFT/ITL bounds; it is a latency diagnostic,
    not a failure — ``ok`` looks only at ``error``."""

    tokens: Tuple[int, ...]
    reason: str
    error: Optional[ServingError] = None
    retries: int = 0
    ttft_ticks: Optional[int] = None
    total_ticks: Optional[int] = None
    prefill_ticks: Optional[int] = None
    tenant_id: str = "default"
    slo: Optional[ServingError] = None

    @property
    def ok(self) -> bool:
        return self.error is None


def snapshot(obj: Any) -> Dict:
    """Best-effort plain-dict view of a stats/outcome object for error
    payloads and bench ``extra`` blocks."""
    if hasattr(obj, "as_dict"):
        return obj.as_dict()
    if dataclasses.is_dataclass(obj):
        return dataclasses.asdict(obj)
    return dict(obj)

"""Host-side observability for the serving engine: tracer + metrics +
flight recorder.

Three pieces, all consulted via injected hooks exactly like
``faults.FaultInjector`` — host-side only, so jitted programs and the
APX512 donation discipline are never perturbed:

- :class:`Tracer` — span/event tracing of the scheduler's tick loop.
  Every event is stamped with TWO clocks: the deterministic tick clock
  (``ContinuousBatchingScheduler._tick_no`` — replay-exact under a
  pinned fault schedule, so two chaos runs at the same seed produce
  byte-identical tick-clock streams) and wall time (``perf_counter`` —
  for humans and Perfetto, excluded from the replay contract).
  ``dump_jsonl`` writes chrome-tracing / Perfetto "JSON object per
  line" events (``ph``/``ts``/``name``; ``ts`` is ticks scaled so one
  tick renders as 1ms, real wall time rides in ``args``).
- :class:`MetricsRegistry` — counters, gauges, and fixed-bucket
  histograms (TTFT in ticks, inter-token ticks, committed tokens per
  tick, per-stream acceptance, pool occupancy, queue depth),
  exportable as JSON (``as_dict``) and Prometheus text format
  (``to_prometheus``). ``health.ServingStats`` is a *view* over this
  registry — the legacy counter block and the exported metrics share
  storage and cannot drift.
- :class:`FlightRecorder` — a bounded ring of the most recent trace
  events. Typed ``ServingError``\\ s (``LivelockError``,
  ``PoolExhausted``, ...) get the ring attached to their ``payload``
  so a chaos failure ships its own last-N-events diagnosis.

The inert contract mirrors ``FaultInjector``: an engine constructed
without a tracer gets ``Tracer(enabled=False)``, and every hook site
in the scheduler is guarded by a single attribute check
(``if trc.enabled:``) — the disabled path adds one branch per site and
records nothing.

Everything here is plain host-side Python state: no jax imports, and
like ``serving.health`` / ``serving.faults`` this module is registered
as APX401 host state — reading a tracer flag, a counter value, or a
recorder ring inside a traced function would freeze it into the
compiled program (``apex_tpu/lint/hygiene.py``).
"""

import bisect
import json
import time
from collections import deque
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Tuple

#: Per-tick phase spans, in tick order. ``prefill`` one jitted
#: whole-prompt forward at admission; ``exec`` covers the jitted
#: decode / verify / tree-verify dispatch inside the engine;
#: ``chunk_prefill`` one jitted prompt-chunk forward (several may run
#: per tick, one span each); ``page_transfer`` one host-staged
#: cross-replica page handoff (``serving.transfer.PageTransfer``,
#: retries included in the span); ``reshard`` one device-to-device
#: spec-to-spec page reshard (``serving.transfer.PageReshard`` — the
#: pool router's default handoff); the rest are host-side scheduler
#: phases. apxlint APX804 resolves every ``begin``/``end`` emit site
#: against this tuple.
PHASES = ("prefill", "draft", "prepare_decode", "exec", "accept",
          "commit", "chunk_prefill", "page_transfer", "reshard")

#: Per-request lifecycle instants. ``host_spill`` / ``host_promote``
#: mark KV pages crossing the HBM <-> host-tier boundary (one instant
#: per spilled page / per promoted chain, ``ok=False`` on a fault or
#: verification failure); ``rebalance`` marks the pool router moving
#: decode placement onto a sibling replica (the N-way failover pick,
#: chosen by pages-free headroom). (``prefill`` is a SPAN, not an
#: instant — it lives in :data:`PHASES`; apxlint APX804 resolves
#: every ``instant`` emit site against this tuple.)
LIFECYCLE = ("submitted", "admitted", "first_token",
             "preempted", "retried", "quarantined", "failover",
             "finished", "host_spill", "host_promote", "rebalance",
             "stream_emit", "slo_violation")

#: Default histogram buckets for tick-denominated latencies (TTFT,
#: inter-token). Roughly geometric: fine where SLOs live, coarse in
#: the tail; +Inf is implicit.
TICK_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
                48.0, 64.0, 96.0, 128.0, 192.0, 256.0, 384.0, 512.0)


def _label_key(labels: Optional[Dict[str, Any]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


def _label_str(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{%s}" % inner


class Counter:
    """Monotonic counter. ``value`` is plain int — ``ServingStats``
    aliases these directly, so reads/writes through either face see
    the same storage."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, Any]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def scalar(self):
        return self.value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, pool
    occupancy, per-stream acceptance)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, Any]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def scalar(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram with Prometheus cumulative-``le``
    semantics: ``bounds`` are ascending finite upper edges, a final
    +Inf bucket is implicit. ``quantile`` interpolates linearly inside
    the containing bucket, so its error is bounded by that bucket's
    width (the overflow bucket interpolates toward the observed max)."""

    kind = "histogram"

    def __init__(self, name: str, buckets: Iterable[float] = TICK_BUCKETS,
                 help: str = "", labels: Optional[Dict[str, Any]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.bounds = tuple(float(b) for b in buckets)
        if not self.bounds or list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(
                f"histogram {name!r}: buckets must be ascending and "
                f"non-empty, got {self.bounds}")
        self.counts = [0] * (len(self.bounds) + 1)  # [-1] = +Inf
        self.count = 0
        self.sum = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated estimate of the q-quantile (0..1), or
        ``None`` if empty."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            if not n:
                continue
            if cum + n >= target:
                lo = self.vmin if i == 0 else self.bounds[i - 1]
                hi = self.vmax if i == len(self.bounds) else self.bounds[i]
                lo = min(lo, hi)
                frac = max(0.0, min(1.0, (target - cum) / n))
                return lo + frac * (hi - lo)
            cum += n
        return self.vmax

    def scalar(self):
        d = {"count": self.count, "sum": self.sum,
             "buckets": dict(zip([*map(str, self.bounds), "+Inf"],
                                 self.counts))}
        for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = self.quantile(q)
            if v is not None:
                d[tag] = round(v, 4)
        return d


class MetricsRegistry:
    """Get-or-create registry of named metrics, keyed by
    ``(name, labels)``. Deterministic: iteration follows creation
    order, no clocks, no randomness."""

    def __init__(self):
        self._metrics: Dict[Tuple, Any] = {}

    def _get(self, cls, name, help, labels, **kw):
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, Any]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, Any]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, buckets: Iterable[float] = TICK_BUCKETS,
                  help: str = "",
                  labels: Optional[Dict[str, Any]] = None) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str,
            labels: Optional[Dict[str, Any]] = None) -> Optional[Any]:
        return self._metrics.get((name, _label_key(labels)))

    def quantiles(self, name: str,
                  qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
                  labels: Optional[Dict[str, Any]] = None,
                  ) -> Optional[Dict[str, float]]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` for a histogram, or
        ``None`` if absent/empty — the bench ``extra`` helper."""
        h = self.get(name, labels)
        if h is None or not isinstance(h, Histogram) or not h.count:
            return None
        return {f"p{int(q * 100)}": h.quantile(q) for q in qs}

    def as_dict(self) -> Dict[str, Any]:
        out = {}
        for (name, _), m in self._metrics.items():
            out[name + _label_str(m.labels)] = m.scalar()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines: List[str] = []
        seen_header = set()
        for (name, _), m in self._metrics.items():
            if name not in seen_header:
                seen_header.add(name)
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
            ls = _label_str(m.labels)
            if m.kind == "histogram":
                cum = 0
                for bound, n in zip([*m.bounds, float("inf")], m.counts):
                    cum += n
                    le = "+Inf" if bound == float("inf") else repr(bound)
                    sep = "," if m.labels else ""
                    inner = ls[1:-1] + sep if m.labels else ""
                    lines.append(
                        f'{name}_bucket{{{inner}le="{le}"}} {cum}')
                lines.append(f"{name}_sum{ls} {m.sum}")
                lines.append(f"{name}_count{ls} {m.count}")
            else:
                lines.append(f"{name}{ls} {m.value}")
        return "\n".join(lines) + "\n"


class TraceEvent(NamedTuple):
    """One trace record. ``tick`` (+ name/ph/ids/args) is the
    deterministic face — :meth:`tick_key` deliberately excludes the
    wall-clock fields so replay-exactness can be asserted byte-for-byte
    across chaos runs. ``wall``/``dur`` (perf_counter seconds) are the
    human face, surfaced only in the Perfetto dump. A NamedTuple, not a
    dataclass: construction sits on the per-tick hot path and tuple
    ``__new__`` is severalfold cheaper than a frozen-dataclass init."""

    name: str
    ph: str                 # "X" complete span | "i" instant
    tick: int
    wall: float
    dur: float = 0.0
    request_id: int = -1
    slot: int = -1
    args: Tuple[Tuple[str, Any], ...] = ()

    def tick_key(self) -> Tuple:
        return (self.name, self.ph, self.tick, self.request_id,
                self.slot, self.args)

    def to_chrome(self) -> Dict[str, Any]:
        """chrome://tracing / Perfetto event dict. ``ts`` is the tick
        clock scaled by 1000 (ticks render as milliseconds; wall-clock
        span durations ride in microseconds, so sub-tick phase timing
        stays visible)."""
        args = dict(self.args)
        args["tick"] = self.tick
        args["wall_s"] = self.wall
        if self.request_id >= 0:
            args["request_id"] = self.request_id
        d = {"name": self.name, "ph": self.ph, "ts": self.tick * 1000,
             "pid": 0, "tid": max(self.slot, 0), "args": args}
        if self.ph == "X":
            d["dur"] = max(round(self.dur * 1e6), 1)
        else:
            d["s"] = "t"  # instant scope: thread
        return d


class FlightRecorder:
    """Bounded ring of the most recent trace events — the black box a
    typed ``ServingError`` carries out of a chaos failure."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)

    def record(self, evt: TraceEvent) -> None:
        self._ring.append(evt)

    def events(self) -> List[TraceEvent]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


class Tracer:
    """Span/event tracer + metric hooks for the scheduler's tick loop.

    Hook contract (mirrors the inert ``FaultInjector``): the scheduler
    holds ``trc = self.tracer`` and guards EVERY call with
    ``if trc.enabled:`` — a disabled tracer costs one attribute check
    per site and records nothing. The scheduler advances :attr:`tick`
    once per loop iteration, so all events within a tick share its
    deterministic timestamp.
    """

    def __init__(self, enabled: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 max_events: int = 1_000_000):
        self.enabled = enabled
        self.registry = registry if registry is not None else MetricsRegistry()
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self.events: List[TraceEvent] = []
        self.tick = 0
        self.dropped = 0
        self._open: Dict[str, Tuple[int, float]] = {}
        self._max_events = max_events
        # per-tick metric hooks resolve their registry entry once and
        # keep the object — the (name, labels)-keyed lookup is off the
        # hot path after first use
        self._hot: Dict[Any, Any] = {}

    # -- event recording ------------------------------------------------

    def set_tick(self, tick: int) -> None:
        self.tick = int(tick)

    def _record(self, evt: TraceEvent) -> None:
        if len(self.events) < self._max_events:
            self.events.append(evt)
        else:
            self.dropped += 1  # ring below still sees it
        self.recorder.record(evt)

    def instant(self, name: str, request_id: int = -1, slot: int = -1,
                **args) -> None:
        self._record(TraceEvent(
            name, "i", self.tick, time.perf_counter(), 0.0,
            request_id, slot,
            tuple(sorted(args.items())) if args else ()))

    def begin(self, name: str) -> None:
        """Open a span; close it with :meth:`end`. Spans are keyed by
        name — the tick loop is single-threaded and phases never nest
        under the same name."""
        self._open[name] = (self.tick, time.perf_counter())

    def end(self, name: str, request_id: int = -1, slot: int = -1,
            **args) -> None:
        tick, t0 = self._open.pop(name, (self.tick, time.perf_counter()))
        self._record(TraceEvent(
            name, "X", tick, t0, time.perf_counter() - t0,
            request_id, slot,
            tuple(sorted(args.items())) if args else ()))

    # -- views / export -------------------------------------------------

    def tick_stream(self) -> Tuple[Tuple, ...]:
        """The deterministic event stream: every event's
        :meth:`~TraceEvent.tick_key`, wall clock excluded. Two runs at
        the same seed under a pinned fault schedule must produce equal
        tick streams (chaos replay contract)."""
        return tuple(e.tick_key() for e in self.events)

    def flight(self, request_id: Optional[int] = None) -> List[Dict]:
        """The flight-recorder ring as chrome dicts (JSON-safe, ready
        for an error payload), optionally filtered to one request."""
        evts = self.recorder.events()
        if request_id is not None:
            evts = [e for e in evts if e.request_id == request_id]
        return [e.to_chrome() for e in evts]

    def attach(self, err) -> Any:
        """Attach the flight-recorder ring to a typed ``ServingError``
        payload and return it."""
        try:
            err.payload["flight"] = self.flight()
        except AttributeError:
            pass  # foreign exception without a payload dict
        return err

    def dump_jsonl(self, path: str) -> int:
        """Write one chrome-tracing JSON object per line (Perfetto and
        chrome://tracing both ingest this). Returns the event count."""
        with open(path, "w") as fh:
            for e in self.events:
                fh.write(json.dumps(e.to_chrome(), sort_keys=True) + "\n")
        return len(self.events)

    # -- metric hooks (names are the stable export surface) -------------

    def observe_ttft(self, ticks: int) -> None:
        h = self._hot.get("ttft")
        if h is None:
            h = self._hot["ttft"] = self.registry.histogram(
                "serving_ttft_ticks",
                help="submit -> first committed token, in scheduler "
                     "ticks")
        h.observe(ticks)

    def observe_itl(self, ticks: int) -> None:
        h = self._hot.get("itl")
        if h is None:
            h = self._hot["itl"] = self.registry.histogram(
                "serving_itl_ticks",
                help="inter-token gap, in scheduler ticks (0 within a "
                     "multi-token speculative commit)")
        h.observe(ticks)

    def observe_tenant_ttft(self, tenant: str, ticks: int) -> None:
        h = self._hot.get(("tttft", tenant))
        if h is None:
            h = self._hot[("tttft", tenant)] = self.registry.histogram(
                "serving_tenant_ttft_ticks",
                help="submit -> first committed token, in scheduler "
                     "ticks, per tenant",
                labels={"tenant": tenant})
        h.observe(ticks)

    def observe_tenant_itl(self, tenant: str, ticks: int) -> None:
        h = self._hot.get(("titl", tenant))
        if h is None:
            h = self._hot[("titl", tenant)] = self.registry.histogram(
                "serving_tenant_itl_ticks",
                help="inter-token gap, in scheduler ticks, per tenant",
                labels={"tenant": tenant})
        h.observe(ticks)

    def tenant_gauges(self, snapshot: Dict[str, Dict[str, float]]) -> None:
        """End-of-tick tenancy rollup: per-tenant page reservations,
        fair-share virtual time, and cumulative committed tokens
        (``snapshot`` comes from ``TenancyPolicy.gauge_snapshot``)."""
        hot = self._hot
        for tenant in sorted(snapshot):
            gs = hot.get(("tenant", tenant))
            if gs is None:
                r = self.registry
                gs = hot[("tenant", tenant)] = (
                    r.gauge("serving_tenant_pages_charged",
                            help="pages reserved against the tenant's "
                                 "quota by its live requests",
                            labels={"tenant": tenant}),
                    r.gauge("serving_tenant_share_vtime",
                            help="weighted fair-share virtual time "
                                 "(charged tokens / weight) — tenants "
                                 "advance together when shares match "
                                 "their weights",
                            labels={"tenant": tenant}),
                    r.gauge("serving_tenant_tokens",
                            help="tokens charged to the tenant so far "
                                 "(committed + prefill chunk tokens)",
                            labels={"tenant": tenant}))
            g_pages, g_vtime, g_tokens = gs
            row = snapshot[tenant]
            g_pages.set(row["pages"])
            g_vtime.set(row["vtime"])
            g_tokens.set(row["tokens"])

    def tenant_latency_summary(self, tenant: str) -> Dict[str, float]:
        """Per-tenant ``{ttft_p50: ..., itl_p99: ...}`` quantile dict —
        the bench helper behind the noisy-neighbor contract; silently
        omits empty histograms."""
        out: Dict[str, float] = {}
        for short, name in (("ttft", "serving_tenant_ttft_ticks"),
                            ("itl", "serving_tenant_itl_ticks")):
            qs = self.registry.quantiles(name,
                                         labels={"tenant": tenant})
            if qs:
                for tag, v in qs.items():
                    out[f"{short}_{tag}"] = round(v, 3)
        return out

    def stream_acceptance(self, slot: int, rate: float) -> None:
        g = self._hot.get(("acc", slot))
        if g is None:
            g = self._hot[("acc", slot)] = self.registry.gauge(
                "serving_stream_acceptance_rate",
                help="per-stream speculative acceptance rate, last tick",
                labels={"slot": slot})
        g.set(rate)

    def tick_metrics(self, committed: int, queue_depth: int,
                     pool: Optional[Dict[str, float]] = None) -> None:
        """End-of-tick rollup: committed-token histogram, queue-depth
        gauge, and (paged engines) pool gauges."""
        hot = self._hot
        if "tick" not in hot:
            r = self.registry
            hot["tick"] = (
                r.histogram(
                    "serving_committed_tokens_per_tick",
                    buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
                    help="tokens committed across all slots in one tick"),
                r.gauge("serving_queue_depth",
                        help="requests waiting for admission"))
        h_commit, g_queue = hot["tick"]
        h_commit.observe(committed)
        g_queue.set(queue_depth)
        if pool:
            if "pool" not in hot:  # dense engines never create these
                r = self.registry
                hot["pool"] = (
                    r.gauge("serving_pages_free",
                            help="free pages in the pool"),
                    r.gauge("serving_pages_cached",
                            help="pages held only by the prefix cache "
                                 "(evictable)"),
                    r.gauge("serving_page_pool_occupancy",
                            help="fraction of usable pages referenced"))
            g_free, g_cached, g_occ = hot["pool"]
            g_free.set(pool["free"])
            g_cached.set(pool["cached"])
            g_occ.set(pool["occupancy"])
            if "host_pages" in pool:  # host-tier engines only
                if "host" not in hot:
                    r = self.registry
                    hot["host"] = (
                        r.gauge("serving_page_pool_hbm_used",
                                help="HBM pages currently referenced"),
                        r.gauge("serving_page_pool_host_pages",
                                help="pages resident in the host spill "
                                     "tier"),
                        r.gauge("serving_page_pool_host_bytes",
                                help="bytes resident in the host spill "
                                     "tier (headers + payload + scales)"),
                        r.gauge("serving_page_pool_host_hit_rate",
                                help="host-tier registry hit rate since "
                                     "start"))
                g_hbm, g_hp, g_hb, g_hr = hot["host"]
                g_hbm.set(pool["hbm_used"])
                g_hp.set(pool["host_pages"])
                g_hb.set(pool["host_bytes"])
                g_hr.set(pool["host_hit_rate"])

    def latency_summary(self) -> Dict[str, float]:
        """``{ttft_p50: ..., itl_p99: ...}`` — flat quantile dict for
        bench ``extra`` blocks; silently omits empty histograms."""
        out: Dict[str, float] = {}
        for short, name in (("ttft", "serving_ttft_ticks"),
                            ("itl", "serving_itl_ticks")):
            qs = self.registry.quantiles(name)
            if qs:
                for tag, v in qs.items():
                    out[f"{short}_{tag}"] = round(v, 3)
        return out

"""Multi-tenant admission policy: weighted fair share, page quotas,
priority preemption and per-tenant SLOs for the serving front-end.

The scheduler's untenanted admission is FIFO + EDF chunk interleaving
(PR 14): fair across requests, blind to who submitted them. This
module adds the *who*: a :class:`Tenant` config per traffic class and
a :class:`TenancyPolicy` the scheduler consults at three points —

- **selection** — which queued request to admit next. Stride
  scheduling over the tick token budget: every token charged to a
  tenant advances its virtual time by ``1 / weight``
  (:meth:`TenancyPolicy.charge_tokens`), and selection prefers
  ``(quota-chargeable, priority desc, vtime asc, tenant id, FIFO)`` —
  so over a backlogged interval each tenant's committed-token share
  converges to its declared weight ratio, heavier tenants advancing
  their vtime more slowly per token. An idle tenant's vtime is
  clamped forward to the busy floor when new work arrives for it
  (:meth:`note_enqueued`), so sleeping never banks credit — while a
  BACKLOGGED tenant (queued or resident work outstanding) keeps its
  earned deficit across request boundaries.
- **quota** — whether the candidate's tenant can reserve its
  worst-case page need. Reservations live in a
  :class:`~apex_tpu.serving.paging.QuotaLedger` charged once per
  request at first admission and credited once at finish; transient
  pressure defers admission (the selection key sorts unchargeable
  candidates last), a request that could NEVER fit raises typed
  :class:`~apex_tpu.serving.health.QuotaExhausted` at ``submit()``.
- **preemption** — whether a strictly-higher-priority waiting tenant
  may requeue a resident lower-priority slot (the scheduler's
  preemption-by-requeue resume path — the same ladder pool pressure
  uses, so recovered streams stay bit-identical).

The policy reorders WHEN work happens, never WHAT commits: sampling
keys depend only on ``(seed, n_generated)``, so committed streams are
integer-identical to the untenanted scheduler — the invariant the
``serving_tenancy_vs_untenanted`` A/B bench asserts.

Host state (APX401): vtimes, ledgers and reservation maps — never
read them inside a traced function.
"""

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

from apex_tpu.serving.health import SloViolation
from apex_tpu.serving.paging import QuotaLedger

#: The tenant every untenanted ``Request`` lands in. A bare
#: ``TenancyPolicy([])`` still defines it (weight 1, no quota,
#: priority 0, no SLOs), so enabling tenancy without classifying
#: traffic changes nothing.
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One traffic class. ``weight`` is the fair-share ratio (tokens
    per tick converge to ``weight / sum(weights)`` among backlogged
    tenants); ``page_quota`` caps the worst-case KV pages its live
    requests may reserve (``None`` = unlimited, dense engines ignore
    it); ``priority`` rungs gate preemption — a strictly higher rung
    may requeue a resident lower rung; the ``*_slo_ticks`` bounds are
    checked at finish and stamp a typed
    :class:`~apex_tpu.serving.health.SloViolation` into
    ``RequestOutcome.slo`` when broken."""

    name: str
    weight: float = 1.0
    page_quota: Optional[int] = None
    priority: int = 0
    ttft_slo_ticks: Optional[int] = None
    itl_slo_ticks: Optional[int] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if not self.weight > 0.0:
            raise ValueError(
                f"tenant {self.name!r} weight must be > 0, got "
                f"{self.weight}")
        for field in ("page_quota", "ttft_slo_ticks", "itl_slo_ticks"):
            v = getattr(self, field)
            if v is not None and v < 1:
                raise ValueError(
                    f"tenant {self.name!r} {field} must be >= 1 or "
                    f"None, got {v}")


class TenancyPolicy:
    """The scheduler-facing tenancy state machine (see module doc).
    Construct with the non-default :class:`Tenant` configs; the
    :data:`DEFAULT_TENANT` is added automatically unless declared."""

    def __init__(self, tenants: Sequence[Tenant] = ()):
        self.tenants: Dict[str, Tenant] = {}
        for t in tenants:
            if t.name in self.tenants:
                raise ValueError(f"duplicate tenant {t.name!r}")
            self.tenants[t.name] = t
        if DEFAULT_TENANT not in self.tenants:
            self.tenants[DEFAULT_TENANT] = Tenant(DEFAULT_TENANT)
        self.ledger = QuotaLedger(
            {name: self.tenants[name].page_quota
             for name in sorted(self.tenants)})
        self._vtime: Dict[str, float] = {
            name: 0.0 for name in sorted(self.tenants)}
        self._tokens: Dict[str, int] = {
            name: 0 for name in sorted(self.tenants)}
        # request id -> (tenant, reserved pages): one charge at first
        # admission, one credit at finish — preempt/requeue/retry in
        # between never touch the books (leak-free by construction)
        self._reserved: Dict[int, Tuple[str, int]] = {}
        # outstanding work per tenant (queued + resident requests):
        # one increment at submit, one decrement at finish. A tenant
        # with live work is BACKLOGGED — its vtime deficit is its
        # fair-share claim and must survive request boundaries; the
        # idle clamp fires only on the 0 -> 1 transition.
        self._live: Dict[str, int] = {
            name: 0 for name in sorted(self.tenants)}

    def has(self, tenant: str) -> bool:
        return tenant in self.tenants

    @property
    def needs_quota(self) -> bool:
        """True when any tenant declares a page quota — the scheduler
        requires a paged engine in that case (quotas price KV pages)."""
        for name in sorted(self.tenants):
            if self.tenants[name].page_quota is not None:
                return True
        return False

    def priority(self, tenant: str) -> int:
        return self.tenants[tenant].priority

    def vtime(self, tenant: str) -> float:
        return self._vtime[tenant]

    def tokens(self, tenant: str) -> int:
        return self._tokens[tenant]

    # -- fair share -------------------------------------------------------

    def charge_tokens(self, tenant: str, n: int) -> None:
        """Advance the tenant's virtual time by ``n / weight`` — called
        for every committed token and every prefill-chunk token, so the
        stride clock prices ALL forward work, not just decode."""
        self._vtime[tenant] += n / self.tenants[tenant].weight
        self._tokens[tenant] += n

    def selection_key(self, tenant: str, chargeable: bool) -> Tuple:
        """Admission-selection sort key, lower is better: chargeable
        candidates first, then priority rung (high first), then
        fair-share vtime (low first — the tenant furthest behind its
        share), then the tenant id as a deterministic tiebreak. The
        scheduler appends queue position for FIFO within a tenant."""
        return (0 if chargeable else 1,
                -self.tenants[tenant].priority,
                self._vtime[tenant],
                tenant)

    # -- quota reservations -----------------------------------------------

    def fits_quota(self, tenant: str, need: int) -> bool:
        """Whether ``need`` pages could EVER fit the tenant's quota
        (the ``submit()`` fail-fast — ignores current reservations)."""
        q = self.tenants[tenant].page_quota
        return q is None or need <= q

    def can_admit(self, request_id: int, tenant: str, need: int) -> bool:
        """Whether admitting the request now stays within quota. A
        request that already holds its reservation (preempted, being
        re-admitted) is always admissible — its pages are pre-paid."""
        if request_id in self._reserved:
            return True
        return self.ledger.can_charge(tenant, need)

    def charge_admission(self, request_id: int, tenant: str,
                         need: int) -> bool:
        """Reserve ``need`` pages for the request (idempotent per id).
        Returns False when quota pressure defers the admission."""
        if request_id in self._reserved:
            return True
        if not self.ledger.can_charge(tenant, need):
            return False
        self.ledger.charge(tenant, need)
        self._reserved[request_id] = (tenant, need)
        return True

    def note_enqueued(self, tenant: str) -> None:
        """Record an arriving request. On the idle -> backlogged
        transition (the tenant had NO outstanding work — queued or
        resident), clamp its vtime forward to the busy floor (the
        minimum vtime among backlogged tenants) so an idle interval
        never banks fair-share credit. A tenant that stayed
        backlogged is left alone: its vtime deficit IS its earned
        fair-share claim, and clamping it at every request boundary
        would collapse stride scheduling into round-robin."""
        if self._live[tenant] == 0:
            floor = None
            for name in sorted(self._live):
                if name != tenant and self._live[name] > 0:
                    v = self._vtime[name]
                    if floor is None or v < floor:
                        floor = v
            if floor is not None and self._vtime[tenant] < floor:
                self._vtime[tenant] = floor
        self._live[tenant] += 1

    def note_finished(self, tenant: str) -> None:
        """Record a request leaving the system (finish — the same
        single exit point :meth:`credit` rides)."""
        if self._live[tenant] < 1:
            raise ValueError(
                f"tenant {tenant!r}: note_finished without a matching "
                "note_enqueued (live-count underflow)")
        self._live[tenant] -= 1

    def credit(self, request_id: int) -> None:
        """Release the request's reservation (called once, at finish —
        the single exit point every request passes through)."""
        row = self._reserved.pop(request_id, None)
        if row is not None:
            tenant, need = row
            self.ledger.credit(tenant, need)

    def charged_total(self) -> int:
        """Pages reserved across all tenants — 0 once the scheduler
        drains (the leak-free check)."""
        total = 0
        for rid in sorted(self._reserved):
            total += self._reserved[rid][1]
        return total

    # -- SLOs -------------------------------------------------------------

    def slo_check(self, tenant: str, ttft_ticks: Optional[int],
                  max_itl_ticks: Optional[int]) -> Optional[SloViolation]:
        """Evaluate a finished request against its tenant's declared
        bounds; returns the typed violation (worst metric first: TTFT
        before ITL) or None."""
        cfg = self.tenants[tenant]
        if (cfg.ttft_slo_ticks is not None and ttft_ticks is not None
                and ttft_ticks > cfg.ttft_slo_ticks):
            return SloViolation(
                f"tenant {tenant!r}: TTFT {ttft_ticks} ticks over the "
                f"{cfg.ttft_slo_ticks}-tick bound",
                tenant=tenant, metric="ttft", observed=ttft_ticks,
                bound=cfg.ttft_slo_ticks)
        if (cfg.itl_slo_ticks is not None and max_itl_ticks
                and max_itl_ticks > cfg.itl_slo_ticks):
            return SloViolation(
                f"tenant {tenant!r}: worst inter-token gap "
                f"{max_itl_ticks} ticks over the "
                f"{cfg.itl_slo_ticks}-tick bound",
                tenant=tenant, metric="itl", observed=max_itl_ticks,
                bound=cfg.itl_slo_ticks)
        return None

    # -- observability ----------------------------------------------------

    def gauge_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant gauge rows for ``Tracer.tenant_gauges``."""
        return {name: {"pages": float(self.ledger.charged(name)),
                       "vtime": self._vtime[name],
                       "tokens": float(self._tokens[name])}
                for name in sorted(self.tenants)}

    def __repr__(self):
        rows = ", ".join(
            f"{name}(w={self.tenants[name].weight}, "
            f"v={self._vtime[name]:.1f})"
            for name in sorted(self.tenants))
        return f"TenancyPolicy({rows})"

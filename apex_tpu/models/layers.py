"""Minimal functional NN layers for the in-tree model zoo.

The reference ships no layer library (its models come from torchvision /
Megatron); these exist so the examples, benchmarks and tests are
self-contained. Conventions: params are nested dicts of arrays; layers are
``init_*(key, ...) -> params`` + ``apply`` functions; compute follows the
AMP policy of the caller (params cast outside, stats in fp32).
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.autocast import cast_args


def lecun_normal(key, shape, fan_in, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)


def kaiming_normal(key, shape, fan_in, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)


def trunc_normal(key, shape, stddev=0.02, dtype=jnp.float32):
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * stddev


# -- dense ------------------------------------------------------------------

def init_dense(key, in_features: int, out_features: int, *, bias: bool = True,
               init=trunc_normal, dtype=jnp.float32) -> dict:
    p = {"kernel": init(key, (in_features, out_features), dtype=dtype)
         if init is trunc_normal
         else init(key, (in_features, out_features), in_features, dtype)}
    if bias:
        p["bias"] = jnp.zeros((out_features,), dtype)
    return p


def dense(params: dict, x: jax.Array) -> jax.Array:
    # No explicit preferred_element_type: widening the output would make the
    # transpose (backward) call dot/conv with an f32 cotangent against a
    # bf16 kernel (dtype-mismatch); the MXU accumulates bf16 matmuls in f32
    # internally regardless.
    # O1: under amp.autocast the op-policy casts both operands to the
    # compute dtype (dense is on FP16_FUNCS); outside the context this is
    # the identity (ref: apex/amp/wrap.py cached_cast over torch.nn.linear)
    x, kernel = cast_args("dense", x, params["kernel"])
    y = jnp.dot(x, kernel.astype(x.dtype))
    if "bias" in params:
        y = y + params["bias"].astype(x.dtype)
    return y


# -- conv (NHWC) ------------------------------------------------------------

def init_conv(key, in_ch: int, out_ch: int, kernel: Tuple[int, int],
              dtype=jnp.float32) -> dict:
    fan_in = in_ch * kernel[0] * kernel[1]
    return {"kernel": kaiming_normal(
        key, kernel + (in_ch, out_ch), fan_in, dtype)}


def conv(params: dict, x: jax.Array, stride: int = 1,
         padding="SAME") -> jax.Array:
    x, kernel = cast_args("conv2d", x, params["kernel"])
    return lax.conv_general_dilated(
        x, kernel.astype(x.dtype),
        window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


# -- batch norm -------------------------------------------------------------

def init_batchnorm(ch: int) -> Tuple[dict, dict]:
    """Returns (params, running_state). Params fp32 (AMP keep_batchnorm_fp32
    default), running stats fp32."""
    params = {"scale": jnp.ones((ch,), jnp.float32),
              "bias": jnp.zeros((ch,), jnp.float32)}
    state = {"mean": jnp.zeros((ch,), jnp.float32),
             "var": jnp.ones((ch,), jnp.float32)}
    return params, state


def batchnorm(params: Optional[dict], state: Optional[dict],
              x: jax.Array, *, train: bool,
              momentum: float = 0.9, eps: float = 1e-5,
              axis_name: Optional[str] = None,
              axis_index_groups=None
              ) -> Tuple[jax.Array, Optional[dict]]:
    """BatchNorm over all but the channel (last) axis.

    ``axis_name``: when set and running inside shard_map/pmap, batch
    statistics are averaged across that mesh axis — this is the SyncBN hook
    used by ``apex_tpu.parallel.SyncBatchNorm`` (ref:
    ``apex/parallel/sync_batchnorm.py``). ``axis_index_groups`` limits the
    sync to rank subgroups (the groupbn ``bn_group`` hook).

    ``momentum`` is the KEEP fraction (new = momentum·old +
    (1-momentum)·batch); the module wrappers expose torch's update
    fraction and pass ``1 - momentum`` here.

    ``params=None`` skips the affine transform (``affine=False``);
    ``state=None`` means no running stats are tracked — batch statistics
    are used even when ``train=False`` (torch's
    ``track_running_stats=False`` semantics).
    """
    x32 = x.astype(jnp.float32)
    use_batch_stats = train or state is None
    if use_batch_stats:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x32, axis=axes)
        mean_sq = jnp.mean(jnp.square(x32), axis=axes)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name,
                             axis_index_groups=axis_index_groups)
            mean_sq = lax.pmean(mean_sq, axis_name,
                                axis_index_groups=axis_index_groups)
        var = mean_sq - jnp.square(mean)
        if train and state is not None:
            n = x32.size // x32.shape[-1]
            if axis_name is not None:
                n = n * lax.psum(1, axis_name,
                                 axis_index_groups=axis_index_groups)
            unbiased = var * (n / max(n - 1, 1))
            new_state = {
                "mean": momentum * state["mean"] + (1 - momentum) * mean,
                "var": momentum * state["var"] + (1 - momentum) * unbiased,
            }
        else:
            new_state = state
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if params is not None:
        y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype), new_state


# -- embedding --------------------------------------------------------------

def init_embedding(key, vocab: int, features: int,
                   dtype=jnp.float32) -> dict:
    return {"embedding": trunc_normal(key, (vocab, features), dtype=dtype)}


def embedding(params: dict, ids: jax.Array, dtype=None) -> jax.Array:
    table = params["embedding"]
    if dtype is not None:
        table = table.astype(dtype)
    return jnp.take(table, ids, axis=0)

"""In-tree model zoo for examples, benchmarks and tests.

The reference's models are external (torchvision ResNet in
``examples/imagenet``; Megatron-style GPT/BERT in
``apex/transformer/testing``); these functional equivalents keep the
framework self-contained on TPU.
"""

from apex_tpu.models.bert import (  # noqa: F401
    BertConfig,
    apply_bert,
    bert_base,
    bert_large,
    bert_partition_specs,
    bert_tiny,
    init_bert,
    mlm_loss,
)
from apex_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    apply_gpt_unsharded,
    gpt_loss_unsharded,
    gpt_medium,
    gpt_partition_specs,
    gpt_pipeline_model,
    gpt_tiny,
    gpt_to_pipeline_params,
    init_gpt,
)
from apex_tpu.models.resnet import (  # noqa: F401
    apply_resnet,
    cross_entropy_loss,
    init_resnet,
)

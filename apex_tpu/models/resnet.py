"""ResNet (v1.5, NHWC) — the ``examples/imagenet`` acceptance model.

Reference entry point: ``examples/imagenet/main_amp.py`` builds a
torchvision ResNet-50; this in-tree functional equivalent exists because
torchvision isn't part of the TPU stack. BatchNorm threads running stats
explicitly and takes an ``axis_name`` so the same model runs under
SyncBatchNorm (``apex_tpu.parallel``) without modification.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.models import layers as L

# (block counts, bottleneck?) per variant
_SPECS = {
    10: ((1, 1, 1, 1), False),  # test/CI tier: smallest compilable resnet
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


def init_resnet(key: jax.Array, depth: int = 50, num_classes: int = 1000,
                dtype=jnp.float32) -> Tuple[Dict, Dict]:
    """Returns (params, batch_stats)."""
    blocks, bottleneck = _SPECS[depth]
    keys = iter(jax.random.split(key, 4 + sum(blocks) * 4 + 8))
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}

    params["stem_conv"] = L.init_conv(next(keys), 3, 64, (7, 7), dtype)
    params["stem_bn"], stats["stem_bn"] = L.init_batchnorm(64)

    in_ch = 64
    for si, n in enumerate(blocks):
        width = 64 * (2 ** si)
        out_ch = width * (4 if bottleneck else 1)
        for bi in range(n):
            name = f"layer{si + 1}_{bi}"
            bp: Dict[str, Any] = {}
            bs: Dict[str, Any] = {}
            stride = 2 if (si > 0 and bi == 0) else 1
            if bottleneck:
                bp["conv1"] = L.init_conv(next(keys), in_ch, width, (1, 1), dtype)
                bp["bn1"], bs["bn1"] = L.init_batchnorm(width)
                bp["conv2"] = L.init_conv(next(keys), width, width, (3, 3), dtype)
                bp["bn2"], bs["bn2"] = L.init_batchnorm(width)
                bp["conv3"] = L.init_conv(next(keys), width, out_ch, (1, 1), dtype)
                bp["bn3"], bs["bn3"] = L.init_batchnorm(out_ch)
            else:
                bp["conv1"] = L.init_conv(next(keys), in_ch, width, (3, 3), dtype)
                bp["bn1"], bs["bn1"] = L.init_batchnorm(width)
                bp["conv2"] = L.init_conv(next(keys), width, out_ch, (3, 3), dtype)
                bp["bn2"], bs["bn2"] = L.init_batchnorm(out_ch)
            if stride != 1 or in_ch != out_ch:
                bp["proj_conv"] = L.init_conv(next(keys), in_ch, out_ch,
                                              (1, 1), dtype)
                bp["proj_bn"], bs["proj_bn"] = L.init_batchnorm(out_ch)
            params[name] = bp
            stats[name] = bs
            in_ch = out_ch

    params["fc"] = L.init_dense(next(keys), in_ch, num_classes,
                                init=L.lecun_normal, dtype=dtype)
    return params, stats


def _block(bp, bs, x, *, stride, bottleneck, train, axis_name, momentum):
    ns = {}
    y = x
    if bottleneck:
        y = L.conv(bp["conv1"], y, 1)
        y, ns["bn1"] = L.batchnorm(bp["bn1"], bs["bn1"], y, train=train,
                                   axis_name=axis_name, momentum=momentum)
        y = jax.nn.relu(y)
        y = L.conv(bp["conv2"], y, stride)
        y, ns["bn2"] = L.batchnorm(bp["bn2"], bs["bn2"], y, train=train,
                                   axis_name=axis_name, momentum=momentum)
        y = jax.nn.relu(y)
        y = L.conv(bp["conv3"], y, 1)
        y, ns["bn3"] = L.batchnorm(bp["bn3"], bs["bn3"], y, train=train,
                                   axis_name=axis_name, momentum=momentum)
    else:
        y = L.conv(bp["conv1"], y, stride)
        y, ns["bn1"] = L.batchnorm(bp["bn1"], bs["bn1"], y, train=train,
                                   axis_name=axis_name, momentum=momentum)
        y = jax.nn.relu(y)
        y = L.conv(bp["conv2"], y, 1)
        y, ns["bn2"] = L.batchnorm(bp["bn2"], bs["bn2"], y, train=train,
                                   axis_name=axis_name, momentum=momentum)
    if "proj_conv" in bp:
        sc = L.conv(bp["proj_conv"], x, stride)
        sc, ns["proj_bn"] = L.batchnorm(bp["proj_bn"], bs["proj_bn"], sc,
                                        train=train, axis_name=axis_name,
                                        momentum=momentum)
    else:
        sc = x
    return jax.nn.relu(y + sc), ns


def apply_resnet(params: Dict, stats: Dict, x: jax.Array, depth: int = 50,
                 *, train: bool = True, axis_name: Optional[str] = None,
                 momentum: float = 0.9
                 ) -> Tuple[jax.Array, Dict]:
    """x: (N, H, W, 3). Returns (logits, new_batch_stats)."""
    blocks, bottleneck = _SPECS[depth]
    new_stats: Dict[str, Any] = {}
    y = L.conv(params["stem_conv"], x, 2)
    y, new_stats["stem_bn"] = L.batchnorm(
        params["stem_bn"], stats["stem_bn"], y, train=train,
        axis_name=axis_name, momentum=momentum)
    y = jax.nn.relu(y)
    y = jax.lax.reduce_window(
        y, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])

    for si, n in enumerate(blocks):
        for bi in range(n):
            name = f"layer{si + 1}_{bi}"
            stride = 2 if (si > 0 and bi == 0) else 1
            y, new_stats[name] = _block(
                params[name], stats[name], y, stride=stride,
                bottleneck=bottleneck, train=train, axis_name=axis_name,
                momentum=momentum)

    y = jnp.mean(y, axis=(1, 2))
    return L.dense(params["fc"], y), new_stats


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))

"""Tensor-parallel GPT (decoder-only transformer).

Reference: ``apex/transformer/testing/standalone_gpt.py`` — the in-tree
Megatron-style GPT the reference uses to exercise its tensor/pipeline
parallel stack end-to-end (ColumnParallelLinear qkv/fc1, RowParallelLinear
proj/fc2, VocabParallelEmbedding, vocab-parallel cross entropy, causal
fused softmax). BASELINE config #5 benchmarks exactly this model at TP=8.

TPU-first design choices (vs. the reference's nn.Module stack):

- **Stacked layers + ``lax.scan``**: all transformer-layer params carry a
  leading ``num_layers`` axis and the depth loop is a scan — compile time
  is O(1) in depth and the same stack reshapes to ``(pp, L/pp, ...)`` for
  the collective pipeline schedules with zero re-plumbing.
- **Two execution paths from one weight layout**: ``apply_gpt`` /
  ``gpt_loss`` run INSIDE ``parallel_state.shard_map`` and speak the TP
  collectives (the Megatron path); ``apply_gpt_unsharded`` is plain jnp on
  the same (full) params — the golden model for parity tests and the
  single-chip path (no mesh needed).
- Attention heads are derived from the LOCAL qkv width at trace time, so
  the same code serves any tp degree without threading tp through shapes.
- The LM head ties to the (vocab-sharded) word embedding; logits stay
  vocab-sharded and feed ``vocab_parallel_cross_entropy`` (never a full
  (b, s, V) softmax — the reference's ``parallel_output=True``).
- RoPE (``use_rope=True``) or learned absolute positions; causal masking
  via the flash kernel above the dispatch crossover, the fused
  upper-triangular softmax below it.
"""

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.autocast import cast_args
from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.functional import (
    flash_attention,
    fused_apply_rotary_pos_emb_bhsd,
    rope_frequencies,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_hidden_size: int = 4096
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    use_rope: bool = False           # learned absolute positions otherwise
    rope_base: float = 10000.0
    hidden_dropout: float = 0.1      # applied only when rng given
    # jax.checkpoint each layer block: live activation memory drops from
    # O(layers) full per-op residual sets to one hidden state per layer
    # plus recompute — mandatory at gpt_medium scale on one chip (ref
    # analogue: Megatron's --recompute-granularity)
    remat: bool = False
    # optional jax.checkpoint policy name (an attribute of
    # jax.checkpoint_policies, e.g. "dots_saveable"): the analogue of
    # Megatron's --recompute-granularity=selective — matmul outputs are
    # SAVED and only the cheap elementwise chain (LN, gelu, residuals)
    # is recomputed in backward. Middle ground between full remat's
    # ~33% fwd recompute and no-remat's O(layers · per-op) live set
    # (whose single-chip gpt_medium program is too large for the
    # compile helper at b>=8, measured r5).
    remat_policy: Optional[str] = None
    # Megatron sequence parallelism: activations OUTSIDE the TP regions
    # (LN, residuals, dropout) are sharded along seq over the model axis
    # (seq_dim=1 in this model's (b, s, h) layout); Column gathers /
    # Row reduce-scatters at the region edges. Requires seq % tp == 0.
    sequence_parallel: bool = False
    # Long-context parallelism: the WHOLE model runs on a sequence shard
    # (ids arrive (b, s/cp)) and attention is ring attention over the
    # ``context`` mesh axis — no rank ever holds the full sequence or an
    # (s, s) score tile. Composes with tp (heads still shard over
    # ``model``). Mutually exclusive with sequence_parallel (different
    # axes, different contracts).
    context_parallel: bool = False
    # which long-context attention runs under context_parallel:
    # "ring" rotates k/v shards (O(cp) permutes, any head count) or
    # "ulysses" all-to-alls seq<->heads (O(1) collectives, needs
    # (num_heads/tp) % cp == 0) — both exact, tested for parity
    context_parallel_impl: str = "ring"
    # per-layer fp32 wgrad emission (the gradient_accumulation_fusion
    # analogue, ref fused_weight_gradient_mlp_cuda): with fp32 master
    # weights + bf16 compute, TP linear wgrads leave each layer at fp32
    # with no bf16 round-trip, so microbatch accumulation keeps low bits
    gradient_accumulation_fusion: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def gpt_medium() -> GPTConfig:
    """GPT-2 medium-class — the BASELINE #5 TP benchmark model."""
    return GPTConfig(remat=True)


def gpt_tiny() -> GPTConfig:
    return GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                     num_heads=8, ffn_hidden_size=128,
                     max_position_embeddings=64)


def draft_gpt_tiny() -> GPTConfig:
    """2-layer draft model pairing :func:`gpt_tiny` for speculative
    serving: same vocab (draft tokens must be target tokens), a fraction
    of the width/depth, and RoPE so the draft's reach is never bound by
    a learned position table shorter than the target's."""
    return GPTConfig(vocab_size=512, hidden_size=32, num_layers=2,
                     num_heads=4, ffn_hidden_size=64,
                     max_position_embeddings=128, use_rope=True)


def draft_gpt_medium() -> GPTConfig:
    """Draft model pairing :func:`gpt_medium` — the cost-model config
    behind the ``gpt_draft_forward_step`` budget entry: its per-step HBM
    traffic (params + draft cache) must stay under 3% of the target's
    per-step parameter read, the amortization condition BASELINE r13
    derives for model-draft break-even.

    ``num_heads=4`` (head_dim 32), not 2: the drafter shares the
    target's pod slice, so its KV-cache head axis must divide every
    tensor-parallel size the target is swept over (APX904 fires on
    ``2 % 4`` at tp=4). Param shapes and cache bytes are unchanged —
    qkv width is ``3 * hidden`` either way."""
    return GPTConfig(vocab_size=50304, hidden_size=128, num_layers=2,
                     num_heads=4, ffn_hidden_size=256,
                     max_position_embeddings=1024, use_rope=True)


# ---------------------------------------------------------------------------
# init — full (unsharded) params; stacked on a leading layer axis
# ---------------------------------------------------------------------------

def _stack(key, n, init_one):
    return jax.vmap(init_one)(jax.random.split(key, n))


def init_gpt(key: jax.Array, cfg: GPTConfig,
             dtype=jnp.float32) -> Dict[str, Any]:
    h, f, L = cfg.hidden_size, cfg.ffn_hidden_size, cfg.num_layers
    k_emb, k_pos, k_layers = jax.random.split(key, 3)

    def dense_init(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype) * math.sqrt(1.0 / fan_in)

    def one_layer(k):
        ks = jax.random.split(k, 4)
        return {
            "ln1": {"weight": jnp.ones((h,), jnp.float32),
                    "bias": jnp.zeros((h,), jnp.float32)},
            "qkv": {"kernel": dense_init(ks[0], h, (h, 3 * h)),
                    "bias": jnp.zeros((3 * h,), dtype)},
            "out": {"kernel": dense_init(ks[1], h, (h, h)),
                    "bias": jnp.zeros((h,), dtype)},
            "ln2": {"weight": jnp.ones((h,), jnp.float32),
                    "bias": jnp.zeros((h,), jnp.float32)},
            "fc1": {"kernel": dense_init(ks[2], h, (h, f)),
                    "bias": jnp.zeros((f,), dtype)},
            "fc2": {"kernel": dense_init(ks[3], f, (f, h)),
                    "bias": jnp.zeros((h,), dtype)},
        }

    params: Dict[str, Any] = {
        "embedding": {"word": {"embedding": jax.random.normal(
            k_emb, (cfg.vocab_size, h), dtype) * 0.02}},
        "layers": _stack(k_layers, L, one_layer),
        "final_ln": {"weight": jnp.ones((h,), jnp.float32),
                     "bias": jnp.zeros((h,), jnp.float32)},
    }
    if not cfg.use_rope:
        params["embedding"]["position"] = {"embedding": jax.random.normal(
            k_pos, (cfg.max_position_embeddings, h), dtype) * 0.02}
    return params


def gpt_partition_specs(cfg: GPTConfig) -> Dict[str, Any]:
    """Megatron TP layout over the ``model`` axis (layer leaves carry the
    leading stacked-layer dim)."""
    from jax.sharding import PartitionSpec as P

    t = ps.TENSOR_AXIS
    specs = {
        "embedding": {"word": {"embedding": P(t, None)}},
        "layers": {
            "ln1": {"weight": P(None), "bias": P(None)},
            "qkv": {"kernel": P(None, None, t), "bias": P(None, t)},
            "out": {"kernel": P(None, t, None), "bias": P(None)},
            "ln2": {"weight": P(None), "bias": P(None)},
            "fc1": {"kernel": P(None, None, t), "bias": P(None, t)},
            "fc2": {"kernel": P(None, t, None), "bias": P(None)},
        },
        "final_ln": {"weight": P(), "bias": P()},
    }
    if not cfg.use_rope:
        specs["embedding"]["position"] = {"embedding": P()}
    return specs


# ---------------------------------------------------------------------------
# shared block math (parameterized by the linear/embedding implementations)
# ---------------------------------------------------------------------------

def _ln(p, x, eps):
    return fused_layer_norm_affine(x, p["weight"], p["bias"],
                                   x.shape[-1], eps).astype(x.dtype)


def _split_qkv(q_k_v: jax.Array, hd: int):
    """(b, s, 3*h_local) head-major -> three (b, nh_local, s, hd)."""
    b, s, w = q_k_v.shape
    nh_local = w // (3 * hd)
    qkv = q_k_v.reshape(b, s, nh_local, 3, hd)
    return (qkv[:, :, :, j].transpose(0, 2, 1, 3) for j in range(3))


def _causal_attention(q_k_v: jax.Array, cfg: GPTConfig,
                      rope_freqs: Optional[jax.Array]) -> jax.Array:
    """(b, s, 3*h_local) -> (b, s, h_local); heads derived from the local
    width so the same code runs at any tp degree.

    qkv column layout is HEAD-MAJOR: ``[head0: q k v | head1: q k v | …]``
    (Megatron's storage order) — a contiguous column shard of the fused
    qkv kernel then holds whole heads, which is what makes plain
    ColumnParallelLinear sharding correct. A ``[Q | K | V]``-major layout
    would hand each rank slices of unrelated heads.
    """
    b, s, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs)
    ctx = flash_attention(q, k, v, causal=True,
                          softmax_scale=1.0 / math.sqrt(hd))
    return ctx.transpose(0, 2, 1, 3).reshape(b, s, -1)


def _ring_causal_attention(q_k_v: jax.Array, cfg: GPTConfig,
                           rope_freqs: Optional[jax.Array]) -> jax.Array:
    """Context-parallel attention: same head-major split, but q/k/v stay
    sequence-sharded and the score/PV work rides the ``context``-axis
    ring (``rope_freqs`` already sliced to this rank's global
    positions)."""
    from apex_tpu.transformer.context_parallel import ring_attention

    return _cp_attention(q_k_v, cfg, rope_freqs, ring_attention)


def _ulysses_causal_attention(q_k_v: jax.Array, cfg: GPTConfig,
                              rope_freqs: Optional[jax.Array]
                              ) -> jax.Array:
    """Context-parallel attention, Ulysses flavor: RoPE is applied on
    the local shard (``rope_freqs`` already globally positioned), then
    one stacked all-to-all gives each rank the FULL sequence for h/cp
    heads (and one brings the context back)."""
    from apex_tpu.transformer.context_parallel import ulysses_attention

    return _cp_attention(q_k_v, cfg, rope_freqs, ulysses_attention)


def _cp_attention(q_k_v, cfg, rope_freqs, attn_fn):
    """Shared context-parallel attention body: split the fused qkv,
    apply RoPE on the local shard, run ``attn_fn``, re-fuse heads."""
    b, s, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs)
    ctx = attn_fn(q, k, v, causal=True,
                  softmax_scale=1.0 / math.sqrt(hd))
    return ctx.transpose(0, 2, 1, 3).reshape(b, s, -1)


_CP_ATTN = {"ring": _ring_causal_attention,
            "ulysses": _ulysses_causal_attention}


def _block(lp, x, cfg, rope_freqs, qkv_fn, out_fn, fc1_fn, fc2_fn,
           dropout_rng=None, ring=False):
    """Pre-LN transformer block: x + Attn(LN(x)); x + MLP(LN(x)).
    ``ring`` is an execution-path choice, not config: the unsharded
    golden model runs the same cfg with plain attention; True selects
    ``cfg.context_parallel_impl``."""
    attn = _CP_ATTN[cfg.context_parallel_impl] if ring \
        else _causal_attention
    with jax.named_scope("attention"):
        att = attn(qkv_fn(lp["qkv"], _ln(lp["ln1"], x,
                                         cfg.layer_norm_eps)),
                   cfg, rope_freqs)
        att = out_fn(lp["out"], att)
        att = _maybe_dropout(att, cfg.hidden_dropout, dropout_rng, 0)
        x = x + att
    with jax.named_scope("mlp"):
        mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
            fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
        mlp = _maybe_dropout(mlp, cfg.hidden_dropout, dropout_rng, 1)
    return x + mlp


# ---------------------------------------------------------------------------
# cache-aware block apply (serving): prefill and single-token decode.
# Parameterized by the same linear fns as _block so the unsharded golden
# path and the TP path share one body (apex_tpu.serving builds both).
# ---------------------------------------------------------------------------

def _prefill_attention(q_k_v: jax.Array, cfg: GPTConfig,
                       rope_freqs: Optional[jax.Array],
                       key_mask: Optional[jax.Array]):
    """Like :func:`_causal_attention` but also returns the (post-RoPE)
    k and raw v tiles so the caller can populate a KV cache, and takes
    an explicit ``key_mask`` ((b, s) int, 1 = real token) so a
    bucket-padded prompt's pad tail is excluded as KEYS. Causality
    already protects real queries from the tail pads (pads sit at the
    END of the bucket), but the mask makes the exclusion unconditional
    — prefill numerics can never depend on pad contents."""
    b, s, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs)
    ctx = flash_attention(q, k, v, key_mask, causal=True,
                          softmax_scale=1.0 / math.sqrt(hd))
    return ctx.transpose(0, 2, 1, 3).reshape(b, s, -1), k, v


def _decode_attention(q_k_v: jax.Array, k_cache: jax.Array,
                      v_cache: jax.Array, pos: jax.Array,
                      cfg: GPTConfig, rope_freqs: Optional[jax.Array]):
    """Single-query attention against a per-slot KV cache.

    ``q_k_v`` is (b, 1, 3*h_local) — the new token's fused projection;
    ``k_cache``/``v_cache`` are (b, nh_local, S_max, hd); ``pos`` (b,)
    int32 is each slot's current length (= the new token's absolute
    position). The new k/v row is written (``lax.dynamic_update_slice``)
    BEFORE attending, so the ``s <= pos`` score mask only ever admits
    rows that hold real tokens — cached pad/stale rows beyond ``pos``
    are unreachable by construction. Scores/softmax run in fp32 (the
    cache may be bf16); returns (ctx (b, 1, h_local), k_cache, v_cache).
    """
    b = q_k_v.shape[0]
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, 1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)

    def write(cache, new, p):
        return lax.dynamic_update_slice(cache, new, (0, p, 0))

    k_cache = jax.vmap(write)(k_cache, k.astype(k_cache.dtype), pos)
    v_cache = jax.vmap(write)(v_cache, v.astype(v_cache.dtype), pos)
    s_max = k_cache.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / math.sqrt(hd)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= pos[:, None, None, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     v_cache.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, 1, -1), k_cache, v_cache


def _block_prefill(lp, x, cfg, rope_freqs, key_mask,
                   qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block` that also emits this layer's (k, v) cache tiles."""
    att, k, v = _prefill_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        cfg, rope_freqs, key_mask)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k, v


def _block_decode(lp, x, k_cache, v_cache, pos, cfg, rope_freqs,
                  qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block` against the cache: x is the (b, 1, h) new-token
    hidden; returns (x', k_cache', v_cache')."""
    att, k_cache, v_cache = _decode_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_cache, v_cache, pos, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_cache, v_cache


def _paged_decode_attention(q_k_v: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, block_tables: jax.Array,
                            pos: jax.Array, cfg: GPTConfig,
                            rope_freqs: Optional[jax.Array]):
    """Single-query attention against a PAGED KV pool.

    ``q_k_v`` is (b, 1, 3*h_local); ``k_pages``/``v_pages`` are
    (num_pages, nh_local, page_size, hd) — one layer's slice of the
    shared physical pool; ``block_tables`` (b, max_pages) int32 maps
    each slot's logical page index to a physical page; ``pos`` (b,)
    int32 is each slot's current length. The paged analogue of
    :func:`_decode_attention`'s write-new-row-then-attend contract: the
    new row is scattered into physical page ``block_tables[b, pos //
    page_size]`` at row ``pos % page_size`` BEFORE attending, then the
    slot's whole table row is gathered back and masked to ``s <= pos``.

    Placement invariance: masked scores are set to ``finfo(f32).min``,
    so their softmax probabilities are EXACTLY zero and garbage beyond
    ``pos`` — stale rows, other requests' pages reached through the
    gather, the scratch page — contributes exactly ``0 * v`` to the
    context. Active-slot logits are therefore bit-identical for any
    physical page assignment of the same logical contents (the serving
    contract ``tests/L0/run_serving`` pins).
    """
    b = q_k_v.shape[0]
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, 1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)
    logical = jnp.clip(pos // page_size, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, logical[:, None], 1)[:, 0]
    rows = pos % page_size
    # (pages, :, rows) pairs advanced indices around a slice, so the
    # scatter value is (b, nh_local, hd): the new row for every slot in
    # one in-place update of the donated pool (APX512's contract)
    k_pages = k_pages.at[pages, :, rows].set(
        k[:, :, 0].astype(k_pages.dtype))
    v_pages = v_pages.at[pages, :, rows].set(
        v[:, :, 0].astype(v_pages.dtype))
    # gather each slot's table row: (b, max_pages, nh, page, hd) ->
    # (b, nh, S, hd) with S = max_pages * page_size logical positions
    kg = k_pages[block_tables].transpose(0, 2, 1, 3, 4)
    vg = v_pages[block_tables].transpose(0, 2, 1, 3, 4)
    s_max = kg.shape[2] * kg.shape[3]
    kg = kg.reshape(b, kg.shape[1], s_max, hd)
    vg = vg.reshape(b, vg.shape[1], s_max, hd)
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) / math.sqrt(hd)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= pos[:, None, None, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     vg.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, 1, -1), k_pages, v_pages


def _block_decode_paged(lp, x, k_pages, v_pages, block_tables, pos, cfg,
                        rope_freqs, qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_decode` over the paged pool (block-table
    indirection instead of a per-slot cache row)."""
    att, k_pages, v_pages = _paged_decode_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, block_tables, pos, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages


def _verify_attention(q_k_v: jax.Array, k_cache: jax.Array,
                      v_cache: jax.Array, pos: jax.Array,
                      cfg: GPTConfig, rope_freqs: Optional[jax.Array]):
    """Multi-query (speculative *verify*) attention against a per-slot
    KV cache: the k+1 generalization of :func:`_decode_attention`.

    ``q_k_v`` is (b, k1, 3*h_local) — the last committed token plus k
    drafted candidates, projected together; ``pos`` (b,) int32 is each
    slot's committed length, so query j sits at absolute position
    ``pos + j`` (RoPE rotates consecutive positions from ``pos``, the
    same ``positions=`` contract the single-token path uses). All k1
    new k/v rows are written (one ``lax.dynamic_update_slice`` block
    per slot) BEFORE attending; the per-query mask ``s <= pos + j``
    then admits exactly the committed history plus the candidate's own
    prefix — write-then-attend, so every admitted row holds a real
    value and logits row j equals a teacher-forced forward at position
    ``pos + j`` bit-for-bit. Rows beyond the accepted prefix are never
    admitted by any later mask before being re-written (positions are
    monotone), which is the whole cache-rollback contract: rejection
    needs no cleanup pass. Callers must guarantee ``pos + k1 <=
    S_max`` (``dynamic_update_slice`` clamps out-of-range starts,
    which would silently shift the write onto committed rows).
    Scores/softmax run in fp32; returns (ctx (b, k1, h_local),
    k_cache, v_cache).
    """
    b, k1, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, k1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)

    def write(cache, new, p):
        return lax.dynamic_update_slice(cache, new, (0, p, 0))

    k_cache = jax.vmap(write)(k_cache, k.astype(k_cache.dtype), pos)
    v_cache = jax.vmap(write)(v_cache, v.astype(v_cache.dtype), pos)
    s_max = k_cache.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / math.sqrt(hd)
    qpos = pos[:, None] + jnp.arange(k1)[None, :]        # (b, k1)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= qpos[:, None, :, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     v_cache.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, k1, -1), k_cache, v_cache


def _block_verify(lp, x, k_cache, v_cache, pos, cfg, rope_freqs,
                  qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_decode` over k1 candidate positions at once."""
    att, k_cache, v_cache = _verify_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_cache, v_cache, pos, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_cache, v_cache


def _paged_verify_attention(q_k_v: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, block_tables: jax.Array,
                            pos: jax.Array, cfg: GPTConfig,
                            rope_freqs: Optional[jax.Array]):
    """Multi-query verify attention against the PAGED pool — the k+1
    generalization of :func:`_paged_decode_attention`, with the same
    write-then-attend and exact-zero masking contracts as
    :func:`_verify_attention` (see there for the rollback argument).
    k1 is static, so the scatter is k1 unrolled single-row updates of
    the donated pool — each position lands in page ``block_tables[b,
    (pos+j) // page_size]`` at row ``(pos+j) % page_size``. Callers
    must hold pages allocated for all k1 positions (the scheduler's
    ``prepare_decode(..., n_new=k1)``).
    """
    b, k1, _ = q_k_v.shape
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, k1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)
    for j in range(k1):
        p = pos + j
        logical = jnp.clip(p // page_size, 0, block_tables.shape[1] - 1)
        pages = jnp.take_along_axis(
            block_tables, logical[:, None], 1)[:, 0]
        rows = p % page_size
        k_pages = k_pages.at[pages, :, rows].set(
            k[:, :, j].astype(k_pages.dtype))
        v_pages = v_pages.at[pages, :, rows].set(
            v[:, :, j].astype(v_pages.dtype))
    kg = k_pages[block_tables].transpose(0, 2, 1, 3, 4)
    vg = v_pages[block_tables].transpose(0, 2, 1, 3, 4)
    s_max = kg.shape[2] * kg.shape[3]
    kg = kg.reshape(b, kg.shape[1], s_max, hd)
    vg = vg.reshape(b, vg.shape[1], s_max, hd)
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) / math.sqrt(hd)
    qpos = pos[:, None] + jnp.arange(k1)[None, :]        # (b, k1)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= qpos[:, None, :, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     vg.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, k1, -1), k_pages, v_pages


def _block_verify_paged(lp, x, k_pages, v_pages, block_tables, pos, cfg,
                        rope_freqs, qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_verify` over the paged pool."""
    att, k_pages, v_pages = _paged_verify_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, block_tables, pos, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages


def _chunk_prefill_attention(q_k_v: jax.Array, k_cache: jax.Array,
                             v_cache: jax.Array, slot: jax.Array,
                             pos: jax.Array, cfg: GPTConfig,
                             rope_freqs: Optional[jax.Array],
                             key_mask: jax.Array):
    """Chunked-prefill attention for ONE slot against the dense cache:
    the prompt-sized generalization of :func:`_verify_attention`.

    ``q_k_v`` is (1, sc, 3*h_local) — one chunk of one slot's prompt,
    projected together; ``slot``/``pos`` are scalar int32 (cache row
    and the chunk's absolute start position, so token j sits at
    ``pos + j``); ``key_mask`` (1, sc) int32 marks real tokens (the
    final chunk of a prompt is bucket-padded at the tail). The chunk's
    k/v rows are zero-masked and written at ``pos`` BEFORE attending —
    write-then-attend, so the per-query ``s <= pos + j`` mask admits
    exactly the previously-written chunks plus the token's own prefix,
    and logits at row j equal a teacher-forced forward at position
    ``pos + j``. Pad queries (mask 0) attend only zeroed rows beyond
    every real query's mask, so their garbage context is unreachable
    from any real row's output. Scores/softmax run in fp32."""
    _, sc, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)            # (1, nh_local, sc, hd)
    p1 = pos[None]
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=p1)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=p1)
    mz = key_mask.astype(k.dtype)[:, None, :, None]
    k_cache = lax.dynamic_update_slice(
        k_cache, (k * mz).astype(k_cache.dtype), (slot, 0, pos, 0))
    v_cache = lax.dynamic_update_slice(
        v_cache, (v * mz).astype(v_cache.dtype), (slot, 0, pos, 0))
    kg = lax.dynamic_slice(k_cache, (slot, 0, 0, 0),
                           (1,) + k_cache.shape[1:])
    vg = lax.dynamic_slice(v_cache, (slot, 0, 0, 0),
                           (1,) + v_cache.shape[1:])
    s_max = kg.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) / math.sqrt(hd)
    qpos = p1[:, None] + jnp.arange(sc)[None, :]         # (1, sc)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= qpos[:, None, :, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     vg.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(1, sc, -1), k_cache, v_cache


def _block_chunk_prefill(lp, x, k_cache, v_cache, slot, pos, cfg,
                         rope_freqs, key_mask, qkv_fn, out_fn, fc1_fn,
                         fc2_fn):
    """:func:`_block_verify` for one slot's prompt chunk."""
    att, k_cache, v_cache = _chunk_prefill_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_cache, v_cache, slot, pos, cfg, rope_freqs, key_mask)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_cache, v_cache


def _paged_chunk_prefill_attention(q_k_v: jax.Array, k_pages: jax.Array,
                                   v_pages: jax.Array,
                                   write_pages: jax.Array,
                                   gather_row: jax.Array, pos: jax.Array,
                                   cfg: GPTConfig,
                                   rope_freqs: Optional[jax.Array],
                                   key_mask: jax.Array):
    """:func:`_chunk_prefill_attention` over the PAGED pool. Chunks are
    whole pages (sc a multiple of page_size), so the write is the
    monolithic paged prefill's page-granular scatter: the chunk's
    zero-masked k/v rows are cut into page tiles and scattered to
    ``write_pages`` ((sc // page_size,) int32 — the host redirects
    prefix-shared pages to ``SCRATCH_PAGE``, so shared pages are never
    rewritten). The attend gathers through ``gather_row`` ((max_pages,)
    int32, the slot's real NULL-padded block-table row) — it is passed
    SEPARATELY from the row the core stores, because the scheduler
    parks the stored row on scratch until the final chunk (mid-prefill
    decode/verify writes by co-tenant steps must land on scratch, not
    on a shared page). Exact-zero masking keeps the result placement-
    invariant, as in :func:`_paged_decode_attention`."""
    _, sc, _ = q_k_v.shape
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    n_chunk_pages = sc // page_size
    q, k, v = _split_qkv(q_k_v, hd)            # (1, nh_local, sc, hd)
    p1 = pos[None]
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=p1)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=p1)
    mz = key_mask.astype(k.dtype)[:, None, :, None]

    def tiles(t, dtype):
        # (1, nh, sc, hd) -> page tiles (n_chunk_pages, nh, page, hd),
        # zero-masked pad rows included (scratch eats redirected pages)
        t = (t * mz)[0]
        t = t.reshape(t.shape[0], n_chunk_pages, page_size, hd)
        return t.transpose(1, 0, 2, 3).astype(dtype)

    k_pages = k_pages.at[write_pages].set(tiles(k, k_pages.dtype))
    v_pages = v_pages.at[write_pages].set(tiles(v, v_pages.dtype))
    kg = k_pages[gather_row][None].transpose(0, 2, 1, 3, 4)
    vg = v_pages[gather_row][None].transpose(0, 2, 1, 3, 4)
    s_max = kg.shape[2] * kg.shape[3]
    kg = kg.reshape(1, kg.shape[1], s_max, hd)
    vg = vg.reshape(1, vg.shape[1], s_max, hd)
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) / math.sqrt(hd)
    qpos = p1[:, None] + jnp.arange(sc)[None, :]         # (1, sc)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= qpos[:, None, :, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     vg.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(1, sc, -1), k_pages, v_pages


def _block_chunk_prefill_paged(lp, x, k_pages, v_pages, write_pages,
                               gather_row, pos, cfg, rope_freqs,
                               key_mask, qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_chunk_prefill` over the paged pool."""
    att, k_pages, v_pages = _paged_chunk_prefill_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, write_pages, gather_row, pos, cfg, rope_freqs,
        key_mask)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages


# ---------------------------------------------------------------------------
# tree verify: one forward scores a whole draft TREE (SpecInfer-style).
# The linear `s <= pos + j` mask generalizes to an ancestor matrix: key
# node i is visible to query node j iff i is an ancestor-of-or-equal-to
# j in the draft tree, so logits row j equal a teacher-forced forward
# over exactly j's root-to-node token path. The linear chain is the
# special case anc[i, j] = (i <= j), depth[j] = j.
# ---------------------------------------------------------------------------

def _tree_score_mask(pos, anc, s_max):
    """(b, 1, k1, k1) tree visibility lifted to the (b, 1, q=k1, s=s_max)
    score layout: key position ``s`` is admitted for query node ``j``
    iff ``s < pos`` (committed history — every node sees all of it) or
    ``s`` holds window node ``i = s - pos`` with ``anc[b, i, j]`` set
    (ancestor-or-self). ``anc`` is (b, k1, k1) bool with anc[j, j]
    required True; rows beyond the window stay masked exactly like the
    linear verify mask, preserving the rollback contract."""
    b, k1, _ = anc.shape
    s_idx = jnp.arange(s_max)
    committed = s_idx[None, :] < pos[:, None]            # (b, s)
    rel = s_idx[None, :] - pos[:, None]                  # (b, s)
    in_win = (rel >= 0) & (rel < k1)
    relc = jnp.clip(rel, 0, k1 - 1)
    vis = jnp.take_along_axis(                           # (b, s, k1)
        anc, jnp.broadcast_to(relc[:, :, None], (b, s_max, k1)), axis=1)
    vis = committed[:, :, None] | (in_win[:, :, None] & vis)
    return vis.transpose(0, 2, 1)[:, None]               # (b, 1, q, s)


def _tree_verify_attention(q_k_v: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, pos: jax.Array,
                           depth: jax.Array, anc: jax.Array,
                           cfg: GPTConfig,
                           rope_freqs: Optional[jax.Array]):
    """Tree-mask verify attention against a per-slot KV cache.

    ``q_k_v`` is (b, k1, 3*h_local) — the grid nodes' fused projection
    in topological order (node 0 = the pending committed token, the
    root every branch hangs off); ``depth`` (b, k1) int32 is each
    node's depth below the committed history, so node j's ATTENTION /
    RoPE position is ``pos + depth[j]`` while its PHYSICAL cache row
    stays ``pos + j`` (distinct rows per node — siblings at one tree
    depth share a position but must not share a row). ``anc`` (b, k1,
    k1) bool is the ancestor-or-self matrix consumed by
    :func:`_tree_score_mask`. Same write-then-attend rollback contract
    as :func:`_verify_attention`: all k1 rows are written before any
    mask admits them, and the host re-sends any committed token whose
    row did not land contiguously (the forced-prefix rule in
    ``scheduler._tree_tick``), so rejected branch rows are overwritten
    before they are ever attended."""
    b, k1, _ = q_k_v.shape
    hd = cfg.head_dim
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, k1, hd)
    if rope_freqs is not None:
        tpos = pos[:, None] + depth                      # (b, k1)
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=tpos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=tpos)

    def write(cache, new, p):
        return lax.dynamic_update_slice(cache, new, (0, p, 0))

    k_cache = jax.vmap(write)(k_cache, k.astype(k_cache.dtype), pos)
    v_cache = jax.vmap(write)(v_cache, v.astype(v_cache.dtype), pos)
    s_max = k_cache.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / math.sqrt(hd)
    valid = _tree_score_mask(pos, anc, s_max)
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     v_cache.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, k1, -1), k_cache, v_cache


def _block_tree_verify(lp, x, k_cache, v_cache, pos, depth, anc, cfg,
                       rope_freqs, qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_verify` under the tree-attention mask."""
    att, k_cache, v_cache = _tree_verify_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_cache, v_cache, pos, depth, anc, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_cache, v_cache


def _paged_tree_verify_attention(q_k_v: jax.Array, k_pages: jax.Array,
                                 v_pages: jax.Array,
                                 block_tables: jax.Array, pos: jax.Array,
                                 depth: jax.Array, anc: jax.Array,
                                 cfg: GPTConfig,
                                 rope_freqs: Optional[jax.Array]):
    """:func:`_tree_verify_attention` over the PAGED pool: the k1
    unrolled row scatters of :func:`_paged_verify_attention` (node j at
    physical position ``pos + j``) with the ancestor-matrix score mask
    and depth-indexed RoPE. Not offered for the int8 pool: an accepted
    non-leftmost branch would require compacting quantized rows across
    pages, re-rounding committed history at branch-dependent scales —
    the engine pins linear spec for kv8 instead."""
    b, k1, _ = q_k_v.shape
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, k1, hd)
    if rope_freqs is not None:
        tpos = pos[:, None] + depth                      # (b, k1)
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=tpos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=tpos)
    for j in range(k1):
        p = pos + j
        logical = jnp.clip(p // page_size, 0, block_tables.shape[1] - 1)
        pages = jnp.take_along_axis(
            block_tables, logical[:, None], 1)[:, 0]
        rows = p % page_size
        k_pages = k_pages.at[pages, :, rows].set(
            k[:, :, j].astype(k_pages.dtype))
        v_pages = v_pages.at[pages, :, rows].set(
            v[:, :, j].astype(v_pages.dtype))
    kg = k_pages[block_tables].transpose(0, 2, 1, 3, 4)
    vg = v_pages[block_tables].transpose(0, 2, 1, 3, 4)
    s_max = kg.shape[2] * kg.shape[3]
    kg = kg.reshape(b, kg.shape[1], s_max, hd)
    vg = vg.reshape(b, vg.shape[1], s_max, hd)
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg.astype(jnp.float32)) / math.sqrt(hd)
    valid = _tree_score_mask(pos, anc, s_max)
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs,
                     vg.astype(jnp.float32)).astype(q_k_v.dtype)
    return ctx.transpose(0, 2, 1, 3).reshape(b, k1, -1), k_pages, v_pages


def _block_tree_verify_paged(lp, x, k_pages, v_pages, block_tables, pos,
                             depth, anc, cfg, rope_freqs,
                             qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_tree_verify` over the paged pool."""
    att, k_pages, v_pages = _paged_tree_verify_attention(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, block_tables, pos, depth, anc, cfg, rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages


# ---------------------------------------------------------------------------
# int8-quantized paged attention: RMW whole-page requant on write,
# dequant inside the gather
# ---------------------------------------------------------------------------

def _q8_page_insert(pool, scale, pages, rows, new_row, rescale=True,
                    zero_dead=False):
    """Insert ``new_row`` (b, nh, hd) fp32 into the int8 page ``pages``
    of each slot at row ``rows`` by a whole-page READ-MODIFY-WRITE
    requant: gather page + scale, dequantize, set the exact new row,
    recompute the per-head amax scale over the whole page, round-requant
    and scatter page + scale back.

    Whole-page RMW is the correctness-bearing choice: quantizing only
    the new row against a RUNNING scale would silently corrupt history
    rows quantized at the old scale. Re-quantizing existing rows at a
    fixed scale is round-to-nearest idempotent, so untouched-amax pages
    come back bit-identical; an amax-raising row re-rounds the history
    at the new scale, which the teacher-forced tolerance gate covers.

    The VERIFY path passes ``zero_dead=True``: every row strictly
    beyond the insert is zeroed before the amax (rows past the insert
    point are stale/speculative garbage by the write-then-attend
    contract, never admitted by any mask), making the new scale a pure
    function of LIVE rows. That is what upgrades the kv8 spec stream
    from tolerance-gated to bit-identical across rejected-tail
    differences (two runs that committed the same tokens but drafted
    different rejected tails requantize every page at identical
    scales). The single-token decode step keeps the whole-tile amax —
    its beyond-rows are zeros, stale-owner garbage (never attended,
    about to be overwritten), or a rejected tail the next verify
    window rewrites before any rescale — preserving r12's plain-tick
    bit pattern exactly.

    ``rescale=False`` (the speculative verify columns j >= 1) pins the
    page's existing scale instead: the new row quantizes (clipped)
    against it and every other row re-rounds at its own scale, which is
    round-to-nearest idempotent — so a SPECULATIVE row can never
    re-round committed history at a scale influenced by other (possibly
    rejected) candidates. A row landing at page row 0 always resets the
    scale (the page holds nothing live below it), which keeps fresh
    pages usable mid-draft and is wiped by the next tick's writes if
    the candidate is rejected. Duplicate scatter targets only arise
    when several inactive slots park on SCRATCH_PAGE — never attended,
    and a 0-or-positive scale always dequantizes finite, so the
    nondeterminism can't escape."""
    from apex_tpu.quant.kernels import kv_dequantize, kv_quantize

    b = pages.shape[0]
    old = scale[pages]                                 # (b, nh)
    tile = kv_dequantize(pool[pages], old)             # (b, nh, page, hd)
    tile = tile.at[jnp.arange(b), :, rows].set(new_row)
    if zero_dead:
        ridx = jnp.arange(tile.shape[2])
        live = ridx[None, None, :, None] <= rows[:, None, None, None]
        tile = jnp.where(live, tile, 0.0)
    nq, ns = kv_quantize(tile)
    if not rescale:
        keep = (rows > 0)[:, None]                     # (b, 1) over heads
        sel = jnp.where(keep, old, ns)
        safe = jnp.where(sel > 0, sel, 1.0)[..., None, None]
        qk = jnp.clip(jnp.round(tile / safe), -127, 127).astype(pool.dtype)
        nq = jnp.where(keep[..., None, None], qk, nq)
        ns = sel
    return pool.at[pages].set(nq), scale.at[pages].set(ns)


def _q8_gather(pool, scale, block_tables, b, hd):
    """Dequantized (b, nh, S, hd) fp32 view of each slot's table row."""
    from apex_tpu.quant.kernels import kv_dequantize

    g = kv_dequantize(pool[block_tables], scale[block_tables])
    g = g.transpose(0, 2, 1, 3, 4)
    return g.reshape(b, g.shape[1], g.shape[2] * g.shape[3], hd)


def _paged_decode_attention_q8(q_k_v, k_pages, v_pages, k_scale, v_scale,
                               block_tables, pos, cfg: GPTConfig,
                               rope_freqs):
    """:func:`_paged_decode_attention` over an INT8 page pool with
    per-page-per-head fp32 scales. Same write-then-attend and exact-zero
    masking contracts; the write is the whole-page RMW requant of
    :func:`_q8_page_insert` and the gather dequantizes against the
    scatter-updated scales, so the attended history is exactly what the
    pool stores. Placement independence survives: the RMW is a pure
    function of page content, and masked probabilities are exactly
    zero."""
    b = q_k_v.shape[0]
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, 1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)
    logical = jnp.clip(pos // page_size, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, logical[:, None], 1)[:, 0]
    rows = pos % page_size
    k_pages, k_scale = _q8_page_insert(
        k_pages, k_scale, pages, rows, k[:, :, 0].astype(jnp.float32))
    v_pages, v_scale = _q8_page_insert(
        v_pages, v_scale, pages, rows, v[:, :, 0].astype(jnp.float32))
    kg = _q8_gather(k_pages, k_scale, block_tables, b, hd)
    vg = _q8_gather(v_pages, v_scale, block_tables, b, hd)
    s_max = kg.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg) / math.sqrt(hd)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= pos[:, None, None, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs, vg).astype(q_k_v.dtype)
    return (ctx.transpose(0, 2, 1, 3).reshape(b, 1, -1),
            k_pages, v_pages, k_scale, v_scale)


def _block_decode_paged_q8(lp, x, k_pages, v_pages, k_scale, v_scale,
                           block_tables, pos, cfg, rope_freqs,
                           qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_decode_paged` over the int8 pool + scales."""
    att, k_pages, v_pages, k_scale, v_scale = _paged_decode_attention_q8(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, k_scale, v_scale, block_tables, pos, cfg,
        rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages, k_scale, v_scale


def _paged_verify_attention_q8(q_k_v, k_pages, v_pages, k_scale, v_scale,
                               block_tables, pos, cfg: GPTConfig,
                               rope_freqs):
    """:func:`_paged_verify_attention` over the int8 pool: k1 unrolled
    whole-page RMW requants (consecutive candidates re-read the latest
    page state, so same-page candidates compose), then the dequantized
    gather with the per-query ``s <= pos + j`` masks. Column 0 is the
    pending COMMITTED token, so it may rescale its page (the amax runs
    over live rows only — :func:`_q8_page_insert` zeroes the dead
    tail); columns j >= 1 are speculative and write with
    ``rescale=False``, pinning the page scale so rejected candidates
    can never re-round committed history. Together these make later
    logits on the int8 cache bit-identical across runs that differ
    only in rejected draft tails (the kv8 spec-stream contract pinned
    by ``test_quant.py::test_kv8_rejected_tails_do_not_perturb``);
    spec-vs-PLAIN kv8 streams remain tolerance-gated, since plain
    decode rescales at every step where verify pins mid-draft.
    """
    b, k1, _ = q_k_v.shape
    hd = cfg.head_dim
    page_size = k_pages.shape[2]
    q, k, v = _split_qkv(q_k_v, hd)            # (b, nh_local, k1, hd)
    if rope_freqs is not None:
        q = fused_apply_rotary_pos_emb_bhsd(q, rope_freqs, positions=pos)
        k = fused_apply_rotary_pos_emb_bhsd(k, rope_freqs, positions=pos)
    for j in range(k1):
        p = pos + j
        logical = jnp.clip(p // page_size, 0, block_tables.shape[1] - 1)
        pages = jnp.take_along_axis(
            block_tables, logical[:, None], 1)[:, 0]
        rows = p % page_size
        k_pages, k_scale = _q8_page_insert(
            k_pages, k_scale, pages, rows,
            k[:, :, j].astype(jnp.float32), rescale=(j == 0),
            zero_dead=True)
        v_pages, v_scale = _q8_page_insert(
            v_pages, v_scale, pages, rows,
            v[:, :, j].astype(jnp.float32), rescale=(j == 0),
            zero_dead=True)
    kg = _q8_gather(k_pages, k_scale, block_tables, b, hd)
    vg = _q8_gather(v_pages, v_scale, block_tables, b, hd)
    s_max = kg.shape[2]
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                        kg) / math.sqrt(hd)
    qpos = pos[:, None] + jnp.arange(k1)[None, :]        # (b, k1)
    valid = jnp.arange(s_max)[None, None, None, :] \
        <= qpos[:, None, :, None]
    scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqs,bhsd->bhqd", probs, vg).astype(q_k_v.dtype)
    return (ctx.transpose(0, 2, 1, 3).reshape(b, k1, -1),
            k_pages, v_pages, k_scale, v_scale)


def _block_verify_paged_q8(lp, x, k_pages, v_pages, k_scale, v_scale,
                           block_tables, pos, cfg, rope_freqs,
                           qkv_fn, out_fn, fc1_fn, fc2_fn):
    """:func:`_block_verify_paged` over the int8 pool + scales."""
    att, k_pages, v_pages, k_scale, v_scale = _paged_verify_attention_q8(
        qkv_fn(lp["qkv"], _ln(lp["ln1"], x, cfg.layer_norm_eps)),
        k_pages, v_pages, k_scale, v_scale, block_tables, pos, cfg,
        rope_freqs)
    x = x + out_fn(lp["out"], att)
    mlp = fc2_fn(lp["fc2"], jax.nn.gelu(
        fc1_fn(lp["fc1"], _ln(lp["ln2"], x, cfg.layer_norm_eps))))
    return x + mlp, k_pages, v_pages, k_scale, v_scale


def _maybe_dropout(x, rate, rng, salt):
    if rng is None or rate <= 0:
        return x
    keep = jax.random.bernoulli(jax.random.fold_in(rng, salt),
                                1 - rate, x.shape)
    return x * keep / (1 - rate)


def _rope_or_none(cfg: GPTConfig, s: int):
    if not cfg.use_rope:
        return None
    return rope_frequencies(cfg.head_dim, s, cfg.rope_base)


# The vetted ZERO-ARG members of jax.checkpoint_policies — directly
# usable as jax.checkpoint(policy=...). Everything else in that
# namespace is a factory (verified by signature inspection: the
# save_*_names / save_from_both_policies / offload_* entries all take
# arguments and return a policy). hasattr-filtered so the set tracks
# whichever jax is running.
_REMAT_POLICIES = frozenset(
    name for name in (
        "checkpoint_dots",
        "checkpoint_dots_with_no_batch_dims",
        "dots_saveable",
        "dots_with_no_batch_dims_saveable",
        "everything_saveable",
        "nothing_saveable",
    ) if hasattr(jax.checkpoint_policies, name))


def _scan_layers(x, layers, cfg, freqs, qkv_fn, out_fn, fc1_fn, fc2_fn,
                 dropout_rng, ring=False):
    """Depth loop: lax.scan over the stacked layer leaves, optionally
    rematerialized per layer (``cfg.remat``)."""
    def block(lp, x, rng):
        return _block(lp, x, cfg, freqs, qkv_fn, out_fn, fc1_fn, fc2_fn,
                      dropout_rng=rng, ring=ring)

    if cfg.remat:
        pol = None
        if cfg.remat_policy:
            # allowlist of the ZERO-ARG policies: callability alone
            # also admits the factory entries (save_only_these_names,
            # save_and_offload_only_these_names, ...) which ARE callable
            # but take names/policies, not residuals — jax.checkpoint
            # would then fail deep inside the scan trace (or worse,
            # treat the factory as an accept-everything predicate)
            # instead of at config time
            if cfg.remat_policy not in _REMAT_POLICIES:
                raise ValueError(
                    f"remat_policy {cfg.remat_policy!r} is not a "
                    "zero-arg jax.checkpoint_policies policy; pick one "
                    f"of {sorted(_REMAT_POLICIES)} (factories like "
                    "'save_only_these_names' need arguments and are "
                    "not usable here)")
            pol = getattr(jax.checkpoint_policies, cfg.remat_policy)
        block = jax.checkpoint(block, policy=pol)
    if dropout_rng is None:
        x, _ = lax.scan(lambda x, lp: (block(lp, x, None), None),
                        x, layers)
    else:
        x, _ = lax.scan(
            lambda x, sl: (block(sl[0], x, sl[1]), None), x,
            (layers, jax.random.split(dropout_rng, cfg.num_layers)))
    return x


# ---------------------------------------------------------------------------
# tensor-parallel path — call inside parallel_state.shard_map
# ---------------------------------------------------------------------------

def _tied_lm_logits(hidden: jax.Array, table_local: jax.Array) -> jax.Array:
    """hidden (replicated) @ local-vocab-shard.T — a ColumnParallelLinear
    in disguise: the input must pass through copy_to_region so the
    BACKWARD all-reduces dhidden across TP ranks (each rank's dlogits @
    table_local is only its vocab slice's partial sum). Forward is the
    identity."""
    from apex_tpu.transformer.tensor_parallel import mappings

    hidden = mappings.copy_to_tensor_model_parallel_region(hidden)
    return jnp.dot(hidden, table_local.astype(hidden.dtype).T).astype(
        jnp.float32)


class GPTModel:
    """Bundles the TP layer objects (Column/Row/VocabParallel) for one
    config. ``apply``/``loss`` run inside shard_map; ``init`` and
    ``partition_specs`` describe the full params."""

    def __init__(self, cfg: GPTConfig, tp_size: Optional[int] = None):
        self.cfg = cfg
        h, f = cfg.hidden_size, cfg.ffn_hidden_size
        t = tp_size if tp_size is not None else \
            ps.get_tensor_model_parallel_world_size()
        if cfg.num_heads % t:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp {t} "
                "(attention heads shard over the model axis)")
        if cfg.sequence_parallel and cfg.context_parallel:
            raise ValueError(
                "sequence_parallel and context_parallel are mutually "
                "exclusive (different axes, different activation "
                "contracts)")
        if cfg.context_parallel_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"context_parallel_impl must be 'ring' or 'ulysses', "
                f"got {cfg.context_parallel_impl!r}")
        if cfg.context_parallel and cfg.context_parallel_impl == "ulysses":
            cp = ps.get_context_parallel_world_size()
            if (cfg.num_heads // t) % cp:
                raise ValueError(
                    f"ulysses context parallelism needs local heads "
                    f"({cfg.num_heads}//tp{t}) divisible by cp={cp}")
        sp = dict(sequence_parallel_enabled=cfg.sequence_parallel,
                  sequence_parallel_seq_dim=1,  # (b, s, h) layout
                  gradient_accumulation_fusion=
                  cfg.gradient_accumulation_fusion)
        self.qkv = tp.ColumnParallelLinear(h, 3 * h, gather_output=False,
                                           tp_size=tp_size, **sp)
        self.out = tp.RowParallelLinear(h, h, input_is_parallel=True,
                                        tp_size=tp_size, **sp)
        self.fc1 = tp.ColumnParallelLinear(h, f, gather_output=False,
                                           tp_size=tp_size, **sp)
        self.fc2 = tp.RowParallelLinear(f, h, input_is_parallel=True,
                                        tp_size=tp_size, **sp)
        self.embed = tp.VocabParallelEmbedding(cfg.vocab_size, h,
                                               tp_size=tp_size)

    def init(self, key: jax.Array, dtype=jnp.float32) -> Dict[str, Any]:
        return init_gpt(key, self.cfg, dtype)

    def partition_specs(self) -> Dict[str, Any]:
        return gpt_partition_specs(self.cfg)

    def apply(self, params: Dict[str, Any], input_ids: jax.Array,
              *, dropout_rng: Optional[jax.Array] = None,
              compute_dtype=None) -> jax.Array:
        """ids (b, s) -> hidden (b, s, h). Inside shard_map over the
        ``model`` axis (tp=1 mesh is fine)."""
        from apex_tpu.transformer.tensor_parallel import mappings

        cfg = self.cfg
        b, s = input_ids.shape
        x = self.embed.apply(params["embedding"]["word"], input_ids)
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        if cfg.context_parallel:
            # ids arrived (b, s/cp): positions and rotary angles are the
            # GLOBAL ones for this rank's shard
            cp_rank = lax.axis_index(ps.CONTEXT_AXIS)
            if not cfg.use_rope:
                pos = lax.dynamic_slice_in_dim(
                    params["embedding"]["position"]["embedding"],
                    cp_rank * s, s, 0)
                x = x + pos.astype(x.dtype)[None]
            freqs = _rope_or_none(
                cfg, s * axis_size(ps.CONTEXT_AXIS))
            if freqs is not None:
                freqs = lax.dynamic_slice_in_dim(freqs, cp_rank * s, s, 0)
            if dropout_rng is not None:
                dropout_rng = jax.random.fold_in(dropout_rng, cp_rank)
            x = _scan_layers(x, params["layers"], cfg, freqs,
                             self.qkv.apply, self.out.apply,
                             self.fc1.apply, self.fc2.apply, dropout_rng,
                             ring=True)
            return _ln(params["final_ln"], x, cfg.layer_norm_eps)
        if not cfg.use_rope:
            pos = params["embedding"]["position"]["embedding"][:s]
            x = x + pos.astype(x.dtype)[None]
        freqs = _rope_or_none(cfg, s)
        if cfg.sequence_parallel:
            # enter the SP region: shard seq over the model axis; the
            # attention itself still sees the full sequence (Column
            # gathers it back). Decorrelate per-rank dropout streams —
            # ranks hold different tokens.
            x = mappings.scatter_to_sequence_parallel_region(x, 1)
            if dropout_rng is not None:
                dropout_rng = jax.random.fold_in(
                    dropout_rng, lax.axis_index(ps.TENSOR_AXIS))
        x = _scan_layers(x, params["layers"], cfg, freqs,
                         self.qkv.apply, self.out.apply,
                         self.fc1.apply, self.fc2.apply, dropout_rng)
        return _ln(params["final_ln"], x, cfg.layer_norm_eps)

    def logits_local(self, params: Dict[str, Any],
                     hidden: jax.Array) -> jax.Array:
        """Tied LM head: (b, s, h) -> vocab-SHARDED logits (b, s, V/tp),
        in rank order (the ``parallel_output=True`` convention)."""
        table = params["embedding"]["word"]["embedding"]
        return _tied_lm_logits(hidden, table)

    def allreduce_sequence_parallel_grads(self, grads: Dict[str, Any]
                                          ) -> Dict[str, Any]:
        """SP closure (ref: Megatron's
        ``allreduce_sequence_parallel_gradients`` step, which the
        training loop runs after backward): params that live in the
        sequence-parallel region — the layer norms and the Row-parallel
        biases — see only the local tokens' grads on each rank; psum
        them over the model axis. No-op when SP is off."""
        if not self.cfg.sequence_parallel:
            return grads

        def fix(path, g):
            keys = "/".join(str(getattr(k, "key", k)) for k in path)
            if ("ln1" in keys or "ln2" in keys or "final_ln" in keys
                    or ("out" in keys and "bias" in keys)
                    or ("fc2" in keys and "bias" in keys)):
                return lax.psum(g, ps.TENSOR_AXIS)
            return g

        return jax.tree_util.tree_map_with_path(fix, grads)

    def loss(self, params: Dict[str, Any], input_ids: jax.Array,
             labels: jax.Array, *,
             dropout_rng: Optional[jax.Array] = None,
             compute_dtype=None) -> jax.Array:
        """Mean next-token loss via vocab-parallel CE (labels = targets,
        NOT shifted here — shift upstream, reference convention)."""
        from apex_tpu.transformer.tensor_parallel import (
            vocab_parallel_cross_entropy,
        )

        from apex_tpu.transformer.tensor_parallel import mappings

        hidden = self.apply(params, input_ids, dropout_rng=dropout_rng,
                            compute_dtype=compute_dtype)
        if self.cfg.sequence_parallel:
            # leave the SP region for the LM head; the gather's backward
            # reduce-scatters dhidden — the SP dual of copy_to_region, so
            # the head dots the gathered hidden directly
            hidden = mappings.gather_from_sequence_parallel_region(
                hidden, True, 1)
            table = params["embedding"]["word"]["embedding"]
            logits = jnp.dot(hidden,
                             table.astype(hidden.dtype).T).astype(
                jnp.float32)
        else:
            logits = self.logits_local(params, hidden)
        loss = vocab_parallel_cross_entropy(logits, labels).mean()
        if self.cfg.context_parallel:
            # per-token losses live on seq shards of equal size: the
            # global mean is the mean of rank means. NOTE the trainer's
            # closure: like DDP over the batch, each rank's AD yields
            # d(local token mean)/dp — pmean the GRADS over the context
            # axis after backward (see test_context_parallel_*).
            loss = lax.pmean(loss, ps.CONTEXT_AXIS)
        return loss


# ---------------------------------------------------------------------------
# unsharded golden path — plain jnp, no mesh
# ---------------------------------------------------------------------------

def apply_gpt_unsharded(params: Dict[str, Any], cfg: GPTConfig,
                        input_ids: jax.Array,
                        *, dropout_rng: Optional[jax.Array] = None,
                        compute_dtype=None) -> jax.Array:
    b, s = input_ids.shape
    table = params["embedding"]["word"]["embedding"]
    if compute_dtype is not None:
        table = table.astype(compute_dtype)
    x = jnp.take(table, input_ids, axis=0)
    if not cfg.use_rope:
        pos = params["embedding"]["position"]["embedding"][:s]
        x = x + pos.astype(x.dtype)[None]
    freqs = _rope_or_none(cfg, s)

    def dense(p, x):
        return jnp.dot(x, p["kernel"].astype(x.dtype)) \
            + p["bias"].astype(x.dtype)

    x = _scan_layers(x, params["layers"], cfg, freqs,
                     dense, dense, dense, dense, dropout_rng)
    return _ln(params["final_ln"], x, cfg.layer_norm_eps)


def gpt_loss_unsharded(params: Dict[str, Any], cfg: GPTConfig,
                       input_ids: jax.Array, labels: jax.Array,
                       *, dropout_rng: Optional[jax.Array] = None,
                       compute_dtype=None) -> jax.Array:
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    hidden = apply_gpt_unsharded(params, cfg, input_ids,
                                 dropout_rng=dropout_rng,
                                 compute_dtype=compute_dtype)
    table = params["embedding"]["word"]["embedding"]
    hidden, table_t = cast_args("matmul", hidden,
                                table.astype(hidden.dtype).T)
    logits = jnp.dot(hidden, table_t)
    # fused xentropy (ref apex/contrib/xentropy): fp32 logsumexp inside
    # the kernel, no (b, s, V) log-softmax ever materialized — at
    # V=50304 that tensor dominated the unsharded step's HBM footprint
    v = logits.shape[-1]
    nll = softmax_cross_entropy_loss(logits.reshape(-1, v),
                                     labels.reshape(-1))
    return nll.mean()


# ---------------------------------------------------------------------------
# pipeline adapter — {"embed", "stages", "head"} layout for the schedules
# ---------------------------------------------------------------------------

def gpt_to_pipeline_params(params: Dict[str, Any], cfg: GPTConfig,
                           pp: int, vpp: Optional[int] = None
                           ) -> Dict[str, Any]:
    """Reshape the stacked ``(L, ...)`` layer leaves into the schedules'
    stage stack: ``(pp, L/pp, ...)``, or ``(vpp, pp, L/(pp*vpp), ...)``
    with the reference's round-robin chunk order (chunk c on device
    c % pp, lane c // pp)."""
    L = cfg.num_layers
    chunks = pp * (vpp or 1)
    if L % chunks:
        raise ValueError(f"num_layers {L} not divisible by {chunks}")
    per = L // chunks

    def reshape(a):
        if vpp is None:
            return a.reshape((pp, per) + a.shape[1:])
        # layer l -> chunk l // per; chunk c -> (lane c // pp, dev c % pp)
        c_first = a.reshape((chunks, per) + a.shape[1:])
        return c_first.reshape((vpp, pp, per) + a.shape[1:])

    return {
        "embed": params["embedding"],
        "stages": jax.tree.map(reshape, params["layers"]),
        "head": {"final_ln": params["final_ln"],
                 "word": params["embedding"]["word"]},
    }


def gpt_pipeline_partition_specs(cfg: GPTConfig,
                                 vpp: Optional[int] = None):
    """PartitionSpecs matching ``gpt_to_pipeline_params``: stage leaves
    gain a leading ``pipe``-sharded stage dim (``(vpp, pp, per, ...)``
    with vpp) while keeping their Megatron TP shardings; the tied word
    table stays vocab-sharded over the model axis in BOTH its embed and
    head copies (a replicated table would make vocab-parallel CE
    double-count sum_exp — the forward is wrong, not just slow)."""
    from jax.sharding import PartitionSpec as P

    base = gpt_partition_specs(cfg)

    def stage_spec(p: P) -> P:
        tail = tuple(p)[1:]  # drop the stacked-L dim's entry
        if vpp is None:
            return P(ps.PIPE_AXIS, None, *tail)
        return P(None, ps.PIPE_AXIS, None, *tail)

    return {
        "embed": base["embedding"],
        "stages": jax.tree.map(stage_spec, base["layers"],
                               is_leaf=lambda x: isinstance(x, P)),
        "head": {"final_ln": base["final_ln"],
                 "word": base["embedding"]["word"]},
    }


def accumulate_tied_word_grads(grads: Dict[str, Any]) -> Dict[str, Any]:
    """Sum the two pipeline-layout copies of the tied word-table grad
    (embed lookup + LM head) into BOTH slots so the copies take
    identical updates and stay tied — Megatron's shared-embedding
    allreduce (ref: ``megatron/model/language_model.py ::
    Embedding`` shared-word-embeddings grad allreduce). Call after the
    pipeline schedule (which already psums embed/head grads over pipe)
    and before the optimizer step."""
    grads = dict(grads)
    tied = jax.tree.map(jnp.add, grads["embed"]["word"],
                        grads["head"]["word"])
    grads["embed"] = dict(grads["embed"], word=tied)
    grads["head"] = dict(grads["head"], word=tied)
    return grads


def gpt_pipeline_model(model: GPTModel) -> "PipelineModel":
    """A ``PipelineModel`` over the TP block — runs inside shard_map over
    BOTH the pipe and model axes (tp×pp)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        PipelineModel,
    )
    from apex_tpu.transformer.tensor_parallel import (
        vocab_parallel_cross_entropy,
    )

    cfg = model.cfg

    def embed_fn(embed_params, mb):
        from apex_tpu.transformer.tensor_parallel import mappings

        ids = mb["input_ids"]
        x = model.embed.apply(embed_params["word"], ids)
        if not cfg.use_rope:
            pos = embed_params["position"]["embedding"][:ids.shape[1]]
            x = x + pos.astype(x.dtype)[None]
        if cfg.sequence_parallel:
            # hidden states travel the pipe seq-sharded; each stage's
            # Column layers gather / Row layers re-scatter internally
            x = mappings.scatter_to_sequence_parallel_region(x, 1)
        return x

    def stage_fn(stage_params, x):
        # under SP the hidden travels seq-sharded (s/tp): rotary angles
        # must span the GLOBAL sequence the Column gather reassembles
        s = x.shape[1]
        if cfg.sequence_parallel:
            s *= ps.get_tensor_model_parallel_world_size()
        freqs = _rope_or_none(cfg, s)

        def body(x, lp):
            return _block(lp, x, cfg, freqs,
                          model.qkv.apply, model.out.apply,
                          model.fc1.apply, model.fc2.apply), None

        x, _ = lax.scan(body, x, stage_params)
        return x

    def loss_fn(head_params, hidden, mb):
        from apex_tpu.transformer.tensor_parallel import mappings

        hidden = _ln(head_params["final_ln"], hidden, cfg.layer_norm_eps)
        if cfg.sequence_parallel:
            hidden = mappings.gather_from_sequence_parallel_region(
                hidden, True, 1)
            table = head_params["word"]["embedding"]
            logits = jnp.dot(hidden,
                             table.astype(hidden.dtype).T).astype(
                jnp.float32)
        else:
            logits = _tied_lm_logits(hidden,
                                     head_params["word"]["embedding"])
        return vocab_parallel_cross_entropy(logits, mb["labels"]).mean()

    return PipelineModel(embed_fn, stage_fn, loss_fn)


# ---------------------------------------------------------------------------
# bench hook (BASELINE config #5)
# ---------------------------------------------------------------------------

def gpt_tp_bench(on_tpu: bool, n_devices: int, *,
                 batch: Optional[int] = None, remat: bool = False
                 ) -> Tuple[Any, Any, Any, int]:
    """Returns (body, make_init, fetch, global_batch) for bench.py:
    a full TP train step (loss, grads inside shard_map; FusedAdam update)
    on a tp=n mesh. ``make_init`` is a zero-arg factory building the
    (params, opt_state) train state on device, so bench.py's donating
    timer keeps exactly ONE copy in HBM. ``batch``/``remat`` let
    bench.py sweep configs the way the BERT headline does."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.optimizers import FusedAdam

    cfg = gpt_medium() if on_tpu else gpt_tiny()
    # gpt_medium() defaults remat=True — OVERRIDE both ways, or every
    # "remat=False" bench config silently pays the ~33% fwd recompute
    # (which is exactly what flattened gpt_tp1_step at ~30 samples/s
    # through rounds 3-4). A string names a jax.checkpoint policy
    # (selective recompute).
    if isinstance(remat, str):
        cfg = dataclasses.replace(cfg, remat=True, remat_policy=remat)
    else:
        cfg = dataclasses.replace(cfg, remat=bool(remat))
    default_b, seq = (8, 1024) if on_tpu else (2, 32)
    batch = default_b if batch is None else batch
    ids = jnp.zeros((batch, seq), jnp.int32)
    labels = jnp.zeros((batch, seq), jnp.int32)
    if n_devices == 1:
        # tp=1: every TP collective is the identity — run the unsharded
        # path so the step compiles without topology metadata (the axon
        # relay's chipless AOT helper cannot resolve host bounds for
        # mesh-collective programs; the CPU rig covers the collectives)
        opt = FusedAdam(lr=1e-4, weight_decay=0.01)

        def make_init():
            params = init_gpt(jax.random.PRNGKey(0), cfg)
            return params, opt.init(params)

        # bf16 compute over fp32 params (O2-style: optimizer math fp32):
        # measured 30.0 vs 23.5 samples/s over fp32 compute on v5e
        vg = jax.value_and_grad(
            lambda p: gpt_loss_unsharded(p, cfg, ids, labels,
                                         compute_dtype=jnp.bfloat16))

        def body1(state):
            p, o = state
            _, grads = vg(p)
            return opt.step(grads, p, o)

        return (body1, make_init,
                lambda s: jnp.sum(s[0]["final_ln"]["weight"]), batch)
    ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size_=n_devices)
    model = GPTModel(cfg, tp_size=n_devices)
    opt = FusedAdam(lr=1e-4, weight_decay=0.01)
    specs = model.partition_specs()
    shard = lambda tree, sp: jax.tree.map(  # noqa: E731
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, sp)

    def make_init():
        # opt.init's zeros_like inherits the params' NamedSharding, so
        # m/v come out sharded without a second device_put pass
        params = shard(model.init(jax.random.PRNGKey(0)), specs)
        return params, opt.init(params)

    ids = jnp.zeros((batch, seq), jnp.int32)
    labels = jnp.zeros((batch, seq), jnp.int32)

    loss_grad = ps.shard_map(
        jax.value_and_grad(
            lambda p, i, t: model.loss(p, i, t,
                                       compute_dtype=jnp.bfloat16),
            argnums=0), mesh=mesh,
        in_specs=(specs, P(), P()),
        out_specs=(P(), specs))

    def body(state):
        p, o = state
        loss, grads = loss_grad(p, ids, labels)
        p, o = opt.step(grads, p, o)
        return (p, o)

    def fetch(state):
        return jnp.sum(state[0]["final_ln"]["weight"])

    return body, make_init, fetch, batch

"""BERT encoder (flagship / north-star model).

The reference has no in-tree BERT; its test GPT/BERT live in
``apex/transformer/testing/standalone_bert.py`` and the north-star workload
is BERT-Large pretrain with amp O2 + FusedAdam + FusedLayerNorm. This is a
functional BERT built on the package's own accelerants:

- ``apex_tpu.normalization.fused_layer_norm_affine`` for every LayerNorm;
- attention softmax routed through ``apex_tpu.transformer.functional``'s
  fused kernel once built (plain jnp softmax until then);
- params are a nested dict so the AMP O2 cast (`keep_batchnorm_fp32` treats
  "layernorm" paths as norms) and TP sharding specs apply mechanically.

Layout: activations are (batch, seq, hidden); attention is
(batch, heads, seq, seq) — MXU-friendly, all dims static.
"""

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.autocast import cast_args
from apex_tpu.models import layers as L
from apex_tpu.normalization import fused_layer_norm_affine


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1     # applied only when rng given
    attention_dropout: float = 0.1
    # fused flash-attention path (ref: apex/contrib multihead_attn/fmha);
    # False falls back to materialized scores + fused softmax kernel
    fused_attention: bool = True
    # jax.checkpoint each encoder layer: one hidden state per layer of
    # live memory plus recompute — unlocks per-chip batch 32 for
    # BERT-Large amp O2 on v5e (b=32 OOMs without it). Ref analogue:
    # tensor_parallel/random.py::CheckpointFunction discipline.
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def bert_large() -> BertConfig:
    return BertConfig()


def bert_base() -> BertConfig:
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072)


def bert_tiny() -> BertConfig:  # for tests / dryruns
    return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_heads=4, intermediate_size=256,
                      max_position_embeddings=128)


def init_bert(key: jax.Array, cfg: BertConfig,
              dtype=jnp.float32) -> Dict[str, Any]:
    keys = iter(jax.random.split(key, 6 + 8 * cfg.num_layers))
    h, i = cfg.hidden_size, cfg.intermediate_size
    params: Dict[str, Any] = {
        "embeddings": {
            "word": L.init_embedding(next(keys), cfg.vocab_size, h, dtype),
            "position": L.init_embedding(
                next(keys), cfg.max_position_embeddings, h, dtype),
            "token_type": L.init_embedding(
                next(keys), cfg.type_vocab_size, h, dtype),
            "layernorm": {"weight": jnp.ones((h,), jnp.float32),
                          "bias": jnp.zeros((h,), jnp.float32)},
        },
        "encoder": [],
        "mlm_head": {
            "transform": L.init_dense(next(keys), h, h, dtype=dtype),
            "layernorm": {"weight": jnp.ones((h,), jnp.float32),
                          "bias": jnp.zeros((h,), jnp.float32)},
            # decoder ties to the word embedding; only a bias is stored
            "bias": jnp.zeros((cfg.vocab_size,), dtype),
        },
        "pooler": L.init_dense(next(keys), h, h, dtype=dtype),
    }
    for _ in range(cfg.num_layers):
        layer = {
            "attention": {
                "qkv": L.init_dense(next(keys), h, 3 * h, dtype=dtype),
                "out": L.init_dense(next(keys), h, h, dtype=dtype),
                "layernorm": {"weight": jnp.ones((h,), jnp.float32),
                              "bias": jnp.zeros((h,), jnp.float32)},
            },
            "mlp": {
                "fc1": L.init_dense(next(keys), h, i, dtype=dtype),
                "fc2": L.init_dense(next(keys), i, h, dtype=dtype),
                "layernorm": {"weight": jnp.ones((h,), jnp.float32),
                              "bias": jnp.zeros((h,), jnp.float32)},
            },
        }
        params["encoder"].append(layer)
    return params


def _ln(p, x, eps):
    return fused_layer_norm_affine(x, p["weight"], p["bias"],
                                   x.shape[-1], eps).astype(x.dtype)


def _attention(p, cfg: BertConfig, x, mask, dropout_rng=None):
    from apex_tpu.transformer.functional import (
        flash_attention, scaled_masked_softmax)

    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    qkv = L.dense(p["qkv"], x).reshape(b, s, 3, nh, hd)
    q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
    if cfg.fused_attention:
        ctx = flash_attention(
            q, k, v, mask, softmax_scale=1.0 / math.sqrt(hd),
            dropout_rate=cfg.attention_dropout, dropout_rng=dropout_rng)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h)
        return L.dense(p["out"], ctx)
    scores = jnp.einsum("bnqd,bnkd->bnqk", *cast_args("einsum", q, k))
    if mask is not None:
        # mask: (b, s) with 1 = attend; the fused kernel masks nonzero
        inv = (1 - mask)[:, None, None, :]
    else:
        inv = jnp.zeros((b, 1, 1, s), jnp.int32)
    probs = scaled_masked_softmax(scores, inv, 1.0 / math.sqrt(hd))
    if dropout_rng is not None and cfg.attention_dropout > 0:
        keep = jax.random.bernoulli(dropout_rng, 1 - cfg.attention_dropout,
                                    probs.shape)
        probs = probs * keep / (1 - cfg.attention_dropout)
    ctx = jnp.einsum("bnqk,bnkd->bnqd", *cast_args("einsum", probs, v))
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h)
    return L.dense(p["out"], ctx)


def _maybe_dropout(x, rate, rng):
    if rng is None or rate <= 0:
        return x
    keep = jax.random.bernoulli(rng, 1 - rate, x.shape)
    return x * keep / (1 - rate)


def apply_bert(params: Dict[str, Any], cfg: BertConfig,
               input_ids: jax.Array,
               attention_mask: Optional[jax.Array] = None,
               token_type_ids: Optional[jax.Array] = None,
               *, dropout_rng: Optional[jax.Array] = None,
               compute_dtype=None) -> Dict[str, jax.Array]:
    """Returns {"hidden": (b,s,h), "mlm_logits": (b,s,vocab),
    "pooled": (b,h)}."""
    b, s = input_ids.shape
    emb = params["embeddings"]
    x = L.embedding(emb["word"], input_ids, compute_dtype)
    x = x + L.embedding(emb["position"], jnp.arange(s), compute_dtype)[None]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    x = x + L.embedding(emb["token_type"], token_type_ids, compute_dtype)
    x = _ln(emb["layernorm"], x, cfg.layer_norm_eps)

    rngs = (jax.random.split(dropout_rng, 2 * cfg.num_layers + 1)
            if dropout_rng is not None else [None] * (2 * cfg.num_layers + 1))
    x = _maybe_dropout(x, cfg.hidden_dropout, rngs[0])

    def encoder_layer(layer, x, rng_a, rng_h):
        with jax.named_scope("attention"):
            att = _attention(layer["attention"], cfg, x, attention_mask,
                             rng_a)
            att = _maybe_dropout(att, cfg.hidden_dropout, rng_h)
            x = _ln(layer["attention"]["layernorm"], x + att,
                    cfg.layer_norm_eps)
        with jax.named_scope("mlp"):
            mlp = L.dense(layer["mlp"]["fc2"],
                          jax.nn.gelu(L.dense(layer["mlp"]["fc1"], x)))
            x = _ln(layer["mlp"]["layernorm"], x + mlp, cfg.layer_norm_eps)
        return x

    if cfg.remat:
        encoder_layer = jax.checkpoint(encoder_layer,
                                       static_argnums=())
    for li, layer in enumerate(params["encoder"]):
        with jax.named_scope(f"layer{li}"):
            x = encoder_layer(layer, x, rngs[2 * li + 1],
                              rngs[2 * li + 2])

    head = params["mlm_head"]
    t = jax.nn.gelu(L.dense(head["transform"], x))
    t = _ln(head["layernorm"], t, cfg.layer_norm_eps)
    word_table = emb["word"]["embedding"].astype(t.dtype)
    mlm_logits = (jnp.dot(t, word_table.T).astype(jnp.float32)
                  + head["bias"].astype(jnp.float32))
    pooled = jnp.tanh(L.dense(params["pooler"], x[:, 0]))
    return {"hidden": x, "mlm_logits": mlm_logits, "pooled": pooled}


def mlm_loss(logits: jax.Array, labels: jax.Array,
             label_mask: jax.Array) -> jax.Array:
    """Masked-LM cross entropy in fp32; ``label_mask`` (1 = predict)
    selects positions. Routed through the fused xentropy kernel (ref:
    ``apex/contrib/xentropy``) so the (b, s, vocab) log-softmax is never
    materialized."""
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    b, s, v = logits.shape
    flat_labels = jnp.where(label_mask != 0, labels, -1).reshape(b * s)
    losses = softmax_cross_entropy_loss(logits.reshape(b * s, v),
                                        flat_labels)
    m = label_mask.astype(jnp.float32)
    return losses.sum() / jnp.maximum(m.sum(), 1.0)


def bert_partition_specs(params: Dict[str, Any]):
    """Megatron-style PartitionSpecs for a BERT param tree over the global
    mesh axes (ref layout: ``apex/transformer/tensor_parallel/layers.py`` —
    qkv/fc1 column-sharded, out/fc2 row-sharded, embeddings vocab-sharded).

    Used by pjit/GSPMD sharding of the whole-model path; the explicit
    shard_map TP layers (phase 7) reproduce the same layout per-layer.
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state as ps

    tp = ps.TENSOR_AXIS

    def spec_for(path) -> P:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        joined = "/".join(keys)
        name = keys[-1]
        if "layernorm" in joined or name == "bias" and "mlm_head" in joined:
            return P()
        if "word" in joined and name == "embedding":
            return P(tp, None)          # vocab-sharded
        if name == "embedding":
            return P()                   # position / token-type replicated
        if "qkv" in joined or "fc1" in joined:
            return P(None, tp) if name == "kernel" else P(tp)
        if ("attention/out" in joined or "fc2" in joined) and name == "kernel":
            return P(tp, None)           # row-parallel
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for(path), params)

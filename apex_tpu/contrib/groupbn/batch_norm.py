"""Group-synchronized NHWC BatchNorm with fused add+ReLU epilogue.

Reference: ``apex/contrib/groupbn/batch_norm.py :: BatchNorm2d_NHWC``
(CUDA in ``csrc/groupbn/*``) — the MLPerf ResNet block: NHWC batch norm
whose statistics sync across a GROUP of ``bn_group`` GPUs (not the whole
world), with the residual add and ReLU fused into the normalization
kernel's epilogue.

TPU mapping: group-limited stat sync is ``lax.pmean`` with
``axis_index_groups`` partitioning the data axis into consecutive groups
of ``bn_group`` ranks — XLA emits the reduced-scope allreduce over ICI
exactly as the CUDA kernels run NCCL on a sub-communicator. The
add+ReLU epilogue is ordinary code XLA fuses into the normalization's
elementwise chain (the "let XLA fuse" rule); stats are fp32.
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models import layers as L
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size


class BatchNorm2d_NHWC:
    """``init() -> (params, running_state)``; ``apply(params, state, x,
    z=None, train=...) -> (y, new_state)``. ``bn_group=0`` syncs across
    the WHOLE axis; ``bn_group=1`` is rank-local (the reference
    default); ``k > 1`` syncs consecutive groups of k ranks.

    The stat machinery is ``layers.batchnorm`` (the one SyncBN uses)
    with an ``axis_index_groups`` restriction — one implementation, one
    momentum convention (this class exposes torch's UPDATE fraction,
    default 0.1, and hands the keep fraction down)."""

    def __init__(self, num_features: int, *, fuse_relu: bool = False,
                 bn_group: int = 1, momentum: float = 0.1,
                 eps: float = 1e-5,
                 axis_name: Optional[str] = None):
        self.num_features = num_features
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.momentum = momentum
        self.eps = eps
        self.axis_name = axis_name if axis_name is not None else \
            ps.DATA_AXIS

    def init(self) -> Tuple[Dict, Dict]:
        return L.init_batchnorm(self.num_features)

    def _groups(self):
        n = axis_size(self.axis_name)
        k = n if self.bn_group == 0 else self.bn_group
        if n % k:
            raise ValueError(
                f"bn_group {k} does not divide axis size {n}")
        return [list(range(g * k, (g + 1) * k)) for g in range(n // k)]

    def apply(self, params: Dict, state: Dict, x: jax.Array,
              z: Optional[jax.Array] = None, *, train: bool = True
              ) -> Tuple[jax.Array, Dict]:
        sync = self.bn_group != 1  # bn_group=1: rank-local, no collective
        y, new_state = L.batchnorm(
            params, state, x, train=train,
            momentum=1.0 - self.momentum, eps=self.eps,
            axis_name=self.axis_name if (sync and train) else None,
            axis_index_groups=self._groups() if (sync and train) else None)
        if z is not None or self.fuse_relu:
            # the fused add+ReLU epilogue (reference: bn_add_relu kernel);
            # XLA fuses this into the normalization's elementwise chain
            y32 = y.astype(jnp.float32)
            if z is not None:
                y32 = y32 + z.astype(jnp.float32)
            if self.fuse_relu:
                y32 = jax.nn.relu(y32)
            y = y32.astype(x.dtype)
        return y, new_state

    __call__ = apply

"""Group-synchronized NHWC BatchNorm with fused add+ReLU epilogue.

Reference: ``apex/contrib/groupbn/batch_norm.py :: BatchNorm2d_NHWC``
(CUDA in ``csrc/groupbn/*``) — the MLPerf ResNet block: NHWC batch norm
whose statistics sync across a GROUP of ``bn_group`` GPUs (not the whole
world), with the residual add and ReLU fused into the normalization
kernel's epilogue.

TPU mapping: group-limited stat sync is ``lax.pmean`` with
``axis_index_groups`` partitioning the data axis into consecutive groups
of ``bn_group`` ranks — XLA emits the reduced-scope allreduce over ICI
exactly as the CUDA kernels run NCCL on a sub-communicator. The
add+ReLU epilogue is ordinary code XLA fuses into the normalization's
elementwise chain (the "let XLA fuse" rule); stats are fp32.
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps


class BatchNorm2d_NHWC:
    """``init() -> (params, running_state)``; ``apply(params, state, x,
    z=None, train=...) -> (y, new_state)``. ``bn_group=0`` syncs across
    the WHOLE axis; ``bn_group=1`` is rank-local (the reference
    default); ``k > 1`` syncs consecutive groups of k ranks."""

    def __init__(self, num_features: int, *, fuse_relu: bool = False,
                 bn_group: int = 1, momentum: float = 0.1,
                 eps: float = 1e-5,
                 axis_name: Optional[str] = None):
        self.num_features = num_features
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.momentum = momentum
        self.eps = eps
        self.axis_name = axis_name if axis_name is not None else \
            ps.DATA_AXIS

    def init(self) -> Tuple[Dict, Dict]:
        params = {"scale": jnp.ones((self.num_features,), jnp.float32),
                  "bias": jnp.zeros((self.num_features,), jnp.float32)}
        state = {"mean": jnp.zeros((self.num_features,), jnp.float32),
                 "var": jnp.ones((self.num_features,), jnp.float32)}
        return params, state

    def _groups(self):
        if self.bn_group == 1:
            return None  # rank-local stats: no collective at all
        n = lax.axis_size(self.axis_name)
        k = n if self.bn_group == 0 else self.bn_group
        if n % k:
            raise ValueError(
                f"bn_group {k} does not divide axis size {n}")
        return [list(range(g * k, (g + 1) * k)) for g in range(n // k)]

    def apply(self, params: Dict, state: Dict, x: jax.Array,
              z: Optional[jax.Array] = None, *, train: bool = True
              ) -> Tuple[jax.Array, Dict]:
        x32 = x.astype(jnp.float32)
        if train:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x32, axis=axes)
            mean_sq = jnp.mean(jnp.square(x32), axis=axes)
            if self.bn_group != 1:
                groups = self._groups()
                mean = lax.pmean(mean, self.axis_name,
                                 axis_index_groups=groups)
                mean_sq = lax.pmean(mean_sq, self.axis_name,
                                    axis_index_groups=groups)
            var = mean_sq - jnp.square(mean)
            n = x32.size // x32.shape[-1]
            if self.bn_group != 1:
                n = n * (lax.axis_size(self.axis_name)
                         if self.bn_group == 0 else self.bn_group)
            unbiased = var * (n / max(n - 1, 1))
            new_state = {
                "mean": (1 - self.momentum) * state["mean"]
                + self.momentum * mean,
                "var": (1 - self.momentum) * state["var"]
                + self.momentum * unbiased,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        y = (x32 - mean) * lax.rsqrt(var + self.eps)
        y = y * params["scale"] + params["bias"]
        if z is not None:
            # the fused add epilogue (reference: bn_add_relu kernel)
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype), new_state

    __call__ = apply

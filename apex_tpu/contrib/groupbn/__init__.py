"""Group BatchNorm (ref: ``apex/contrib/groupbn``)."""

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC  # noqa: F401

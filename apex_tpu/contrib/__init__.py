"""Optional accelerants (ref: ``apex/contrib``).

The reference gates each contrib package behind a build flag
(``setup.py --xentropy --fast_multihead_attn ...``); here everything is
importable — kernels compile on TPU and interpret on CPU.

- :mod:`xentropy` — fused softmax-cross-entropy (no materialized softmax)
- ``multihead_attn`` lives as the flash-attention kernel in
  ``apex_tpu.transformer.functional.flash_attention`` (SURVEY §2b: the
  fmha/fast_multihead_attn rows are subsumed by it).
"""

from apex_tpu.contrib import xentropy  # noqa: F401

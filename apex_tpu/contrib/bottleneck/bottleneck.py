"""Spatially-parallel bottleneck block.

Reference: ``apex/contrib/bottleneck/bottleneck.py`` (``Bottleneck`` /
``SpatialBottleneck``) — the ResNet bottleneck whose 3x3 conv runs with
the image's H dimension sharded across GPUs, fed by the peer-memory
halo exchange.

TPU version: the same three-conv block (1x1 reduce -> 3x3 spatial ->
1x1 expand, residual add) where the sharded variant widens its local
shard by one halo row from each H-neighbor via
:func:`~apex_tpu.contrib.peer_memory.halo_exchange_1d` over the
``context`` mesh axis, then runs the 3x3 conv VALID in H over the
widened shard. The exchange zero-fills at the outer boundary, which is
exactly SAME zero padding — so the sharded block is numerically
identical to the unsharded reference, not an approximation, and the
parity test asserts equality to float tolerance.

Layout is NHWC with HWIO weights (the TPU-native convolution layout);
stride is 1 and channels are in == out so the residual needs no
projection — the minimal block that exercises the communication
pattern. The reference's CUDNN-workspace/frozen-BN machinery has no
TPU analogue and is intentionally absent.
"""

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.peer_memory import halo_exchange_1d
from apex_tpu.transformer import parallel_state as ps

_DIMS = ("NHWC", "HWIO", "NHWC")


def init_spatial_bottleneck(key, channels: int, bottleneck_channels: int,
                            dtype=jnp.float32):
    """He-initialized params for a stride-1 bottleneck (no projection)."""
    k1, k2, k3 = jax.random.split(key, 3)

    def he(k, shape):
        fan_in = shape[0] * shape[1] * shape[2]
        return (jax.random.normal(k, shape) *
                jnp.sqrt(2.0 / fan_in)).astype(dtype)

    return {
        "w1": he(k1, (1, 1, channels, bottleneck_channels)),
        "w2": he(k2, (3, 3, bottleneck_channels, bottleneck_channels)),
        "w3": he(k3, (1, 1, bottleneck_channels, channels)),
    }


def _conv(x, w, padding):
    return lax.conv_general_dilated(x, w, window_strides=(1, 1),
                                    padding=padding,
                                    dimension_numbers=_DIMS)


def spatial_bottleneck(params, x: jax.Array) -> jax.Array:
    """Unsharded reference block on a full NHWC tensor."""
    y = jax.nn.relu(_conv(x, params["w1"], "VALID"))
    y = jax.nn.relu(_conv(y, params["w2"], "SAME"))
    y = _conv(y, params["w3"], "VALID")
    return jax.nn.relu(x + y)


def spatial_parallel_bottleneck(params, x: jax.Array, *,
                                axis_name: str = ps.CONTEXT_AXIS,
                                ) -> jax.Array:
    """The same block on an H-sharded local shard (inside shard_map).

    Only the 3x3 conv sees neighbor pixels: its input is widened by a
    one-row halo from each H-neighbor, then convolved VALID in H (the
    halo plays the role of SAME padding's zero ring — zero-filled at
    the outer boundary by the exchange) and SAME in W. The 1x1 convs
    and the residual are purely local.
    """
    y = jax.nn.relu(_conv(x, params["w1"], "VALID"))
    y = halo_exchange_1d(y, 1, axis=1, axis_name=axis_name)
    y = jax.nn.relu(_conv(y, params["w2"], [(0, 0), (1, 1)]))
    y = _conv(y, params["w3"], "VALID")
    return jax.nn.relu(x + y)

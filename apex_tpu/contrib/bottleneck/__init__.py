from apex_tpu.contrib.bottleneck.bottleneck import (  # noqa: F401
    init_spatial_bottleneck,
    spatial_bottleneck,
    spatial_parallel_bottleneck,
)

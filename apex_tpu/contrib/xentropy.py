"""Fused softmax-cross-entropy — Pallas kernels.

Reference: ``apex/contrib/csrc/xentropy/xentropy_kernel.cu`` +
``apex/contrib/xentropy :: SoftmaxCrossEntropyLoss`` — loss (with
in-place label smoothing) computed WITHOUT materializing the softmax /
log-softmax over the vocabulary.

The naive jnp path materializes an (N, V) fp32 log-softmax (≈4 GB for
a 32×512 batch over a 30k vocab) plus the gather; here the forward is a
flash-style online logsumexp sweep over vocab tiles producing only the
per-row ``(loss, lse)`` — O(N) HBM output — and the backward emits
``dx = (softmax(x) - target) * dloss`` tile by tile, recomputing
``exp(x - lse)`` from the saved lse instead of re-normalizing.

Semantics (matching the reference kernel):
- ``loss = lse - (1-eps) * x[label] - eps * mean_valid(x)``
  (label smoothing spreads eps uniformly over the vocab);
- rows with ``label < 0`` are ignored (zero loss, zero grad) — the
  functional analogue of the reference's padding handling.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.math import round_up_to_multiple
from apex_tpu.utils.pallas import dimsem as _dimsem, NEG_INF as _NEG, pad2 as _pad2
from apex_tpu.utils.platform import pallas_interpret

_BR = 256     # rows per block (sublane dim)
_BV = 2048    # vocab lanes per block


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref,
                m_ref, l_ref, xy_ref, xsum_ref, *, n, v, eps):
    rt, vt = pl.program_id(0), pl.program_id(1)
    nv = pl.num_programs(1)
    br = x_ref.shape[0]

    @pl.when(vt == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        xy_ref[:] = jnp.zeros_like(xy_ref)
        xsum_ref[:] = jnp.zeros_like(xsum_ref)

    x = x_ref[:].astype(jnp.float32)
    bv = x.shape[1]
    col = vt * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    in_vocab = col < v
    x = jnp.where(in_vocab, x, _NEG)

    m_prev = m_ref[:, 0:1]
    m_cur = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.where(in_vocab, jnp.exp(x - m_cur), 0.0)
    l_ref[:, 0:1] = l_ref[:, 0:1] * alpha + jnp.sum(p, 1, keepdims=True)
    m_ref[:, 0:1] = m_cur

    labels = lab_ref[0, pl.ds(rt * br, br)][:, None]  # (br, 1)
    xy_ref[:, 0:1] += jnp.sum(jnp.where(col == labels, x, 0.0), 1,
                              keepdims=True)
    if eps > 0.0:
        xsum_ref[:, 0:1] += jnp.sum(jnp.where(in_vocab, x, 0.0), 1,
                                    keepdims=True)

    @pl.when(vt == nv - 1)
    def _():
        lse = m_ref[:, 0] + jnp.log(l_ref[:, 0])
        labels_row = lab_ref[0, pl.ds(rt * br, br)]
        row = rt * br + jax.lax.broadcasted_iota(
            jnp.int32, (br, 1), 0)[:, 0]
        ignore = (labels_row < 0) | (row >= n)
        loss = lse - (1.0 - eps) * xy_ref[:, 0]
        if eps > 0.0:
            loss = loss - eps * xsum_ref[:, 0] / v
        loss_ref[0, pl.ds(rt * br, br)] = jnp.where(ignore, 0.0, loss)
        lse_ref[0, pl.ds(rt * br, br)] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, dl_ref, dx_ref, *, n, v, eps):
    rt, vt = pl.program_id(0), pl.program_id(1)
    br = x_ref.shape[0]
    x = x_ref[:].astype(jnp.float32)
    bv = x.shape[1]
    col = vt * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    in_vocab = col < v
    lse = lse_ref[0, pl.ds(rt * br, br)][:, None]
    labels = lab_ref[0, pl.ds(rt * br, br)][:, None]
    dloss = dl_ref[0, pl.ds(rt * br, br)][:, None]
    row = rt * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    live = jnp.logical_not((labels < 0) | (row >= n))
    soft = jnp.exp(x - lse)
    target = (1.0 - eps) * (col == labels).astype(jnp.float32)
    if eps > 0.0:
        target = target + eps / v
    g = jnp.where(in_vocab & live, (soft - target) * dloss, 0.0)
    dx_ref[:] = g.astype(dx_ref.dtype)


def _row_spec(n_p):
    return pl.BlockSpec((1, n_p), lambda rt, vt: (0, 0),
                        memory_space=pltpu.VMEM)


def _fwd_call(logits, labels, eps, interpret):
    n, v = logits.shape
    n_p = round_up_to_multiple(n, _BR)
    bv = min(_BV, round_up_to_multiple(v, 128))
    v_p = round_up_to_multiple(v, bv)
    xp = _pad2(logits, n_p, v_p)
    lab = jnp.pad(labels.astype(jnp.int32), (0, n_p - n),
                  constant_values=-1)[None, :]
    grid = (n_p // _BR, v_p // bv)
    x_spec = pl.BlockSpec((_BR, bv), lambda rt, vt: (rt, vt),
                          memory_space=pltpu.VMEM)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n=n, v=v, eps=eps),
        grid=grid,
        in_specs=[x_spec, _row_spec(n_p)],
        out_specs=(_row_spec(n_p), _row_spec(n_p)),
        out_shape=(jax.ShapeDtypeStruct((1, n_p), jnp.float32),
                   jax.ShapeDtypeStruct((1, n_p), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((_BR, 128), jnp.float32)] * 4,
        # BOTH dims arbitrary: the (1, n_p) loss/lse outputs are one
        # revisited block each row-tile writes a slice of — a "parallel"
        # rt could be split across megacore TensorCores, each holding a
        # private copy and losing the other's slices
        compiler_params=_dimsem("arbitrary", "arbitrary"),
        interpret=pallas_interpret(interpret),
    )(xp, lab)
    return loss[0, :n], lse  # lse stays padded (1, n_p)


def _bwd_call(logits, labels, lse_p, dloss, eps, interpret):
    n, v = logits.shape
    n_p = round_up_to_multiple(n, _BR)
    bv = min(_BV, round_up_to_multiple(v, 128))
    v_p = round_up_to_multiple(v, bv)
    xp = _pad2(logits, n_p, v_p)
    lab = jnp.pad(labels.astype(jnp.int32), (0, n_p - n),
                  constant_values=-1)[None, :]
    dl = jnp.pad(dloss.astype(jnp.float32), (0, n_p - n))[None, :]
    grid = (n_p // _BR, v_p // bv)
    x_spec = pl.BlockSpec((_BR, bv), lambda rt, vt: (rt, vt),
                          memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, n=n, v=v, eps=eps),
        grid=grid,
        in_specs=[x_spec, _row_spec(n_p), _row_spec(n_p), _row_spec(n_p)],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((n_p, v_p), logits.dtype),
        compiler_params=_dimsem("parallel", "parallel"),
        interpret=pallas_interpret(interpret),
    )(xp, lab, lse_p, dl)
    return dx[:n, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _xent_core(cfg, logits, labels):
    eps, interpret = cfg
    loss, _ = _fwd_call(logits, labels, eps, interpret)
    return loss


def _xent_fwd(cfg, logits, labels):
    eps, interpret = cfg
    loss, lse_p = _fwd_call(logits, labels, eps, interpret)
    return loss, (logits, labels, lse_p)


def _xent_bwd(cfg, res, dloss):
    eps, interpret = cfg
    logits, labels, lse_p = res
    dx = _bwd_call(logits, labels, lse_p, dloss, eps, interpret)
    return dx, None


_xent_core.defvjp(_xent_fwd, _xent_bwd)


def softmax_cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                               smoothing: float = 0.0,
                               interpret: Optional[bool] = None
                               ) -> jax.Array:
    """Per-row cross entropy without materializing log-softmax.

    logits: (N, V); labels: (N,) int, negative = ignore. Returns (N,)
    fp32 losses (ref: ``xentropy :: SoftmaxCrossEntropyLoss.apply``).
    """
    return _xent_core((float(smoothing), interpret), logits, labels)


class SoftmaxCrossEntropyLoss:
    """API-parity shim for the reference module (``half_to_float`` is
    implicit: losses are always fp32)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=None,
              half_to_float=True):
        if padding_idx is not None:
            labels = jnp.where(labels == padding_idx, -1, labels)
        return softmax_cross_entropy_loss(logits, labels, smoothing)

"""1-D halo exchange for spatially-sharded tensors.

Reference: ``apex/contrib/peer_memory/peer_halo_exchanger_1d.py`` (+
``peer_memory_cuda``) — spatial parallelism for convolutions: an image's
H dim is sharded across GPUs, and each conv needs ``halo`` rows from its
neighbors, moved over direct peer-to-peer CUDA mappings.

TPU version: neighbor exchange IS ``lax.ppermute`` over the mesh axis —
XLA lowers it to direct ICI sends between logical neighbors, the same
physical pattern peer_memory_cuda hand-builds over NVLink. Two permutes
(up, down) move both halos; autodiff transposes each rotation to its
reverse, so the backward "halo accumulation" of the reference falls out
for free. Non-periodic edges zero-fill (the reference's default conv
padding behavior at the outer boundary).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.utils.compat import axis_size


def halo_exchange_1d(x: jax.Array, halo: int, *, axis: int = 1,
                     axis_name: str = ps.CONTEXT_AXIS,
                     periodic: bool = False) -> jax.Array:
    """Concatenate neighbors' boundary slices onto this rank's shard.

    Args:
      x: the local shard; the sharded spatial dim is ``axis``.
      halo: rows to fetch from EACH neighbor.
      periodic: wrap around the ring instead of zero-filling the edges.

    Returns x extended to ``2*halo + x.shape[axis]`` along ``axis``:
    ``[prev-rank's last halo | x | next-rank's first halo]``.
    """
    n = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    if halo <= 0:
        raise ValueError(f"halo must be positive, got {halo}")
    if halo > x.shape[axis]:
        raise ValueError(
            f"halo {halo} exceeds local extent {x.shape[axis]}")

    down = [(i, (i + 1) % n) for i in range(n)]   # send toward rank+1
    up = [(i, (i - 1) % n) for i in range(n)]     # send toward rank-1

    bottom = lax.slice_in_dim(x, x.shape[axis] - halo, x.shape[axis],
                              axis=axis)
    top = lax.slice_in_dim(x, 0, halo, axis=axis)
    from_prev = lax.ppermute(bottom, axis_name, down)  # prev's bottom
    from_next = lax.ppermute(top, axis_name, up)       # next's top
    if not periodic:
        # first rank has no prev, last has no next: zero-fill
        from_prev = jnp.where(rank == 0, jnp.zeros_like(from_prev),
                              from_prev)
        from_next = jnp.where(rank == n - 1, jnp.zeros_like(from_next),
                              from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=axis)


class PeerHaloExchanger1d:
    """Module-shaped wrapper keeping the reference's constructor shape
    (``peer_ranks`` becomes the mesh axis; ``peer_pool`` has no TPU
    analogue — ICI buffers are XLA-managed)."""

    def __init__(self, axis_name: str = ps.CONTEXT_AXIS,
                 halo: int = 1, *, axis: int = 1,
                 periodic: bool = False):
        self.axis_name = axis_name
        self.halo = halo
        self.axis = axis
        self.periodic = periodic

    def __call__(self, x: jax.Array,
                 halo: Optional[int] = None) -> jax.Array:
        return halo_exchange_1d(
            x, halo if halo is not None else self.halo, axis=self.axis,
            axis_name=self.axis_name, periodic=self.periodic)

"""Peer-to-peer halo exchange (ref: ``apex/contrib/peer_memory``)."""

from apex_tpu.contrib.peer_memory.halo_exchange import (  # noqa: F401
    PeerHaloExchanger1d,
    halo_exchange_1d,
)

"""ZeRO-style distributed Adam — optimizer state sharded over data ranks.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py ::
DistributedFusedAdam`` (kernel ``distributed_adam_cuda``) — the ZeRO
optimizer: gradients are reduce-scattered across the data-parallel group,
each rank owns 1/dp of the fp32 master params and Adam moments, updates
only its shard, and the updated params are all-gathered back. Grad
communication collapses from allreduce+replicated-state to
reduce_scatter+all_gather with 1/dp per-rank state memory.

TPU redesign:

- The shard unit is a ROW of the multi-tensor engine's flat ``(R, 128)``
  buffer (``multi_tensor_apply.flatten``): params/moments flatten once
  into tile-aligned flat buffers, and rank d owns rows
  ``[d·R/dp, (d+1)·R/dp)``. No per-tensor bucketing logic — the CUDA
  implementation's block/bucket bookkeeping is replaced by one reshape.
- ``step`` runs INSIDE ``parallel_state.shard_map`` with the ``data``
  axis bound: ``lax.psum_scatter`` (grads, tiled) → local fused update →
  ``lax.all_gather`` (params, tiled). XLA schedules both collectives to
  overlap with the elementwise update where profitable.
- At rest the state is a GLOBAL ``(R, 128)`` array whose
  ``partition_spec()`` is ``P("data", None)``: under GSPMD/``device_put``
  each device PHYSICALLY stores only its R/dp rows — the ZeRO memory
  saving — while the code addresses it as one logical array.
- The fp32 master weights live in the state (``state.master``) and are
  authoritative; ``step`` returns the full-precision params all-gathered
  and cast back to the model dtype. This subsumes amp-O2 master weights
  for the ZeRO path (the reference likewise absorbs
  ``FP16_Optimizer``-style master storage).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.optimizers._common import check_m_dtype, f32, select_finite
from apex_tpu.transformer import parallel_state as ps


class DistributedAdamState(NamedTuple):
    step: jax.Array
    master: jax.Array   # (R, 128) fp32 — shard over rows at rest
    m: jax.Array        # (R, 128) fp32 or bf16 (``m_dtype``)
    v: jax.Array        # (R, 128) fp32


def _check_shardable(total_rows: int, dp: int) -> None:
    if total_rows % dp:
        raise ValueError(
            f"flat buffer rows {total_rows} not divisible by data-parallel "
            f"size {dp}; ALIGN_ROWS={_flatten.ALIGN_ROWS} guarantees this "
            "for power-of-two dp <= 256")


class DistributedFusedAdam:
    """Construct OUTSIDE shard_map; call ``step`` INSIDE shard_map with
    the ``data`` axis bound (state passed with ``partition_spec()``)."""

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, *,
                 average_grads: bool = True,
                 dp_size: Optional[int] = None,
                 axis_name: str = ps.DATA_AXIS,
                 m_dtype=jnp.float32):
        self.lr = lr
        # reduced-precision first moment: the bf16 shard halves m's share
        # of the at-rest state (see ``state_bytes_per_device``); the
        # update still accumulates in fp32 and stores round-to-nearest.
        self.m_dtype = check_m_dtype(m_dtype)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.average_grads = average_grads
        self.axis_name = axis_name
        self.dp = dp_size if dp_size is not None else \
            ps.get_data_parallel_world_size()
        self._specs = {}

    def _layout(self, params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((l.shape, jnp.dtype(l.dtype)) for l in leaves))
        spec = self._specs.get(key)
        if spec is None:
            spec = self._specs[key] = _flatten.make_spec(leaves)
            _check_shardable(spec.total_rows, self.dp)
        return leaves, treedef, spec

    def init(self, params: Any) -> DistributedAdamState:
        leaves, _, spec = self._layout(params)
        master, _ = _flatten.flatten_tensors(leaves, spec,
                                             dtype=jnp.float32)
        return DistributedAdamState(
            step=jnp.zeros((), jnp.int32), master=master,
            m=jnp.zeros(master.shape, self.m_dtype),
            v=jnp.zeros_like(master))

    def partition_spec(self, *, tensor_axis: Optional[str] = None
                       ) -> DistributedAdamState:
        """PartitionSpecs for the state pytree (shard_map in_specs /
        ``NamedSharding`` at rest): master/m/v row-sharded over data.

        Under dp x tp the flat buffers are built from TP-LOCAL param
        shards, so each tp rank holds different rows: pass
        ``tensor_axis`` to shard the row dim over ``(tensor_axis, data)``
        jointly — tuple order is major-to-minor, so rank ``(t, d)`` owns
        block ``t*dp + d``, matching the per-(t,d) ``psum_scatter`` over
        ``data`` inside :meth:`step`."""
        from jax.sharding import PartitionSpec as P

        if tensor_axis is None:
            row = P(self.axis_name, None)
        else:
            row = P((tensor_axis, self.axis_name), None)
        return DistributedAdamState(step=P(), master=row, m=row, v=row)

    def step(self, grads: Any, params: Any, state: DistributedAdamState,
             *, lr=None, grad_scale=1.0, weight_decay=None,
             found_inf: Optional[jax.Array] = None
             ) -> Tuple[Any, DistributedAdamState]:
        """One ZeRO step. ``grads`` are the rank-LOCAL (unreduced) grads —
        do NOT pre-average with DDP; the reduce-scatter averages here
        (``average_grads``). ``grad_scale`` MULTIPLIES (inverse loss
        scale), the package-wide convention. ``params`` supplies
        structure/dtypes only — ``state.master`` is authoritative.
        Returns (full params in model dtype, new state)."""
        leaves, treedef, spec = self._layout(params)
        ax = self.axis_name
        lr = f32(self.lr if lr is None else lr)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        gs = f32(grad_scale)
        if self.average_grads:
            gs = gs / self.dp

        gbuf, _ = _flatten.flatten_tensors(
            jax.tree_util.tree_leaves(grads), spec)
        # ZeRO collective #1: sum-reduce + scatter rows in rank order
        g_local = lax.psum_scatter(gbuf, ax, scatter_dimension=0,
                                   tiled=True)

        t = state.step + 1
        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        tf = t.astype(jnp.float32)
        if self.bias_correction:
            c1, c2 = 1.0 - b1 ** tf, 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)

        g = g_local.astype(jnp.float32) * gs
        p32 = state.master
        if not self.adam_w_mode:
            g = g + wd * p32
        m = b1 * state.m.astype(jnp.float32) + (1.0 - b1) * g
        v = b2 * state.v + (1.0 - b2) * g * g
        u = (m / c1) / (jnp.sqrt(v / c2) + eps)
        if self.adam_w_mode:
            u = u + wd * p32
        master = p32 - lr * u

        new_state = DistributedAdamState(
            step=t, master=master, m=m.astype(self.m_dtype), v=v)
        if found_inf is not None:
            # a rank-local overflow must skip the step EVERYWHERE — the
            # shards are disjoint, so OR across the data group first
            found_inf = lax.pmax(
                jnp.asarray(found_inf).astype(jnp.int32), ax) > 0
        new_state = select_finite(found_inf, new_state, state)

        # ZeRO collective #2: regather the updated master rows
        full = lax.all_gather(new_state.master, ax, axis=0, tiled=True)
        new_params = jax.tree_util.tree_unflatten(
            treedef, _flatten.unflatten_tensors(full, spec))
        return new_params, new_state

    def state_bytes_per_device(self, params: Any) -> int:
        """Per-device optimizer-state bytes at rest (the ~1/dp claim):
        master + v at 4 bytes each, m at ``m_dtype`` width."""
        _, _, spec = self._layout(params)
        per_elem = 4 + 4 + jnp.dtype(self.m_dtype).itemsize
        return per_elem * (spec.total_rows // self.dp) * _flatten.LANES

"""ZeRO-style distributed LAMB — sharded state + per-tensor trust ratios.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py ::
DistributedFusedLAMB`` (kernel ``distributed_lamb_cuda``) — LAMB with the
optimizer state sharded across the data-parallel group, used for the
large-batch BERT MLPerf runs.

Same flat-row sharding as ``DistributedFusedAdam``; what LAMB adds is
cross-shard reductions (per the two CUDA stages):

- the GLOBAL grad norm for clipping: local sum-of-squares → psum;
- per-TENSOR ``||p||``/``||u||`` for trust ratios, where a tensor's rows
  may span several ranks: the flat layout's per-row tensor-id table makes
  this a ``segment_sum`` over the local rows followed by one psum of the
  (num_tensors,) vectors — the TPU analogue of the reference's
  ``reduce_scatter``-then-allreduce norm plumbing. Tile alignment
  guarantees pad lanes are zero, so segment sums need no masking.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    _check_shardable,
)
from apex_tpu.multi_tensor_apply import flatten as _flatten
from apex_tpu.optimizers._common import check_m_dtype, f32, select_finite
from apex_tpu.transformer import parallel_state as ps


class DistributedLambState(NamedTuple):
    step: jax.Array
    master: jax.Array
    m: jax.Array       # fp32 or bf16 (``m_dtype``)
    v: jax.Array


class DistributedFusedLAMB:
    """Construct OUTSIDE shard_map; ``step`` INSIDE (data axis bound)."""

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False, *,
                 average_grads: bool = True,
                 dp_size: Optional[int] = None,
                 axis_name: str = ps.DATA_AXIS,
                 m_dtype=jnp.float32):
        self.lr = lr
        self.m_dtype = check_m_dtype(m_dtype)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.average_grads = average_grads
        self.axis_name = axis_name
        self.dp = dp_size if dp_size is not None else \
            ps.get_data_parallel_world_size()
        self._specs = {}

    def _layout(self, params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((l.shape, jnp.dtype(l.dtype)) for l in leaves))
        cached = self._specs.get(key)
        if cached is None:
            spec = _flatten.make_spec(leaves)
            _check_shardable(spec.total_rows, self.dp)
            # per-ROW tensor ids (tail padding -> last tensor; its pad
            # lanes are zero so segment sums are unaffected)
            row_ids = jnp.asarray(
                spec.tile_tensor_ids(tile_rows=1), jnp.int32)
            cached = self._specs[key] = (spec, row_ids)
        return leaves, treedef, cached[0], cached[1]

    def init(self, params: Any) -> DistributedLambState:
        leaves, _, spec, _ = self._layout(params)
        master, _ = _flatten.flatten_tensors(leaves, spec,
                                             dtype=jnp.float32)
        return DistributedLambState(
            step=jnp.zeros((), jnp.int32), master=master,
            m=jnp.zeros(master.shape, self.m_dtype),
            v=jnp.zeros_like(master))

    def partition_spec(self) -> DistributedLambState:
        from jax.sharding import PartitionSpec as P

        row = P(self.axis_name, None)
        return DistributedLambState(step=P(), master=row, m=row, v=row)

    def _local_row_ids(self, row_ids, local_rows):
        d = lax.axis_index(self.axis_name)
        return lax.dynamic_slice_in_dim(row_ids, d * local_rows,
                                        local_rows, 0)

    def step(self, grads: Any, params: Any, state: DistributedLambState,
             *, lr=None, weight_decay=None, grad_scale=1.0,
             found_inf: Optional[jax.Array] = None
             ) -> Tuple[Any, DistributedLambState]:
        """ZeRO LAMB step (rank-local unreduced ``grads``; ``grad_scale``
        MULTIPLIES — package convention, the reference's scale divides)."""
        leaves, treedef, spec, row_ids = self._layout(params)
        ax = self.axis_name
        T = spec.num_tensors
        lr = f32(self.lr if lr is None else lr)
        wd = f32(self.weight_decay if weight_decay is None else weight_decay)
        gs = f32(grad_scale)
        if self.average_grads:
            gs = gs / self.dp
        b1, b2, eps = f32(self.beta1), f32(self.beta2), f32(self.eps)
        t = state.step + 1
        tf = t.astype(jnp.float32)
        beta3 = 1.0 - b1 if self.grad_averaging else jnp.float32(1.0)
        if self.bias_correction:
            c1, c2 = 1.0 - b1 ** tf, 1.0 - b2 ** tf
        else:
            c1 = c2 = jnp.float32(1.0)

        gbuf, _ = _flatten.flatten_tensors(
            jax.tree_util.tree_leaves(grads), spec)
        g = lax.psum_scatter(gbuf, ax, scatter_dimension=0,
                             tiled=True).astype(jnp.float32) * gs

        # stage-1 preamble: GLOBAL grad-norm clip (psum of local ssq —
        # shards are disjoint so this is the exact global norm)
        grad_norm = jnp.sqrt(lax.psum(jnp.sum(g * g), ax))
        max_norm = f32(self.max_grad_norm)
        clip = jnp.where((max_norm > 0) & (grad_norm > max_norm),
                         grad_norm / max_norm, jnp.float32(1.0))
        g = g / clip

        p32 = state.master
        if not self.adam_w_mode:
            g = g + wd * p32
        m = b1 * state.m.astype(jnp.float32) + beta3 * g
        v = b2 * state.v + (1.0 - b2) * g * g
        u = (m / c1) / (jnp.sqrt(v / c2) + eps)
        if self.adam_w_mode:
            u = u + wd * p32

        # stage 2: per-tensor trust ratios across shard boundaries
        local_ids = self._local_row_ids(row_ids, g.shape[0])
        w_ssq = lax.psum(jax.ops.segment_sum(
            jnp.sum(p32 * p32, axis=1), local_ids, num_segments=T), ax)
        u_ssq = lax.psum(jax.ops.segment_sum(
            jnp.sum(u * u, axis=1), local_ids, num_segments=T), ax)
        w_norm, u_norm = jnp.sqrt(w_ssq), jnp.sqrt(u_ssq)
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm,
                          jnp.float32(1.0))
        if not self.use_nvlamb:
            ratio = jnp.where(wd == 0.0, jnp.ones_like(ratio), ratio)
        master = p32 - lr * ratio[local_ids][:, None] * u

        new_state = DistributedLambState(
            step=t, master=master, m=m.astype(self.m_dtype), v=v)
        if found_inf is not None:
            found_inf = lax.pmax(
                jnp.asarray(found_inf).astype(jnp.int32), ax) > 0
        new_state = select_finite(found_inf, new_state, state)

        full = lax.all_gather(new_state.master, ax, axis=0, tiled=True)
        new_params = jax.tree_util.tree_unflatten(
            treedef, _flatten.unflatten_tensors(full, spec))
        return new_params, new_state

    def state_bytes_per_device(self, params: Any) -> int:
        _, _, spec, _ = self._layout(params)
        per_elem = 4 + 4 + jnp.dtype(self.m_dtype).itemsize
        return per_elem * (spec.total_rows // self.dp) * _flatten.LANES

"""Distributed (ZeRO-style) optimizers (ref: ``apex/contrib/optimizers``)."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedAdamState,
    DistributedFusedAdam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (  # noqa: F401
    DistributedFusedLAMB,
    DistributedLambState,
)

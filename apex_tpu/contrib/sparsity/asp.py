"""ASP — automatic 2:4 structured sparsity.

Reference: ``apex/contrib/sparsity/asp.py :: class ASP`` +
``sparse_masklib.py`` (``m4n2_1d``: in every group of 4 consecutive
weights along the input dim, keep the 2 largest magnitudes) — the
Ampere sparse-tensor-core workflow: compute masks once on a trained
model, hook the optimizer so masks re-apply after every step, fine-tune.

TPU honesty note: TPUs have no 2:4 sparse MXU mode, so masking buys no
FLOPs here — what this module preserves is the WORKFLOW (prune on TPU,
deploy wherever, or study sparsified training). The mask math is
identical; the optimizer hook becomes a functional wrapper
(``ASP.wrap_optimizer``) because there is no mutable optimizer to hook.
"""

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def m4n2_1d_mask(w: jax.Array, axis: int = 0) -> jax.Array:
    """Boolean keep-mask: top-2-of-4 |w| along ``axis`` (ref:
    ``mn_1d_best`` with m=4, n=2, applied to torch Linear's LAST dim —
    which is the INPUT dim of torch's (out, in) layout). This package's
    dense kernels are (in, out), so the contraction dim is axis 0 and
    that is the default: the 2:4 pattern must run along the dim the GEMM
    contracts or sparse tensor cores reject the export."""
    w = jnp.moveaxis(w, axis, -1)
    if w.shape[-1] % 4:
        raise ValueError(
            f"pruning dim {w.shape[-1]} not divisible by 4 (m4n2 pattern)")
    groups = jnp.abs(w).reshape(*w.shape[:-1], w.shape[-1] // 4, 4)
    # rank within each group; keep the two largest magnitudes
    order = jnp.argsort(jnp.argsort(groups, axis=-1), axis=-1)
    keep = order >= 2
    return jnp.moveaxis(keep.reshape(w.shape), -1, axis)


def _default_predicate(path: tuple, leaf: jax.Array) -> bool:
    """Prunable = float matrices with a 4-divisible contraction (first)
    dim and both dims >= 16, EXCLUDING embedding-like leaves (the
    reference whitelist only sparsifies Linear-like modules — a (vocab,
    h) word table is a gather table, not a GEMM operand, and 2:4-pruning
    it destroys token representations for zero sparse-MXU gain). The
    path-name heuristic matches 'embed'/'embedding'/'lookup' anywhere in
    the key path; models with unconventional naming should pass a custom
    predicate."""
    if not (leaf.ndim == 2 and leaf.shape[0] % 4 == 0
            and min(leaf.shape) >= 16
            and jnp.issubdtype(leaf.dtype, jnp.floating)):
        return False
    path_str = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path).lower()
    return not any(tag in path_str
                   for tag in ("embed", "embedding", "lookup"))


def compute_sparse_masks(params: Any,
                         predicate: Optional[Callable] = None) -> Any:
    """Mask pytree: m4n2 masks for prunable leaves; non-prunable leaves
    hold the scalar ``True`` sentinel — no dense all-True arrays (a byte
    per element across a mostly-non-prunable model is real HBM) and
    ``apply_masks`` skips them entirely (ref:
    ``ASP.compute_sparse_masks`` walking the module whitelist)."""
    pred = predicate or _default_predicate

    def mask_of(path, leaf):
        if pred(path, leaf):
            return m4n2_1d_mask(leaf)
        return True

    return jax.tree_util.tree_map_with_path(mask_of, params)


def apply_masks(params: Any, masks: Any) -> Any:
    return jax.tree.map(
        lambda p, m: p if m is True
        else jnp.where(m, p, jnp.zeros_like(p)),
        params, masks)


class ASP:
    """Functional ASP workflow::

        asp = ASP()
        masks = asp.compute_sparse_masks(params)   # after pretraining
        params = apply_masks(params, masks)
        step = asp.wrap_optimizer(opt, masks)      # masked fine-tuning
        params, opt_state = step(grads, params, opt_state)

    (ref: ``init_model_for_pruning`` + ``init_optimizer_for_pruning`` +
    ``compute_sparse_masks`` — the torch version monkey-patches
    ``optimizer.step``; the wrapper is its functional twin.)"""

    def __init__(self, predicate: Optional[Callable] = None):
        self.predicate = predicate

    def compute_sparse_masks(self, params: Any) -> Any:
        return compute_sparse_masks(params, self.predicate)

    def wrap_optimizer(self, optimizer, masks: Any):
        """Returns a ``step(grads, params, state, **kw)`` that re-applies
        the masks to the updated params (and masks the grads first, so
        momentum never accumulates toward pruned slots)."""

        def step(grads, params, state, **kw
                 ) -> Tuple[Any, Any]:
            grads = apply_masks(grads, masks)
            new_params, new_state = optimizer.step(grads, params, state,
                                                   **kw)
            return apply_masks(new_params, masks), new_state

        return step

"""Automatic structured sparsity (ref: ``apex/contrib/sparsity``)."""

from apex_tpu.contrib.sparsity.asp import (  # noqa: F401
    ASP,
    apply_masks,
    compute_sparse_masks,
    m4n2_1d_mask,
)

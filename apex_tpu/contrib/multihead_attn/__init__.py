"""Module-level fused multi-head attention (ref: ``apex/contrib/multihead_attn``)."""

from apex_tpu.contrib.multihead_attn.multihead_attn import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

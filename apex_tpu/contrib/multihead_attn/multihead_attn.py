"""Fused MHA modules over the flash-attention kernel.

Reference: ``apex/contrib/multihead_attn/self_multihead_attn.py`` and
``encdec_multihead_attn.py`` (impl='fast'; CUDA in
``csrc/multihead_attn/*``) — module-level attention with packed
projection weights, optional fused residual+LayerNorm input
(``include_norm_add=True``, the ``*_norm_add`` kernel variants), and
attention-probability dropout replayed from saved RNG state in backward.

TPU mapping: the giant fused CUDA forward (QKV GEMM → softmax → dropout →
PV GEMM → out GEMM) is the flash-attention Pallas kernel plus XLA-fused
projections; dropout replay is the kernel's counter-hash (no mask
storage). The norm_add variant's "fused" LN+residual is ordinary code —
XLA fuses the add into adjacent ops, so a dedicated kernel would buy
nothing (the "let XLA fuse" rule).

Conventions kept from the reference:
- tensors are sequence-first ``(seq, batch, embed)`` (Megatron layout);
- qkv/kv projection weights are packed; like the in-tree GPT the packing
  is HEAD-MAJOR (``[head0: q k v | head1: …]``) so a future column shard
  holds whole heads;
- ``bias=False`` default (the fast impl's default);
- ``key_padding_mask`` is (batch, src_len) with 1 = ATTEND (the package's
  BERT convention; the reference's byte mask marks pads — invert when
  porting);
- returns only the attention output (fast impl returns
  ``(output, None)`` for weights; per-head weight export is unsupported
  here because flash never materializes them).
"""

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.transformer.functional import flash_attention


def _init_kernel(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)


def _split_heads(x: jax.Array, nh: int) -> jax.Array:
    """(s, b, nh*hd) -> (b, nh, s, hd)."""
    s, b, w = x.shape
    return x.reshape(s, b, nh, w // nh).transpose(1, 2, 0, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    """(b, nh, s, hd) -> (s, b, nh*hd)."""
    b, nh, s, hd = x.shape
    return x.transpose(2, 0, 1, 3).reshape(s, b, nh * hd)


def _output_dropout(x, rate, rng):
    if rng is None or rate <= 0:
        return x
    keep = jax.random.bernoulli(rng, 1 - rate, x.shape)
    return x * keep / (1 - rate)


class _MhaBase:
    def __init__(self, embed_dim: int, num_heads: int, *,
                 dropout: float = 0.0, bias: bool = False,
                 include_norm_add: bool = False,
                 params_dtype=jnp.float32):
        if embed_dim % num_heads:
            raise ValueError(
                f"embed_dim {embed_dim} not divisible by num_heads "
                f"{num_heads}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.use_bias = bias
        self.include_norm_add = include_norm_add
        self.params_dtype = params_dtype
        self.scaling = self.head_dim ** -0.5

    def _norm_params(self):
        if not self.include_norm_add:
            return {}
        return {"layernorm": {
            "weight": jnp.ones((self.embed_dim,), jnp.float32),
            "bias": jnp.zeros((self.embed_dim,), jnp.float32)}}

    def _maybe_norm(self, params, x):
        if not self.include_norm_add:
            return x
        p = params["layernorm"]
        return fused_layer_norm_affine(
            x, p["weight"], p["bias"], self.embed_dim, 1e-5).astype(x.dtype)

    def _proj(self, p, x):
        y = jnp.dot(x, p["kernel"].astype(x.dtype))
        if "bias" in p:
            y = y + p["bias"].astype(y.dtype)
        return y

    def _attend(self, q, k, v, key_padding_mask, attn_mask_causal,
                dropout_rng, is_training):
        rate = self.dropout if (is_training and dropout_rng is not None) \
            else 0.0
        rng = dropout_rng if rate > 0 else None
        return flash_attention(
            q, k, v, key_padding_mask, causal=attn_mask_causal,
            softmax_scale=self.scaling,
            dropout_rate=rate, dropout_rng=rng)


class SelfMultiheadAttn(_MhaBase):
    """Self-attention with one packed qkv projection (ref:
    ``SelfMultiheadAttn(impl='fast')`` / ``*_norm_add`` when
    ``include_norm_add=True``)."""

    def init(self, key: jax.Array) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        h = self.embed_dim
        p = {
            "qkv": {"kernel": _init_kernel(k1, (h, 3 * h), h,
                                           self.params_dtype)},
            "out": {"kernel": _init_kernel(k2, (h, h), h,
                                           self.params_dtype)},
        }
        if self.use_bias:
            p["qkv"]["bias"] = jnp.zeros((3 * h,), self.params_dtype)
            p["out"]["bias"] = jnp.zeros((h,), self.params_dtype)
        p.update(self._norm_params())
        return p

    def apply(self, params: Dict[str, Any], query: jax.Array, *,
              key_padding_mask: Optional[jax.Array] = None,
              attn_mask_causal: bool = False,
              is_training: bool = True,
              dropout_rng: Optional[jax.Array] = None) -> jax.Array:
        """query: (tgt_len, batch, embed) -> same shape."""
        x = self._maybe_norm(params, query)
        qkv = self._proj(params["qkv"], x)        # (s, b, 3h) head-major
        s, b, _ = qkv.shape
        nh, hd = self.num_heads, self.head_dim
        qkv = qkv.reshape(s, b, nh, 3, hd)
        q, k, v = (qkv[:, :, :, j].transpose(1, 2, 0, 3) for j in range(3))
        rngs = (jax.random.split(dropout_rng)
                if dropout_rng is not None else (None, None))
        ctx = self._attend(q, k, v, key_padding_mask, attn_mask_causal,
                           rngs[0], is_training)
        out = self._proj(params["out"], _merge_heads(ctx))
        if self.include_norm_add:
            # reference norm_add epilogue: dropout(output) + residual
            if is_training:
                out = _output_dropout(out, self.dropout, rngs[1])
            out = out + query
        return out

    __call__ = apply


class EncdecMultiheadAttn(_MhaBase):
    """Cross-attention: q from the decoder query, packed kv from the
    encoder output (ref: ``EncdecMultiheadAttn(impl='fast')``)."""

    def init(self, key: jax.Array) -> Dict[str, Any]:
        k1, k2, k3 = jax.random.split(key, 3)
        h = self.embed_dim
        p = {
            "q": {"kernel": _init_kernel(k1, (h, h), h, self.params_dtype)},
            "kv": {"kernel": _init_kernel(k2, (h, 2 * h), h,
                                          self.params_dtype)},
            "out": {"kernel": _init_kernel(k3, (h, h), h,
                                           self.params_dtype)},
        }
        if self.use_bias:
            p["q"]["bias"] = jnp.zeros((h,), self.params_dtype)
            p["kv"]["bias"] = jnp.zeros((2 * h,), self.params_dtype)
            p["out"]["bias"] = jnp.zeros((h,), self.params_dtype)
        p.update(self._norm_params())
        return p

    def apply(self, params: Dict[str, Any], query: jax.Array,
              key: jax.Array, *,
              key_padding_mask: Optional[jax.Array] = None,
              attn_mask_causal: bool = False,
              is_training: bool = True,
              dropout_rng: Optional[jax.Array] = None) -> jax.Array:
        """query: (tgt_len, b, h); key: (src_len, b, h) (the reference
        passes the encoder output as both key and value)."""
        x = self._maybe_norm(params, query)
        nh, hd = self.num_heads, self.head_dim
        q = _split_heads(self._proj(params["q"], x), nh)
        kv = self._proj(params["kv"], key)        # (s_k, b, 2h) head-major
        sk, b, _ = kv.shape
        kv = kv.reshape(sk, b, nh, 2, hd)
        k_, v_ = (kv[:, :, :, j].transpose(1, 2, 0, 3) for j in range(2))
        rngs = (jax.random.split(dropout_rng)
                if dropout_rng is not None else (None, None))
        ctx = self._attend(q, k_, v_, key_padding_mask, attn_mask_causal,
                           rngs[0], is_training)
        out = self._proj(params["out"], _merge_heads(ctx))
        if self.include_norm_add:
            if is_training:
                out = _output_dropout(out, self.dropout, rngs[1])
            out = out + query
        return out

    __call__ = apply

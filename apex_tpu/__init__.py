"""apex_tpu — a TPU-native training-accelerant framework.

A from-scratch reimplementation of the capabilities of NVIDIA Apex
(reference fork: UdonDa/apex) designed for TPU: JAX/XLA for the compute
path, Pallas for fused kernels, and a ``jax.sharding.Mesh`` with XLA
collectives over ICI/DCN in place of NCCL process groups.

Subpackage map (reference anchors in each module's docstring):

- ``apex_tpu.amp``                — mixed precision: O0–O3 opt-levels, dynamic
  loss scaling, master weights (ref: ``apex/amp``).
- ``apex_tpu.normalization``      — FusedLayerNorm / FusedRMSNorm Pallas kernels
  (ref: ``apex/normalization`` + ``csrc/layer_norm_cuda*``).
- ``apex_tpu.optimizers``         — FusedAdam / FusedLAMB / FusedSGD /
  FusedNovoGrad (ref: ``apex/optimizers`` + ``csrc/multi_tensor_*.cu``).
- ``apex_tpu.multi_tensor_apply`` — chunked flat-buffer multi-tensor engine
  (ref: ``apex/multi_tensor_apply``, ``csrc/multi_tensor_apply.cuh``).
- ``apex_tpu.parallel``           — DistributedDataParallel semantics,
  SyncBatchNorm, LARC (ref: ``apex/parallel``).
- ``apex_tpu.transformer``        — Megatron-style tensor/sequence/pipeline
  parallelism over a device mesh (ref: ``apex/transformer``).
- ``apex_tpu.contrib``            — opt-in accelerants: fused softmax
  cross-entropy, fused multi-head attention, fast layer norm, distributed
  (ZeRO) optimizers (ref: ``apex/contrib``).
- ``apex_tpu.fp16_utils``         — legacy FP16_Optimizer-shaped API
  (ref: ``apex/fp16_utils``).
- ``apex_tpu.mlp`` / ``apex_tpu.fused_dense`` — fused MLP / dense blocks
  (ref: ``apex/mlp``, ``apex/fused_dense``).
"""

from apex_tpu import utils  # noqa: F401

__version__ = "0.1.0"

# Mirror the reference's top-level convenience import (`apex/__init__.py`
# imports `apex.parallel`). Kept lazy-ish: these are lightweight modules.
from apex_tpu import parallel  # noqa: F401,E402
from apex_tpu import amp  # noqa: F401,E402

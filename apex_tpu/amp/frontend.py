"""AMP frontend: ``initialize`` and the training-step helpers.

Reference: ``apex/amp/frontend.py :: def initialize`` builds a
``Properties`` from the O0..O3 presets plus user overrides, then
``_initialize`` rewires model+optimizer in place. Functional translation:

    amp_h = amp.initialize(opt_level="O2", loss_scale="dynamic")
    master  = amp_h.master_params(params)        # fp32 source of truth
    state   = amp_h.init_state()                 # scaler state (pytree)

    def train_step(master, opt_state, state, batch):
        params = amp_h.cast_model(master)        # O2: bf16 except norms
        (loss, aux), grads, found_inf, state = amp_h.value_and_grad(
            loss_fn, has_aux=True)(params, state, amp_h.cast_input(batch))
        updates, new_opt = optimizer.update(grads, opt_state, master)
        new_master = optax.apply_updates(master, updates)
        master   = amp.apply_if_finite(new_master, master, found_inf)
        opt_state = amp.apply_if_finite(new_opt, opt_state, found_inf)
        return master, opt_state, state, loss

The ``with amp.scale_loss(loss, optimizer) as scaled_loss`` context manager
of the reference has no backward() to wrap in JAX; its three jobs (scale,
unscale-after-backward, update-scale) are the explicit ``scale_loss`` /
``unscale`` / ``update_scale`` methods, or the fused ``value_and_grad``.
"""

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp import policy as _policy
from apex_tpu.amp.autocast import autocast
from apex_tpu.amp.properties import Properties, opt_levels
from apex_tpu.amp.scaler import (
    LossScaler,
    LossScalerState,
    apply_if_finite,  # noqa: F401  (re-exported)
)


class Amp:
    """Bundle of an opt-level's Properties + a LossScaler + cast helpers.

    ``num_losses`` mirrors the reference's ``amp.initialize(...,
    num_losses=N)``: ``init_state`` then returns a TUPLE of independent
    scaler states, and the reference's ``loss_id`` argument becomes
    plain indexing (``h.scale_loss(loss, state[i])``)."""

    def __init__(self, properties: Properties, num_losses: int = 1):
        self.properties = properties
        self.num_losses = int(num_losses)
        self.scaler = LossScaler(loss_scale=properties.loss_scale)

    # -- model / input casting -----------------------------------------
    def cast_model(self, params: Any, precast: Any = None) -> Any:
        """O2/O3 model cast. ``precast`` is an optimizer-emitted compute
        tree (``FusedAdam(emit_compute_params=True)`` etc.): matching-
        dtype leaves are consumed verbatim so the per-step fp32→bf16
        re-cast over the master tree disappears; only leaves the policy
        keeps fp32 (norms under ``keep_batchnorm_fp32``) still come from
        ``params``."""
        p = self.properties
        if p.cast_model_type is None:
            return params
        return _policy.cast_params(
            params,
            p.cast_model_type,
            keep_batchnorm_fp32=bool(p.keep_batchnorm_fp32),
            precast=precast,
        )

    def cast_input(self, batch: Any) -> Any:
        p = self.properties
        if p.cast_model_type is None:
            return batch
        # O0 included: the reference casts floating inputs to fp32 there too.
        return _policy.cast_inputs(batch, p.cast_model_type)

    def master_params(self, params: Any) -> Any:
        if not self.properties.master_weights:
            return params
        return _policy.master_params(params)

    def autocast(self):
        """O1 context: op-policy casting for apex_tpu ops in scope."""
        p = self.properties
        dtype = p.cast_model_type or jnp.bfloat16
        return autocast(dtype=dtype, enabled=bool(p.patch_torch_functions))

    # -- scaler ---------------------------------------------------------
    def init_state(self):
        if self.num_losses == 1:
            return self.scaler.init_state()
        return tuple(self.scaler.init_state()
                     for _ in range(self.num_losses))

    def scale_loss(self, loss, state: LossScalerState):
        return self.scaler.scale(loss, state)

    def unscale(self, grads, state: LossScalerState):
        return self.scaler.unscale(grads, state)

    def update_scale(self, state: LossScalerState, found_inf):
        return self.scaler.update_scale(state, found_inf)

    def value_and_grad(
        self, loss_fn: Callable, has_aux: bool = False, **grad_kwargs
    ) -> Callable:
        """Scaled value_and_grad: computes grads of the *scaled* loss,
        unscales them, and advances the scaler state.

        Returned callable: ``(params, state, *args, **kw) ->
        (value, grads, found_inf, new_state)`` where ``value`` is the
        unscaled ``loss`` (or ``(loss, aux)`` with has_aux)."""

        def wrapped(params, state: LossScalerState, *args, **kw):
            def scaled_loss_fn(p, *a, **k):
                out = loss_fn(p, *a, **k)
                if has_aux:
                    loss, aux = out
                else:
                    loss, aux = out, None
                return self.scaler.scale(loss, state), (loss, aux)

            (_, (loss, aux)), grads = jax.value_and_grad(
                scaled_loss_fn, has_aux=True, **grad_kwargs
            )(params, *args, **kw)
            grads, found_inf = self.scaler.unscale(grads, state)
            new_state = self.scaler.update_scale(state, found_inf)
            value = (loss, aux) if has_aux else loss
            return value, grads, found_inf, new_state

        return wrapped

    # -- checkpointing (ref: ``amp.state_dict``) ------------------------
    def state_dict(self, state) -> dict:
        """N-scaler form of the reference's ``amp.state_dict``: one
        ``loss_scalerI`` entry per state (a single state is scaler 0)."""
        states = state if isinstance(state, (list, tuple)) else (state,)
        return {f"loss_scaler{i}": self.scaler.state_dict(s)
                for i, s in enumerate(states)}

    def load_state_dict(self, d: dict):
        """Inverse of :meth:`state_dict`. A loss_scaler COUNT mismatch
        warns and loads the overlap (reference behavior: apex's
        ``load_state_dict`` iterates ``zip(self._loss_scalers, ...)`` —
        silently truncating; we keep the load-what-matches semantics but
        say so out loud): extra checkpoint entries are dropped, missing
        ones fall back to a fresh ``init_state()``. Raising here would
        brick every resume-with-changed-loss-count run for a state that
        is, at worst, a scale-warmup hiccup."""
        keys = sorted((k for k in d if k.startswith("loss_scaler")
                       and k[len("loss_scaler"):].isdigit()),
                      key=lambda k: int(k[len("loss_scaler"):]))
        if len(keys) != self.num_losses:
            import warnings
            warnings.warn(
                f"amp state_dict has {len(keys)} loss_scaler entries but "
                f"this handle was initialized with num_losses="
                f"{self.num_losses}; loading the overlap — surplus "
                "checkpoint entries are ignored, missing scalers start "
                "from a fresh init_state()", stacklevel=2)
        states = tuple(
            self.scaler.load_state_dict(d[keys[i]]) if i < len(keys)
            else self.scaler.init_state()
            for i in range(self.num_losses))
        return states[0] if self.num_losses == 1 else states


def initialize(
    opt_level: str = "O1",
    *,
    cast_model_type=None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    loss_scale=None,
    enabled: bool = True,
    verbosity: int = 1,
    num_losses: int = 1,
) -> Amp:
    """Build an :class:`Amp` handle from an opt-level + overrides.

    Mirrors ``apex.amp.initialize``'s knobs; model/optimizer are not
    arguments because nothing is mutated — apply ``amp_h.cast_model`` /
    ``amp_h.master_params`` to your param tree instead.
    """
    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r} "
            "(options are 'O0', 'O1', 'O2', 'O3')."
        )
    props = opt_levels[opt_level](Properties())
    if enabled:
        overrides = {
            "cast_model_type": cast_model_type,
            "keep_batchnorm_fp32": keep_batchnorm_fp32,
            "master_weights": master_weights,
            "loss_scale": loss_scale,
        }
        props._update_options_dict(
            {k: v for k, v in overrides.items() if v is not None}
        )
    else:
        # Hard off-switch (reference parity): all other knobs are ignored.
        props.enabled = False
        props.patch_torch_functions = False
        props.cast_model_type = None
        props.master_weights = False
        props.loss_scale = 1.0
    if verbosity > 0:
        import logging

        logging.getLogger("apex_tpu").info(
            "amp.initialize: opt_level=%s properties=%s", opt_level, props
        )
    return Amp(props, num_losses=num_losses)

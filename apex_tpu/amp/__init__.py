"""Mixed-precision management (AMP) for TPU.

Reference: ``apex/amp`` — opt-levels O0..O3 (``frontend.py``), dynamic loss
scaling (``scaler.py``), op casting lists (``lists/``), master weights
(``_initialize.py`` / ``_process_optimizer.py``).
"""

from apex_tpu.amp.autocast import (  # noqa: F401
    autocast,
    autocast_dtype,
    cast_args,
    is_autocast_enabled,
)
from apex_tpu.amp.frontend import Amp, initialize  # noqa: F401
from apex_tpu.amp.policy import (  # noqa: F401
    cast_inputs,
    cast_params,
    master_params,
    model_params_from_master,
)
from apex_tpu.amp.properties import Properties, opt_levels  # noqa: F401
from apex_tpu.amp.scaler import (  # noqa: F401
    LossScaler,
    LossScalerState,
    apply_if_finite,
)
from apex_tpu.amp import lists  # noqa: F401

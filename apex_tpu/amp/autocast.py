"""Autocast engine — the TPU-native stand-in for apex's torch monkey-patching.

Reference: ``apex/amp/amp.py :: init`` + ``apex/amp/wrap.py :: cached_cast``
install casting shims over torch functions for O1. JAX traces pure
functions, so global patching is both impossible and unnecessary: instead,
apex_tpu's own ops and modules consult a (thread-local, trace-time constant)
autocast context before running. ``cast_args`` implements the per-op policy
from :mod:`apex_tpu.amp.lists`.

Because the context is read at *trace* time, entering/exiting ``autocast``
around a jitted call behaves like the reference's enable/disable —
just recompile-keyed rather than patched.
"""

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from apex_tpu.amp import lists

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


@contextlib.contextmanager
def autocast(dtype=jnp.bfloat16, enabled: bool = True):
    """Enable O1-style op-policy casting within the context."""
    _stack().append(dtype if enabled else None)
    try:
        yield
    finally:
        _stack().pop()


def autocast_dtype() -> Optional[jnp.dtype]:
    """The active autocast compute dtype, or None when disabled."""
    s = _stack()
    return s[-1] if s else None


def is_autocast_enabled() -> bool:
    return autocast_dtype() is not None


def _widest(dtypes):
    """Promotion target for mixed float inputs. Delegates to JAX's lattice:
    f16 + bf16 promotes to f32 (neither format is a superset of the other),
    matching ``jnp.promote_types`` rather than an ad-hoc ranking."""
    # Only dtypes with an implicit promotion path participate; fp8 and other
    # exotic floats are left out (JAX refuses implicit 8-bit-float
    # promotion), matching the reference's fixed op lists.
    promotable = {jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16),
                  jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)}
    floats = [jnp.dtype(d) for d in dtypes if jnp.dtype(d) in promotable]
    if not floats:
        return None
    out = floats[0]
    for d in floats[1:]:
        out = jnp.promote_types(out, d)
    return out


def cast_args(op_name: str, *args):
    """Apply the op policy to floating-point array args; returns a tuple.

    Reference: ``apex/amp/utils.py :: casted_args``.
    """
    dtype = autocast_dtype()
    if dtype is None:
        return args
    policy = lists.policy_for(op_name)
    if policy == "passthrough":
        return args

    def is_float(a):
        return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)

    if policy == "fp16":
        target = dtype
    elif policy == "fp32":
        target = jnp.float32
    else:  # promote
        target = _widest([a.dtype for a in args if is_float(a)])
        if target is None:
            return args
    return tuple(
        a.astype(target) if is_float(a) and a.dtype != target else a
        for a in args
    )

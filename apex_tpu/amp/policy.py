"""Param-pytree casting and master-weight handling.

Reference: ``apex/amp/_initialize.py`` (O2 model cast, keep-BN-fp32) and the
master-param machinery in ``apex/amp/_process_optimizer.py`` /
``apex/fp16_utils/fp16_optimizer.py``. In a functional framework the model
is a param pytree, so "cast the model" is a tree_map and "master weights"
is keeping the original fp32 tree as the optimizer's source of truth.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# flax param-path fragments treated as normalization params when
# keep_batchnorm_fp32 is set. Customizable via the predicate argument.
_NORM_PATH_MARKERS = (
    "batchnorm", "batch_norm", "bn", "layernorm", "layer_norm", "norm",
    "groupnorm", "group_norm", "rmsnorm", "rms_norm",
)


def default_norm_predicate(path: tuple) -> bool:
    joined = "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    ).lower()
    return any(m in joined for m in _NORM_PATH_MARKERS)


def _is_float_leaf(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_float_leaf(x, dtype):
    return x.astype(dtype) if _is_float_leaf(x) else x


def cast_params(
    params: Any,
    dtype,
    keep_batchnorm_fp32: bool = False,
    norm_predicate: Optional[Callable[[tuple], bool]] = None,
) -> Any:
    """Cast floating leaves of a param tree to ``dtype`` (O2/O3 model cast).

    With ``keep_batchnorm_fp32``, leaves whose path looks like a
    normalization parameter stay fp32 (ref: ``_initialize`` skipping
    ``_BatchNorm`` modules).
    """
    pred = norm_predicate or default_norm_predicate

    def cast(path, x):
        if not _is_float_leaf(x):
            return x
        if keep_batchnorm_fp32 and pred(path):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def cast_inputs(batch: Any, dtype) -> Any:
    """Cast floating inputs to the compute dtype (O2 input cast)."""
    return jax.tree_util.tree_map(
        lambda x: _cast_float_leaf(x, dtype), batch
    )


def master_params(params: Any) -> Any:
    """fp32 master copy of a (possibly reduced-precision) param tree.

    Reference: ``apex.amp.master_params(optimizer)``.
    """
    return cast_inputs(params, jnp.float32)


def model_params_from_master(
    master: Any,
    like: Any,
) -> Any:
    """Re-cast master weights to the dtypes of the compute tree ``like``."""
    return jax.tree_util.tree_map(
        lambda m, l: m.astype(l.dtype) if hasattr(l, "dtype") else m,
        master,
        like,
    )

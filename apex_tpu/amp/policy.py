"""Param-pytree casting and master-weight handling.

Reference: ``apex/amp/_initialize.py`` (O2 model cast, keep-BN-fp32) and the
master-param machinery in ``apex/amp/_process_optimizer.py`` /
``apex/fp16_utils/fp16_optimizer.py``. In a functional framework the model
is a param pytree, so "cast the model" is a tree_map and "master weights"
is keeping the original fp32 tree as the optimizer's source of truth.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# flax param-path fragments treated as normalization params when
# keep_batchnorm_fp32 is set. Customizable via the predicate argument.
_NORM_PATH_MARKERS = (
    "batchnorm", "batch_norm", "bn", "layernorm", "layer_norm", "norm",
    "groupnorm", "group_norm", "rmsnorm", "rms_norm",
)


def default_norm_predicate(path: tuple) -> bool:
    joined = "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    ).lower()
    return any(m in joined for m in _NORM_PATH_MARKERS)


def _is_float_leaf(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_float_leaf(x, dtype):
    return x.astype(dtype) if _is_float_leaf(x) else x


def cast_params(
    params: Any,
    dtype,
    keep_batchnorm_fp32: bool = False,
    norm_predicate: Optional[Callable[[tuple], bool]] = None,
    precast: Optional[Any] = None,
) -> Any:
    """Cast floating leaves of a param tree to ``dtype`` (O2/O3 model cast).

    With ``keep_batchnorm_fp32``, leaves whose path looks like a
    normalization parameter stay fp32 (ref: ``_initialize`` skipping
    ``_BatchNorm`` modules). ``precast`` (an optimizer's fused cast-out
    tree) short-circuits the per-leaf cast wherever its dtype already
    matches the target — the O2 per-step model cast then reads no master
    bytes for those leaves.
    """
    pred = norm_predicate or default_norm_predicate

    def cast(path, x, *pre):
        if not _is_float_leaf(x):
            return x
        target = jnp.float32 if (keep_batchnorm_fp32 and pred(path)) \
            else jnp.dtype(dtype)
        if pre and getattr(pre[0], "dtype", None) == target:
            return pre[0]
        return x.astype(target)

    if precast is None:
        return jax.tree_util.tree_map_with_path(cast, params)
    return jax.tree_util.tree_map_with_path(cast, params, precast)


def cast_inputs(batch: Any, dtype) -> Any:
    """Cast floating inputs to the compute dtype (O2 input cast)."""
    return jax.tree_util.tree_map(
        lambda x: _cast_float_leaf(x, dtype), batch
    )


def master_params(params: Any) -> Any:
    """fp32 master copy of a (possibly reduced-precision) param tree.

    Reference: ``apex.amp.master_params(optimizer)``.
    """
    return cast_inputs(params, jnp.float32)


def model_params_from_master(
    master: Any,
    like: Any,
    precast: Optional[Any] = None,
) -> Any:
    """Re-cast master weights to the dtypes of the compute tree ``like``.

    ``precast`` is an optimizer-emitted compute tree (the fused cast-out
    of ``emit_compute_params``): leaves whose dtype already matches
    ``like`` are taken verbatim — no fp32 read of the master — and only
    mismatched leaves (e.g. keep-fp32 norms against a uniform-bf16
    emission) fall back to casting ``master``.
    """
    if precast is None:
        return jax.tree_util.tree_map(
            lambda m, l: m.astype(l.dtype) if hasattr(l, "dtype") else m,
            master,
            like,
        )
    return jax.tree_util.tree_map(
        lambda m, l, c: (c if getattr(c, "dtype", None) == l.dtype
                         else m.astype(l.dtype)) if hasattr(l, "dtype")
        else m,
        master,
        like,
        precast,
    )

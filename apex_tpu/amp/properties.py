"""Opt-level property system.

Reference: ``apex/amp/frontend.py :: class Properties, class O0/O1/O2/O3,
opt_levels``. The five knobs are preserved verbatim; their meanings are
re-grounded for TPU:

- ``cast_model_type``   — dtype model params are cast to (O2/O3). On TPU the
  default "half" is **bfloat16** (MXU-native); fp16 remains selectable for
  experiments that need apex-faithful fp16 numerics.
- ``patch_torch_functions`` — the reference monkey-patches torch (O1). There
  is nothing to patch in a functional framework; the knob instead enables the
  *op-policy autocast* consulted by apex_tpu's own module/op library
  (see ``apex_tpu.amp.autocast``). Name kept for API parity.
- ``keep_batchnorm_fp32`` — keep norm params/statistics fp32 when casting.
- ``master_weights``     — maintain an fp32 master copy of params; the
  optimizer steps the master copy and re-casts to the compute dtype.
- ``loss_scale``         — float for static scaling or ``"dynamic"``.
"""

import dataclasses
from typing import Optional, Union

import jax.numpy as jnp


@dataclasses.dataclass
class Properties:
    enabled: bool = True
    opt_level: Optional[str] = None
    cast_model_type: Optional[jnp.dtype] = None
    patch_torch_functions: bool = False
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[float, str] = 1.0

    def _update_options_dict(self, new_options: dict) -> None:
        for k, v in new_options.items():
            if not hasattr(self, k):
                raise ValueError(f"Tried to set unexpected option {k!r}")
            setattr(self, k, v)

    @property
    def half_dtype(self):
        return self.cast_model_type


# TPU "half" default. Overridable per-initialize via cast_model_type.
HALF = jnp.bfloat16


class O3:
    """FP16/BF16 everything ("speed of light" baseline)."""

    brief = "O3: Pure reduced precision (bf16 on TPU)."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = HALF
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    """Half model + fp32 batchnorm + fp32 master weights + dynamic scale."""

    brief = "O2: cast model to reduced precision, keep master weights in fp32."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = HALF
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O1:
    """Op-policy autocast (the reference's patch-torch-functions mode)."""

    brief = "O1: per-op autocast via the amp op-policy lists."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0:
    """Pure fp32 (the off switch that still goes through the amp API)."""

    brief = "O0: pure fp32."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}

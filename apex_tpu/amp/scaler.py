"""Static & dynamic loss scaling, jit-native.

Reference: ``apex/amp/scaler.py :: class LossScaler`` — start at 2^16, halve
on inf/nan gradients (and skip the step), double after 2000 clean steps.

The reference mutates python attributes between CUDA launches; here the
scaler *state* is a pytree (:class:`LossScalerState`) that lives inside the
jitted train step, so scale updates and the skip decision compile into the
step with no host sync. Overflow detection is a fused all-finite reduction
over the grad pytree (the reference uses ``amp_C.multi_tensor_scale``'s
overflow flag; XLA fuses our reduction into the unscale multiply).
"""

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class LossScalerState:
    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray   # i32 scalar: clean steps since last rescale
    overflows: jnp.ndarray   # i32 scalar: total overflow count (diagnostics)


def _leaf_finite(x: jnp.ndarray) -> jnp.ndarray:
    """All-finite check robust to XLA excess precision.

    Under jit, XLA may legally elide f32→f16→f32 convert pairs
    (``xla_allow_excess_precision``), so an overflow that only exists in the
    grad's storage dtype never materializes as inf for ``isfinite`` to see.
    Compare magnitudes against the storage dtype's max instead — that
    reduction can't be folded away.
    """
    wide = jnp.promote_types(x.dtype, jnp.float32)  # f64 stays f64
    xf = x.astype(wide)
    finite = jnp.all(jnp.isfinite(xf))
    if (
        jnp.issubdtype(x.dtype, jnp.floating)
        and jnp.finfo(x.dtype).max < jnp.finfo(wide).max
    ):
        finite = jnp.logical_and(
            finite, jnp.all(jnp.abs(xf) <= jnp.finfo(x.dtype).max)
        )
    return finite


def _all_finite(tree: Any) -> jnp.ndarray:
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([_leaf_finite(l) for l in leaves]).all()


class LossScaler:
    """Pure-functional loss scaler.

    ``loss_scale="dynamic"`` enables the dynamic policy; a float pins the
    scale. All methods are (state, ...) -> (..., state) pure functions.
    """

    def __init__(
        self,
        loss_scale: Union[float, str] = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
    ):
        self.dynamic = loss_scale == "dynamic"
        self._init_scale = init_scale if self.dynamic else float(loss_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_loss_scale = (
            min_loss_scale if min_loss_scale is not None else 1.0
        )
        self.max_loss_scale = max_loss_scale

    # -- state ----------------------------------------------------------
    def init_state(self) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            overflows=jnp.asarray(0, jnp.int32),
        )

    def loss_scale(self, state: LossScalerState) -> jnp.ndarray:
        return state.loss_scale

    # -- hot path -------------------------------------------------------
    def scale(self, loss: jnp.ndarray, state: LossScalerState) -> jnp.ndarray:
        # The scaled loss is produced (and stays) in >= fp32: the default
        # 2^16 scale is not even representable in float16 (f16 max is
        # 65504), so an f16 scaled loss would be inf regardless of gradient
        # health. f64 losses keep their precision via the promotion lattice.
        # Gradients w.r.t. f16/bf16 params still flow in the param dtype.
        target = jnp.promote_types(loss.dtype, jnp.float32)
        return loss.astype(target) * state.loss_scale.astype(target)

    def unscale(
        self, grads: Any, state: LossScalerState
    ) -> Tuple[Any, jnp.ndarray]:
        """Unscale a grad pytree; returns (unscaled_grads, found_inf).

        The multiply and the finiteness reduction fuse into one pass over
        each buffer under jit (TPU equivalent of multi_tensor_scale's
        fused overflow flag).
        """
        inv = 1.0 / state.loss_scale
        # Overflow is detected on the *incoming scaled* grads in their own
        # storage dtype (what multi_tensor_scale's overflow_buf reports in
        # the reference); post-unscale values shrink back under dtype max
        # and would mask it.
        found_inf = jnp.logical_not(_all_finite(grads))

        def _unscale_leaf(g):
            wide = jnp.promote_types(g.dtype, jnp.float32)
            return (g.astype(wide) * inv.astype(wide)).astype(g.dtype)

        unscaled = jax.tree_util.tree_map(_unscale_leaf, grads)
        return unscaled, found_inf

    def update_scale(
        self, state: LossScalerState, found_inf: jnp.ndarray
    ) -> LossScalerState:
        """Dynamic policy: overflow → scale/=2, reset window; scale_window
        clean steps → scale*=2."""
        if not self.dynamic:
            return state
        overflow = found_inf
        new_on_overflow = jnp.maximum(
            state.loss_scale / self.scale_factor, self.min_loss_scale
        )
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        window_hit = unskipped >= self.scale_window
        grown = jnp.minimum(
            state.loss_scale * self.scale_factor, self.max_loss_scale
        )
        new_scale = jnp.where(
            overflow, new_on_overflow, jnp.where(window_hit, grown, state.loss_scale)
        )
        unskipped = jnp.where(window_hit, 0, unskipped)
        return LossScalerState(
            loss_scale=new_scale,
            unskipped=unskipped.astype(jnp.int32),
            overflows=state.overflows + overflow.astype(jnp.int32),
        )

    # -- checkpointing (ref: amp state_dict carries scaler state) -------
    def state_dict(self, state: LossScalerState) -> dict:
        return {
            "loss_scale": float(state.loss_scale),
            "unskipped": int(state.unskipped),
            "overflows": int(state.overflows),
        }

    def load_state_dict(self, d: dict) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            overflows=jnp.asarray(d.get("overflows", 0), jnp.int32),
        )


def apply_if_finite(updated_tree: Any, old_tree: Any, found_inf) -> Any:
    """Select ``old_tree`` leaves when found_inf (the "skip step" of the
    reference's wrapped ``optimizer.step``), compiled as a cheap select."""
    return jax.tree_util.tree_map(
        lambda new, old: jnp.where(found_inf, old, new), updated_tree, old_tree
    )

"""O1 op-policy tables.

Reference: ``apex/amp/lists/functional_overrides.py`` / ``torch_overrides.py``
/ ``tensor_overrides.py`` — which ops are fp16-safe (run in reduced
precision), which are fp32-forced, and which promote to the widest input
dtype. The reference installs these by monkey-patching torch; here they are
consulted by apex_tpu's own ops/modules through
:mod:`apex_tpu.amp.autocast` (there is no global framework to patch in JAX,
and patching would break tracing).

Names are canonical op identifiers used by our module library.
"""

# MXU-friendly ops: run in the autocast compute dtype (bf16/fp16).
FP16_FUNCS = frozenset({
    "conv1d", "conv2d", "conv3d", "conv_transpose2d",
    "matmul", "dot", "dot_general", "einsum", "linear", "dense",
    "bmm", "mm", "mv", "addmm", "addbmm", "baddbmm",
    "attention_qk", "attention_av",
})

# Numerically sensitive ops: always compute in fp32.
FP32_FUNCS = frozenset({
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "kl_div", "cosine_similarity",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "norm",
    "exp", "expm1", "log", "log10", "log2", "log1p", "pow", "erfinv",
    "softplus", "sigmoid_cross_entropy", "cumprod", "prod", "sum", "mean",
    "var", "std", "renorm", "acos", "asin", "cosh", "sinh", "tan",
})

# Dtype-promoting ops: cast all args to the widest participating dtype.
CASTS = frozenset({
    "add", "sub", "mul", "div", "addcmul", "addcdiv",
    "eq", "ne", "lt", "le", "gt", "ge", "equal",
    "cat", "stack", "where", "min", "max",
})


# Ops carried over from the reference tables that have no cast_args()
# interception site in apex_tpu yet. Kept literal (not derived from the
# lists above) so apxlint can read it statically: APX303 fires for a
# listed op that is neither wired nor declared here, APX304 fires when
# an op below gains a call site — remove it from this set as it gets
# wired.
UNWIRED = frozenset({
    # FP16_FUNCS not yet routed through cast_args
    # (wired: dense, conv2d, matmul, einsum)
    "conv1d", "conv3d", "conv_transpose2d",
    "dot", "dot_general", "linear",
    "bmm", "mm", "mv", "addmm", "addbmm", "baddbmm",
    "attention_qk", "attention_av",
    # FP32_FUNCS
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "kl_div", "cosine_similarity",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "norm",
    "exp", "expm1", "log", "log10", "log2", "log1p", "pow", "erfinv",
    "softplus", "sigmoid_cross_entropy", "cumprod", "prod", "sum", "mean",
    "var", "std", "renorm", "acos", "asin", "cosh", "sinh", "tan",
    # CASTS
    "add", "sub", "mul", "div", "addcmul", "addcdiv",
    "eq", "ne", "lt", "le", "gt", "ge", "equal",
    "cat", "stack", "where", "min", "max",
})


def policy_for(op_name: str) -> str:
    """Return 'fp16' | 'fp32' | 'promote' | 'passthrough' for an op name."""
    if op_name in FP16_FUNCS:
        return "fp16"
    if op_name in FP32_FUNCS:
        return "fp32"
    if op_name in CASTS:
        return "promote"
    return "passthrough"

"""Flat-buffer layout for the multi-tensor engine.

TPU-native replacement for the reference's pointer-chunk metadata
(ref: ``csrc/multi_tensor_apply.cuh`` builds ``TensorListMetadata`` of raw
device pointers + per-chunk tensor indices; ``apex_C`` flatten/unflatten in
``csrc/flatten_unflatten.cpp`` serve the DDP bucketing path).

XLA has no raw pointers, so tensors are packed into ONE flat 2D buffer of
shape ``(rows, 128)`` (128 = TPU lane count). Each tensor's span is aligned
to a whole number of ``(8, 128)`` fp32 tiles so that:

- every ``(8, 128)`` tile belongs to exactly one tensor (the per-chunk
  ``tensor_id`` of the CUDA metadata becomes a per-tile id array, enabling
  per-tensor reductions like LAMB trust ratios), and
- padding never straddles a compute tile (pad lanes hold zeros).
"""

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.math import cdiv, round_up_to_multiple

LANES = 128
SUBLANES = 8
TILE_ELEMS = LANES * SUBLANES  # alignment quantum per tensor
# Whole-buffer alignment: one kernel grid block (kernels.BLOCK_ROWS) so the
# flat kernels never pad/slice (keeps input_output_aliases a true in-place
# update).
ALIGN_ROWS = 256


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static layout of a flat buffer: per-tensor shapes and row spans."""

    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    row_offsets: Tuple[int, ...]   # first row of each tensor's span
    row_counts: Tuple[int, ...]    # rows (of 128 lanes) per tensor
    total_rows: int

    @property
    def num_tensors(self) -> int:
        return len(self.shapes)

    def tile_tensor_ids(self, tile_rows: int = SUBLANES) -> np.ndarray:
        """int32 array mapping each row-tile to its tensor index (the
        ``block_to_tensor`` table of the CUDA metadata). The ALIGN_ROWS
        tail padding is attributed to the last tensor — harmless, since the
        pad lanes are zero and contribute nothing to any reduction."""
        ids = np.full(self.total_rows // tile_rows,
                      max(self.num_tensors - 1, 0), np.int32)
        for t, (off, cnt) in enumerate(zip(self.row_offsets, self.row_counts)):
            ids[off // tile_rows: (off + cnt) // tile_rows] = t
        return ids


def make_spec(tensors: Sequence[jax.Array]) -> FlatSpec:
    shapes, dtypes, offsets, counts = [], [], [], []
    row = 0
    for t in tensors:
        n = int(np.prod(t.shape)) if t.ndim else 1
        rows = round_up_to_multiple(cdiv(n, LANES), SUBLANES)
        shapes.append(tuple(t.shape))
        dtypes.append(t.dtype)
        offsets.append(row)
        counts.append(rows)
        row += rows
    return FlatSpec(tuple(shapes), tuple(dtypes), tuple(offsets),
                    tuple(counts), round_up_to_multiple(row, ALIGN_ROWS))


def flatten_tensors(tensors: Sequence[jax.Array], spec: FlatSpec = None,
                    dtype=jnp.float32) -> Tuple[jax.Array, FlatSpec]:
    """Pack tensors into a zero-padded ``(rows, 128)`` buffer of ``dtype``."""
    if spec is None:
        spec = make_spec(tensors)
    parts = []
    used = 0
    for t, cnt in zip(tensors, spec.row_counts):
        flat = t.reshape(-1).astype(dtype)
        parts.append(jnp.pad(flat, (0, cnt * LANES - flat.shape[0])))
        used += cnt
    tail = spec.total_rows - used  # ALIGN_ROWS tail padding
    if tail:
        parts.append(jnp.zeros((tail * LANES,), dtype))
    return jnp.concatenate(parts).reshape(spec.total_rows, LANES), spec


def zeros_buffer(spec: FlatSpec, dtype=jnp.float32) -> jax.Array:
    """A zeroed flat buffer for ``spec`` in ``dtype`` — the per-slot dtype
    entry point for reduced-precision optimizer state (e.g. a bf16 first
    moment living beside fp32 master/``v`` buffers of the same layout)."""
    return jnp.zeros((spec.total_rows, LANES), dtype)


def unflatten_tensors(buf: jax.Array, spec: FlatSpec,
                      cast_back: bool = True) -> List[jax.Array]:
    """Slice a flat buffer back into tensors (ref: ``apex_C.unflatten``)."""
    out = []
    for shape, dt, off, cnt in zip(spec.shapes, spec.dtypes,
                                   spec.row_offsets, spec.row_counts):
        n = int(np.prod(shape)) if shape else 1
        t = buf[off:off + cnt].reshape(-1)[:n].reshape(shape)
        out.append(t.astype(dt) if cast_back else t)
    return out


def flatten_pytree(tree: Any, dtype=jnp.float32):
    """Pytree front-end: returns (buffer, spec, treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buf, spec = flatten_tensors(leaves, dtype=dtype)
    return buf, spec, treedef


def unflatten_pytree(buf: jax.Array, spec: FlatSpec, treedef,
                     cast_back: bool = True) -> Any:
    return jax.tree_util.tree_unflatten(
        treedef, unflatten_tensors(buf, spec, cast_back=cast_back))

"""Apex-shaped multi-tensor API over lists/pytrees of tensors.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py ::
class MultiTensorApply`` — a chunked launcher that feeds ``amp_C`` kernels
lists of tensors. Under XLA the "one launch for many tensors" property falls
out of compilation: a jitted function applying the same elementwise update
to every leaf is fused into a handful of device kernels, so the list-level
ops here are plain ``jnp`` tree ops. The flat-buffer Pallas engine
(``kernels.py``) remains the native path for callers that keep state packed
(optimizer ``flat=True`` mode, DDP bucket buffers).

Ops are functional: they RETURN new tensors instead of writing the output
list in place, and return ``found_inf`` instead of mutating an
``overflow_buf``.
"""

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import _all_finite


def _found_inf(tensors: Sequence[jax.Array]) -> jax.Array:
    # Uses the scaler's excess-precision-robust check: under jit XLA may
    # elide f32->f16->f32 convert pairs, hiding infs from a bare isfinite.
    return jnp.logical_not(_all_finite(list(tensors)))


def multi_tensor_scale(tensors: Sequence[jax.Array], scale,
                       out_dtypes=None) -> Tuple[List[jax.Array], jax.Array]:
    """(tensors * scale, found_inf) — ref ``amp_C.multi_tensor_scale``.

    Overflow is judged on the incoming values, matching the reference's
    overflow_buf semantics (post-scale values can shrink back into range).
    """
    s = jnp.asarray(scale, jnp.float32)
    found_inf = _found_inf(tensors)
    if out_dtypes is None:
        out = [(t.astype(jnp.float32) * s).astype(t.dtype) for t in tensors]
    else:
        out = [(t.astype(jnp.float32) * s).astype(d)
               for t, d in zip(tensors, out_dtypes)]
    return out, found_inf


def multi_tensor_axpby(a, xs: Sequence[jax.Array], b, ys: Sequence[jax.Array],
                       out_dtypes=None) -> Tuple[List[jax.Array], jax.Array]:
    """a*x + b*y per pair — ref ``amp_C.multi_tensor_axpby``. ``out_dtypes``
    (from the apex out-tensor list) selects result dtypes, default y's."""
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if out_dtypes is None:
        out_dtypes = [y.dtype for y in ys]
    out = [(a * x.astype(jnp.float32) + b * y.astype(jnp.float32)).astype(d)
           for x, y, d in zip(xs, ys, out_dtypes)]
    return out, _found_inf(out)


def multi_tensor_l2norm(tensors: Sequence[jax.Array], per_tensor: bool = False):
    """Global L2 norm (and optionally per-tensor norms) in fp32 —
    ref ``amp_C.multi_tensor_l2norm``."""
    sq = [jnp.sum(jnp.square(t.astype(jnp.float32))) for t in tensors]
    if not sq:
        z = jnp.float32(0)
        return (z, jnp.zeros((0,), jnp.float32)) if per_tensor else z
    total = jnp.sqrt(jnp.stack(sq).sum())
    if per_tensor:
        return total, jnp.sqrt(jnp.stack(sq))
    return total


class MultiTensorApply:
    """API-parity shim for the apex calling convention
    ``MultiTensorApply(chunk_size)(op, noop_flag, tensor_lists, *args)``
    where ``tensor_lists`` is a LIST OF LISTS (e.g. ``[src, dst]`` for
    scale, ``[xs, ys, outs]`` for axpby). ``chunk_size`` and ``noop_flag``
    are accepted and ignored (XLA tiles; found_inf is returned, not
    stored); output lists select the out dtypes and are otherwise unused
    (functional: results are returned)."""

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args):
        if callable(op):
            # Reference arity: ``op(chunk_size, noop_flag, tensor_lists,
            # *args)`` (apex passes both through to the CUDA kernel). We
            # forward them unchanged so ops written against the apex
            # convention drop in; pure-XLA ops are free to ignore them.
            return op(self.chunk_size, noop_flag, tensor_lists, *args)
        del noop_flag
        if op == "scale":
            (src, *rest) = tensor_lists
            out_dtypes = [t.dtype for t in rest[0]] if rest else None
            return multi_tensor_scale(src, args[0], out_dtypes)
        if op == "axpby":
            xs, ys, *rest = tensor_lists
            a, b = args[0], args[1]
            out_dtypes = [t.dtype for t in rest[0]] if rest else None
            return multi_tensor_axpby(a, xs, b, ys, out_dtypes)
        if op == "l2norm":
            return multi_tensor_l2norm(tensor_lists[0], *args)
        raise ValueError(f"unknown multi-tensor op: {op!r}")


multi_tensor_applier = MultiTensorApply()

"""Multi-tensor engine (ref: ``apex/multi_tensor_apply`` + ``amp_C``).

Two tiers:

- List/pytree ops (``multi_tensor_scale`` …): plain jnp, fused by XLA —
  the drop-in API surface.
- Flat-buffer Pallas kernels (``kernels``): a single packed ``(rows, 128)``
  buffer walked tile-by-tile — the native path for packed optimizer state
  and DDP buckets.
"""

from apex_tpu.multi_tensor_apply.flatten import (  # noqa: F401
    FlatSpec,
    flatten_pytree,
    flatten_tensors,
    make_spec,
    unflatten_pytree,
    unflatten_tensors,
)
from apex_tpu.multi_tensor_apply.multi_tensor_apply import (  # noqa: F401
    MultiTensorApply,
    multi_tensor_applier,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)
from apex_tpu.multi_tensor_apply import kernels  # noqa: F401

"""Pallas flat-buffer kernels — the TPU-native ``amp_C``.

Each kernel walks a ``(rows, 128)`` flat buffer (see ``flatten.py``) in
``(BLOCK_ROWS, 128)`` tiles, one grid step per tile, double-buffered by the
Pallas pipeline. Reductions emit per-tile partials that are combined outside
the kernel (the CUDA two-stage reduction pattern of
``csrc/multi_tensor_l2norm_kernel.cu``); the overflow flag of
``csrc/multi_tensor_scale_kernel.cu`` becomes a per-tile finite bit reduced
with ``jnp.all``. Optimizer updates alias their state buffers in place
(``input_output_aliases``) so a step is a single read-modify-write pass over
HBM, matching the one-kernel-per-step property of ``csrc/multi_tensor_adam.cu``.

Hyperparameters arrive as a ``(1, N)`` fp32 array in SMEM so that traced
values (schedules, dynamic loss scale) never trigger recompilation.

Reduced-precision state: the first-moment buffer of Adam/LAMB/NovoGrad (and
the SGD momentum buffer) may be bf16 — kernels load it with an fp32 upcast,
accumulate in fp32, and store back in the buffer's own dtype (plain
round-to-nearest-even, no stochastic rounding; the fp32 master keeps the
update unbiased enough — see ``docs/source/optimizer_states.rst``). ``v``
stays fp32 always. BLOCK_ROWS=256 is divisible by the bf16 min-tile
sublane count (16), so bf16 buffers reuse the same ``(256, 128)`` grid.
The optimizer kernels can additionally emit the updated params pre-cast to
a compute dtype (``emit_compute_dtype=jnp.bfloat16``) as one extra output
written from registers — the fused cast-out that lets amp-O2 skip its
separate fp32→bf16 ``model_params_from_master`` pass over the master tree.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.flatten import ALIGN_ROWS, LANES
from apex_tpu.utils.math import cdiv
from apex_tpu.utils.pallas import dimsem as _dimsem
from apex_tpu.utils.platform import pallas_interpret

BLOCK_ROWS = ALIGN_ROWS  # (256, 128) fp32 tile = 128 KiB per buffer;
# equals the FlatSpec whole-buffer alignment so flat buffers never need
# pad/slice here (input_output_aliases stays a true in-place update)


def _pad_to_block(buf: jax.Array) -> jax.Array:
    rows = buf.shape[0]
    padded = cdiv(rows, BLOCK_ROWS) * BLOCK_ROWS
    if padded != rows:
        buf = jnp.pad(buf, ((0, padded - rows), (0, 0)))
    return buf


def _tile_spec():
    return pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _partial_spec():
    return pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


# ---------------------------------------------------------------------------
# scale (+ overflow check) — ref csrc/multi_tensor_scale_kernel.cu
# ---------------------------------------------------------------------------

def _scale_kernel(sc_ref, x_ref, out_ref, finite_ref):
    x = x_ref[:].astype(jnp.float32)
    out_ref[:] = (x * sc_ref[0, 0]).astype(out_ref.dtype)
    # Overflow is judged on the INCOMING values (pre-unscale), as the
    # reference's overflow_buf does.
    finite_ref[0, 0] = jnp.all(jnp.isfinite(x)).astype(jnp.int32)


def flat_scale(buf: jax.Array, scale, out_dtype=None,
               interpret: Optional[bool] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Returns (buf * scale, found_inf: bool scalar)."""
    rows = buf.shape[0]
    x = _pad_to_block(buf)
    n_tiles = x.shape[0] // BLOCK_ROWS
    sc = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    out, finite = pl.pallas_call(
        _scale_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec(), _tile_spec()],
        out_specs=[_tile_spec(), _partial_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, out_dtype or buf.dtype),
            jax.ShapeDtypeStruct((n_tiles, 1), jnp.int32),
        ],
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, x)
    return out[:rows], jnp.logical_not(jnp.all(finite == 1))


# ---------------------------------------------------------------------------
# axpby — ref csrc/multi_tensor_axpby_kernel.cu
# ---------------------------------------------------------------------------

def _axpby_kernel(sc_ref, x_ref, y_ref, out_ref, finite_ref):
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    r = sc_ref[0, 0] * x + sc_ref[0, 1] * y
    out_ref[:] = r.astype(out_ref.dtype)
    finite_ref[0, 0] = jnp.all(jnp.isfinite(r)).astype(jnp.int32)


def flat_axpby(a, x: jax.Array, b, y: jax.Array, out_dtype=None,
               interpret: Optional[bool] = None
               ) -> Tuple[jax.Array, jax.Array]:
    rows = x.shape[0]
    xp, yp = _pad_to_block(x), _pad_to_block(y)
    n_tiles = xp.shape[0] // BLOCK_ROWS
    sc = jnp.stack([jnp.asarray(a, jnp.float32),
                    jnp.asarray(b, jnp.float32)]).reshape(1, 2)
    out, finite = pl.pallas_call(
        _axpby_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec(), _tile_spec(), _tile_spec()],
        out_specs=[_tile_spec(), _partial_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(xp.shape, out_dtype or x.dtype),
            jax.ShapeDtypeStruct((n_tiles, 1), jnp.int32),
        ],
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, xp, yp)
    return out[:rows], jnp.logical_not(jnp.all(finite == 1))


# ---------------------------------------------------------------------------
# L2 norm — ref csrc/multi_tensor_l2norm_kernel.cu (two-stage reduction)
# ---------------------------------------------------------------------------

_SUB = 8  # fine-partial granularity = one (8, 128) fp32 tile
_SUBS_PER_BLOCK = BLOCK_ROWS // _SUB


def _l2_kernel(x_ref, part_ref):
    x = x_ref[:].astype(jnp.float32)
    # one partial per (8, 128) sub-tile — tensor spans are 8-row aligned
    # (flatten.TILE_ELEMS), so each partial belongs to exactly one tensor.
    part_ref[0, :] = jnp.sum((x * x).reshape(_SUBS_PER_BLOCK, _SUB * LANES),
                             axis=1)


def flat_l2norm_partials(buf: jax.Array,
                         interpret: Optional[bool] = None) -> jax.Array:
    """Per-(8, 128)-sub-tile sum-of-squares partials, fp32, shape (rows/8,)
    (padded up to a whole number of blocks; pad partials are zero).

    ``sqrt(sum(partials))`` is the global norm; a segment-sum of partials by
    ``FlatSpec.tile_tensor_ids(8)`` gives per-tensor norms (used by LAMB
    trust ratios) — stage 2 of the CUDA two-stage reduction, done by XLA.
    """
    x = _pad_to_block(buf)
    n_tiles = x.shape[0] // BLOCK_ROWS
    parts = pl.pallas_call(
        _l2_kernel,
        grid=(n_tiles,),
        in_specs=[_tile_spec()],
        out_specs=pl.BlockSpec((1, _SUBS_PER_BLOCK), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_tiles, _SUBS_PER_BLOCK),
                                       jnp.float32),
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(x)
    return parts.reshape(-1)


def flat_l2norm(buf: jax.Array, interpret: Optional[bool] = None) -> jax.Array:
    return jnp.sqrt(jnp.sum(flat_l2norm_partials(buf, interpret)))


# ---------------------------------------------------------------------------
# Adam / AdamW — ref csrc/multi_tensor_adam.cu
# ---------------------------------------------------------------------------

def _adam_kernel(sc_ref, g_ref, p_ref, m_ref, v_ref,
                 p_out, m_out, v_out, *pc_out):
    lr = sc_ref[0, 0]
    b1 = sc_ref[0, 1]
    b2 = sc_ref[0, 2]
    eps = sc_ref[0, 3]
    wd = sc_ref[0, 4]
    c1 = sc_ref[0, 5]       # 1 - b1^t   (1.0 when bias_correction off)
    c2 = sc_ref[0, 6]       # 1 - b2^t
    adam_w = sc_ref[0, 7]   # 1.0 => decoupled (AdamW), 0.0 => L2 into grad
    grad_scale = sc_ref[0, 8]  # combined inv-loss-scale (1.0 when unused)

    g = g_ref[:].astype(jnp.float32) * grad_scale
    p = p_ref[:]
    m = m_ref[:].astype(jnp.float32)   # fp32 accumulate for bf16 moments
    v = v_ref[:]

    g_l2 = g + (1.0 - adam_w) * wd * p
    m = b1 * m + (1.0 - b1) * g_l2
    v = b2 * v + (1.0 - b2) * g_l2 * g_l2
    update = (m / c1) / (jnp.sqrt(v / c2) + eps) + adam_w * wd * p
    p_new = p - lr * update
    p_out[:] = p_new
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v
    if pc_out:  # fused cast-out: compute params written from registers
        pc_out[0][:] = p_new.astype(pc_out[0].dtype)


def _sgd_kernel(sc_ref, g_ref, p_ref, buf_ref, p_out, buf_out, *pc_out):
    lr = sc_ref[0, 0]
    mom = sc_ref[0, 1]
    damp = sc_ref[0, 2]
    wd = sc_ref[0, 3]
    nesterov = sc_ref[0, 4]        # 1.0 / 0.0
    wd_after = sc_ref[0, 5]        # 1.0 => wd after momentum
    first = sc_ref[0, 6]           # 1.0 on the seeding step
    grad_scale = sc_ref[0, 7]
    use_mom = sc_ref[0, 8]         # momentum > 0

    g = g_ref[:].astype(jnp.float32) * grad_scale
    p = p_ref[:]
    buf = buf_ref[:].astype(jnp.float32)

    g = g + (1.0 - wd_after) * wd * p
    seeded = jnp.where(first > 0, g, mom * buf + (1.0 - damp) * g)
    d_mom = jnp.where(nesterov > 0, g + mom * seeded, seeded)
    d = jnp.where(use_mom > 0, d_mom, g)
    buf_out[:] = jnp.where(use_mom > 0, seeded, buf).astype(buf_out.dtype)
    d = d + wd_after * wd * p
    p_new = p - lr * d
    p_out[:] = p_new
    if pc_out:
        pc_out[0][:] = p_new.astype(pc_out[0].dtype)


def flat_sgd(grads: jax.Array, params: jax.Array, momentum_buf: jax.Array,
             *, lr, momentum: float, dampening: float, weight_decay,
             nesterov: bool, wd_after_momentum: bool, first_run,
             grad_scale=1.0, emit_compute_dtype=None,
             interpret: Optional[bool] = None):
    """One fused SGD step over flat buffers (ref:
    ``csrc/multi_tensor_sgd_kernel.cu`` incl. the ``first_run`` buffer
    seeding and ``wd_after_momentum``). ``params``/``momentum_buf`` alias
    in place; ``first_run`` may be a traced bool. ``momentum_buf`` may be
    bf16 (fp32 accumulate); ``emit_compute_dtype`` appends the fused
    cast-out output (return grows to ``(p, buf, compute)``)."""
    rows = params.shape[0]
    gp, pp, bp = (_pad_to_block(b) for b in (grads, params, momentum_buf))
    n_tiles = pp.shape[0] // BLOCK_ROWS
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(momentum),
        jnp.float32(dampening), jnp.asarray(weight_decay, jnp.float32),
        jnp.float32(1.0 if nesterov else 0.0),
        jnp.float32(1.0 if wd_after_momentum else 0.0),
        jnp.asarray(first_run, jnp.float32),
        jnp.asarray(grad_scale, jnp.float32),
        jnp.float32(1.0 if momentum > 0 else 0.0),
    ]).reshape(1, 9)
    out_shape = [jax.ShapeDtypeStruct(pp.shape, jnp.float32),
                 jax.ShapeDtypeStruct(pp.shape, bp.dtype)]
    if emit_compute_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct(pp.shape, emit_compute_dtype))
    outs = pl.pallas_call(
        _sgd_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec()] + [_tile_spec()] * 3,
        out_specs=[_tile_spec()] * len(out_shape),
        out_shape=out_shape,
        input_output_aliases={2: 0, 3: 1},
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, gp, pp, bp)
    return tuple(o[:rows] for o in outs)


# ---------------------------------------------------------------------------
# LAMB — ref csrc/multi_tensor_lamb.cu (_stage_1 + _stage_2)
# ---------------------------------------------------------------------------

def _lamb_stage1_kernel(sc_ref, g_ref, p_ref, m_ref, v_ref,
                        m_out, v_out, u_out, p_ssq, u_ssq):
    b1 = sc_ref[0, 0]
    b2 = sc_ref[0, 1]
    eps = sc_ref[0, 2]
    wd = sc_ref[0, 3]
    c1 = sc_ref[0, 4]
    c2 = sc_ref[0, 5]
    adam_w = sc_ref[0, 6]
    beta3 = sc_ref[0, 7]          # 1-b1 (grad averaging) or 1.0
    gs_over_clip = sc_ref[0, 8]   # grad_scale / clip, combined

    g = g_ref[:].astype(jnp.float32) * gs_over_clip
    p = p_ref[:]
    m = m_ref[:].astype(jnp.float32)   # fp32 accumulate for bf16 moments
    v = v_ref[:]

    g_l2 = g + (1.0 - adam_w) * wd * p
    m = b1 * m + beta3 * g_l2
    v = b2 * v + (1.0 - b2) * g_l2 * g_l2
    u = (m / c1) / (jnp.sqrt(v / c2) + eps) + adam_w * wd * p
    m_out[:] = m.astype(m_out.dtype)
    v_out[:] = v
    u_out[:] = u
    # fused stage-2 preamble: per-(8,128)-sub-tile ||p||², ||u||² partials
    # (tensor spans are 8-row aligned, so each partial maps to one tensor)
    p_ssq[0, :] = jnp.sum((p * p).reshape(_SUBS_PER_BLOCK, _SUB * LANES), 1)
    u_ssq[0, :] = jnp.sum((u * u).reshape(_SUBS_PER_BLOCK, _SUB * LANES), 1)


def flat_lamb(grads: jax.Array, params: jax.Array, m: jax.Array,
              v: jax.Array, tile_ids, *, lr, beta1: float, beta2: float,
              eps: float, step, weight_decay, num_tensors: int,
              adam_w_mode: bool = True, grad_averaging: bool = True,
              bias_correction: bool = True, use_nvlamb: bool = False,
              max_grad_norm: float = 1.0, grad_scale=1.0,
              grad_norm=None, emit_compute_dtype=None,
              interpret: Optional[bool] = None):
    """Fused LAMB step over flat buffers, following the CUDA
    two-stage split: stage 1 (one Pallas pass) produces moments, the raw
    update AND the per-sub-tile ||p||²/||u||² partials; the per-tensor
    trust-ratio combine (segment-sum + ratio) and the stage-2
    ``p -= lr·ratio·u`` are XLA elementwise/reduction ops that fuse into
    two trivial passes. ``tile_ids`` is ``FlatSpec.tile_tensor_ids(8)``.
    The global grad-norm clip uses one ``flat_l2norm`` pre-pass over the
    scaled grads (the reference likewise pre-reduces). ``m`` may be bf16
    (fp32 accumulate in stage 1); ``emit_compute_dtype`` appends the
    cast-out params to the return (the cast fuses into the XLA stage-2
    pass — no extra read of the fp32 params)."""
    rows = params.shape[0]
    gs = jnp.asarray(grad_scale, jnp.float32)
    if grad_norm is None:
        grad_norm = jnp.sqrt(jnp.sum(
            flat_l2norm_partials(grads, interpret)) * gs * gs)
    max_norm = jnp.float32(max_grad_norm)
    clip = jnp.where((max_norm > 0) & (grad_norm > max_norm),
                     grad_norm / max_norm, jnp.float32(1.0))

    gp, pp, mp, vp = (_pad_to_block(b) for b in (grads, params, m, v))
    n_tiles = pp.shape[0] // BLOCK_ROWS
    t = jnp.asarray(step, jnp.float32)
    if bias_correction:
        c1 = 1.0 - jnp.float32(beta1) ** t
        c2 = 1.0 - jnp.float32(beta2) ** t
    else:
        c1 = c2 = jnp.float32(1.0)
    sc = jnp.stack([
        jnp.float32(beta1), jnp.float32(beta2), jnp.float32(eps),
        jnp.asarray(weight_decay, jnp.float32), c1, c2,
        jnp.float32(1.0 if adam_w_mode else 0.0),
        jnp.float32(1.0 - beta1 if grad_averaging else 1.0),
        gs / clip,
    ]).reshape(1, 9)
    part_spec = pl.BlockSpec((1, _SUBS_PER_BLOCK), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    m_new, v_new, u, p_parts, u_parts = pl.pallas_call(
        _lamb_stage1_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec()] + [_tile_spec()] * 4,
        out_specs=[_tile_spec()] * 3 + [part_spec] * 2,
        out_shape=[jax.ShapeDtypeStruct(pp.shape, mp.dtype),
                   jax.ShapeDtypeStruct(pp.shape, jnp.float32),
                   jax.ShapeDtypeStruct(pp.shape, jnp.float32)]
        + [jax.ShapeDtypeStruct((n_tiles, _SUBS_PER_BLOCK), jnp.float32)] * 2,
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, gp, pp, mp, vp)

    # stage 2: per-tensor trust ratios from the fused partials
    ids = jnp.asarray(tile_ids, jnp.int32)
    n_sub = rows // _SUB
    w_norm = jnp.sqrt(jax.ops.segment_sum(
        p_parts.reshape(-1)[:n_sub], ids, num_segments=num_tensors))
    u_norm = jnp.sqrt(jax.ops.segment_sum(
        u_parts.reshape(-1)[:n_sub], ids, num_segments=num_tensors))
    ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm,
                      jnp.float32(1.0))
    if not use_nvlamb:
        wd_t = jnp.asarray(weight_decay, jnp.float32)
        ratio = jnp.where(wd_t == 0.0, jnp.ones_like(ratio), ratio)
    row_ratio = jnp.repeat(ratio[ids], _SUB)[:, None]  # (rows, 1)
    lr_t = jnp.asarray(lr, jnp.float32)
    p_new = pp[:rows] - lr_t * row_ratio * u[:rows]
    if emit_compute_dtype is not None:
        return (p_new, m_new[:rows], v_new[:rows],
                p_new.astype(emit_compute_dtype))
    return p_new, m_new[:rows], v_new[:rows]


# ---------------------------------------------------------------------------
# Adagrad — ref csrc/multi_tensor_adagrad.cu
# ---------------------------------------------------------------------------

def _adagrad_kernel(sc_ref, g_ref, p_ref, s_ref, p_out, s_out, *pc_out):
    lr = sc_ref[0, 0]
    eps = sc_ref[0, 1]
    wd = sc_ref[0, 2]
    adagrad_w = sc_ref[0, 3]   # 1.0 => decoupled decay, 0.0 => L2 into grad
    grad_scale = sc_ref[0, 4]

    g = g_ref[:].astype(jnp.float32) * grad_scale
    p = p_ref[:]
    s = s_ref[:]

    g = g + (1.0 - adagrad_w) * wd * p
    s = s + g * g
    u = g / (jnp.sqrt(s) + eps) + adagrad_w * wd * p
    p_new = p - lr * u
    p_out[:] = p_new
    s_out[:] = s
    if pc_out:
        pc_out[0][:] = p_new.astype(pc_out[0].dtype)


def flat_adagrad(grads: jax.Array, params: jax.Array, gsum: jax.Array,
                 *, lr, eps: float, weight_decay,
                 adagrad_w_mode: bool = False, grad_scale=1.0,
                 emit_compute_dtype=None,
                 interpret: Optional[bool] = None):
    """One fused Adagrad step over flat fp32 buffers (ref:
    ``csrc/multi_tensor_adagrad.cu``); ``params``/``gsum`` alias in
    place. ``emit_compute_dtype`` appends the fused cast-out output."""
    rows = params.shape[0]
    gp, pp, sp = (_pad_to_block(b) for b in (grads, params, gsum))
    n_tiles = pp.shape[0] // BLOCK_ROWS
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(eps),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.float32(1.0 if adagrad_w_mode else 0.0),
        jnp.asarray(grad_scale, jnp.float32),
    ]).reshape(1, 5)
    out_shape = [jax.ShapeDtypeStruct(pp.shape, jnp.float32)] * 2
    if emit_compute_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct(pp.shape, emit_compute_dtype))
    outs = pl.pallas_call(
        _adagrad_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec()] + [_tile_spec()] * 3,
        out_specs=[_tile_spec()] * len(out_shape),
        out_shape=out_shape,
        input_output_aliases={2: 0, 3: 1},
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, gp, pp, sp)
    return tuple(o[:rows] for o in outs)


# ---------------------------------------------------------------------------
# NovoGrad — ref csrc/multi_tensor_novograd.cu (per-tensor second moment)
# ---------------------------------------------------------------------------

def _novograd_kernel(sc_ref, denom_ref, g_ref, p_ref, m_ref, p_out, m_out,
                     *pc_out):
    lr = sc_ref[0, 0]
    b1 = sc_ref[0, 1]
    beta3 = sc_ref[0, 2]       # 1-b1 (grad averaging) or 1.0
    wd = sc_ref[0, 3]
    c1 = sc_ref[0, 4]          # 1 - b1^t
    reg_inside = sc_ref[0, 5]  # 1.0 => wd folded into the moment
    grad_scale = sc_ref[0, 6]

    g = g_ref[:].astype(jnp.float32) * grad_scale
    p = p_ref[:]
    m = m_ref[:].astype(jnp.float32)   # fp32 accumulate for bf16 moments

    gn = g / denom_ref[:]      # per-row broadcast of the per-tensor denom
    gn = gn + reg_inside * wd * p
    m = b1 * m + beta3 * gn
    u = m / c1 + (1.0 - reg_inside) * wd * p
    p_new = p - lr * u
    p_out[:] = p_new
    m_out[:] = m.astype(m_out.dtype)
    if pc_out:
        pc_out[0][:] = p_new.astype(pc_out[0].dtype)


def flat_novograd(grads: jax.Array, params: jax.Array, m: jax.Array,
                  v: jax.Array, tile_ids, *, lr, beta1: float, beta2: float,
                  eps: float, step, weight_decay, num_tensors: int,
                  grad_averaging: bool = True, bias_correction: bool = True,
                  reg_inside_moment: bool = False, init_zero: bool = False,
                  grad_scale=1.0, emit_compute_dtype=None,
                  interpret: Optional[bool] = None):
    """Fused NovoGrad step over flat fp32 buffers. NovoGrad's second
    moment is ONE scalar per tensor (the layer-wise EMA of ||g||², ref
    ``multi_tensor_novograd.cu``), so ``v`` is a ``(num_tensors,)`` fp32
    vector: the per-sub-tile ||g||² partials come from one l2 pre-pass
    (the same two-stage reduction LAMB uses), the tiny v-EMA update is
    XLA, and the elementwise moment/param update is one Pallas pass with
    the per-tensor denominator broadcast in as a ``(rows, 1)`` column.
    ``tile_ids`` is ``FlatSpec.tile_tensor_ids(8)``. ``m`` may be bf16
    (fp32 accumulate); ``emit_compute_dtype`` appends the fused cast-out
    output (return grows to ``(p, m, v, compute)``).
    """
    rows = params.shape[0]
    gs = jnp.asarray(grad_scale, jnp.float32)
    ids = jnp.asarray(tile_ids, jnp.int32)
    n_sub = rows // _SUB
    gsq = jax.ops.segment_sum(
        flat_l2norm_partials(grads, interpret)[:n_sub], ids,
        num_segments=num_tensors) * gs * gs
    b2 = jnp.float32(beta2)
    first = jnp.asarray(step, jnp.int32) <= 1
    ema = b2 * v + (1.0 - b2) * gsq
    v_new = ema if init_zero else jnp.where(first, gsq, ema)

    t = jnp.asarray(step, jnp.float32)
    if bias_correction:
        c1 = 1.0 - jnp.float32(beta1) ** t
        c2 = 1.0 - b2 ** t
    else:
        c1 = c2 = jnp.float32(1.0)
    denom = jnp.sqrt(v_new / c2) + jnp.float32(eps)
    row_denom = jnp.repeat(denom[ids], _SUB)[:, None]  # (rows, 1)
    row_denom = _pad_to_block(row_denom)
    row_denom = jnp.where(row_denom == 0, 1.0, row_denom)  # block-pad rows

    gp, pp, mp = (_pad_to_block(b) for b in (grads, params, m))
    n_tiles = pp.shape[0] // BLOCK_ROWS
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(beta1),
        jnp.float32(1.0 - beta1 if grad_averaging else 1.0),
        jnp.asarray(weight_decay, jnp.float32), c1,
        jnp.float32(1.0 if reg_inside_moment else 0.0), gs,
    ]).reshape(1, 7)
    denom_spec = pl.BlockSpec((BLOCK_ROWS, 1), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct(pp.shape, jnp.float32),
                 jax.ShapeDtypeStruct(pp.shape, mp.dtype)]
    if emit_compute_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct(pp.shape, emit_compute_dtype))
    outs = pl.pallas_call(
        _novograd_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec(), denom_spec] + [_tile_spec()] * 3,
        out_specs=[_tile_spec()] * len(out_shape),
        out_shape=out_shape,
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, row_denom, gp, pp, mp)
    if emit_compute_dtype is not None:
        return outs[0][:rows], outs[1][:rows], v_new, outs[2][:rows]
    return outs[0][:rows], outs[1][:rows], v_new


def flat_adam(grads: jax.Array, params: jax.Array, m: jax.Array, v: jax.Array,
              *, lr, beta1: float, beta2: float, eps: float, step,
              weight_decay, adam_w_mode: bool = True,
              bias_correction: bool = True, grad_scale=1.0,
              emit_compute_dtype=None,
              interpret: Optional[bool] = None):
    """One fused Adam/AdamW step over flat buffers.

    ``params``/``m``/``v`` are aliased in place (donate them under jit).
    All hyperparameters may be traced scalars. ``m`` may be bf16 (loaded
    with an fp32 upcast, stored back in its own dtype); ``v`` must stay
    fp32. With ``emit_compute_dtype`` the kernel writes one extra
    (non-aliased) output — the updated params cast to that dtype — and the
    return grows to ``(p, m, v, compute)``.
    """
    rows = params.shape[0]
    gp, pp, mp, vp = (_pad_to_block(b) for b in (grads, params, m, v))
    n_tiles = pp.shape[0] // BLOCK_ROWS
    t = jnp.asarray(step, jnp.float32)
    if bias_correction:
        c1 = 1.0 - jnp.asarray(beta1, jnp.float32) ** t
        c2 = 1.0 - jnp.asarray(beta2, jnp.float32) ** t
    else:
        c1 = jnp.float32(1.0)
        c2 = jnp.float32(1.0)
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(beta1), jnp.float32(beta2),
        jnp.float32(eps), jnp.asarray(weight_decay, jnp.float32), c1, c2,
        jnp.float32(1.0 if adam_w_mode else 0.0),
        jnp.asarray(grad_scale, jnp.float32),
    ]).reshape(1, 9)
    n_out = 3 + (1 if emit_compute_dtype is not None else 0)
    out_shape = [
        jax.ShapeDtypeStruct(pp.shape, jnp.float32),
        jax.ShapeDtypeStruct(pp.shape, mp.dtype),
        jax.ShapeDtypeStruct(pp.shape, jnp.float32),
    ]
    if emit_compute_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct(pp.shape, emit_compute_dtype))
    outs = pl.pallas_call(
        _adam_kernel,
        grid=(n_tiles,),
        in_specs=[_smem_spec()] + [_tile_spec()] * 4,
        out_specs=[_tile_spec()] * n_out,
        out_shape=out_shape,
        input_output_aliases={2: 0, 3: 1, 4: 2},
        compiler_params=_dimsem("parallel"),
        interpret=pallas_interpret(interpret),
    )(sc, gp, pp, mp, vp)
    return tuple(o[:rows] for o in outs)

"""Data-parallel utilities (ref: ``apex/parallel/__init__.py``).

``DistributedDataParallel`` (grad psum over the mesh ``data`` axis),
``SyncBatchNorm`` (+``convert_syncbn_model``), ``LARC``, and the
multi-host bootstrap in ``multiproc``.
"""

from apex_tpu.parallel.distributed import DistributedDataParallel  # noqa: F401
from apex_tpu.parallel.LARC import LARC  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)

"""Data-parallel utilities: DDP semantics, SyncBatchNorm, LARC.

Reference: ``apex/parallel/__init__.py``. Populated by the data-parallel
build phase.
"""

"""Data-parallel gradient synchronization.

Reference: ``apex/parallel/distributed.py :: class DistributedDataParallel``
— per-param backward hooks, bucketing with first-iteration structure
discovery, flatten via ``apex_C``, async NCCL allreduce on a side stream,
``delay_allreduce``, ``allreduce_always_fp32``, ``gradient_average``.

On TPU the entire hook/bucket/stream machinery collapses: gradient
"allreduce" is a ``lax.psum`` over the mesh ``data`` axis inside the jitted
step, and overlap with backward compute is XLA's latency-hiding scheduler's
job. What survives of the reference API is the numerics policy:

- ``allreduce_always_fp32`` — upcast grads to fp32 for the reduction;
- ``gradient_average`` — divide by the data-parallel world size;
- ``delay_allreduce`` — moot (there is one fused reduction anyway), kept
  as an accepted no-op for signature parity.

Two usage styles:

1. inside ``shard_map`` over the data axis (closest to the reference)::

       ddp = DistributedDataParallel()
       replica = ddp.local_replica(params)  # per-rank replica (torch-style)
       grads = jax.grad(loss)(replica, shard_of_batch)
       grads = ddp.allreduce_grads(grads)   # psum over "data"

   ``local_replica`` matters under shard_map's varying-axes semantics:
   differentiating w.r.t. a REPLICATED (unvarying) input makes JAX insert
   the cross-axis psum itself (the transpose of the implicit broadcast),
   so grads arrive pre-summed and another allreduce would double-count.
   ``pcast(..., to='varying')`` gives each rank its own replica — exactly
   the torch DDP model — leaving the reduction to this wrapper.

2. whole-program GSPMD: just shard the batch with
   ``ddp.shard_batch(batch)`` and jit — XLA inserts the same reduction
   (summed, so divide the loss, not the grads, for averaging).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu.transformer import parallel_state as ps


class DistributedDataParallel:
    def __init__(self, module=None, *, message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 axis_name: Optional[str] = None):
        # ``module`` / ``message_size`` / ``delay_allreduce`` accepted for
        # reference-signature parity; bucketing has no TPU equivalent.
        self.module = module
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.axis_name = axis_name or ps.DATA_AXIS

    # -- shard_map style ------------------------------------------------
    def local_replica(self, params: Any) -> Any:
        """Per-rank replica of replicated params (call inside shard_map
        before taking grads) — the torch "module replica" of the
        reference; see the module docstring for why this is load-bearing."""
        pcast = getattr(lax, "pcast", None)
        if pcast is None:
            # jax without varying-axes tracking: ps.shard_map runs with
            # check_rep=False there, so replicated inputs are already
            # plain per-rank values and the broadcast transpose inserts
            # no psum — the identity IS the per-rank replica.
            return params
        return jax.tree.map(
            lambda p: pcast(p, self.axis_name, to="varying"), params)

    def allreduce_grads(self, grads: Any) -> Any:
        """psum grads over the data axis (call inside shard_map/pmap).

        Matches the reference reduction numerics: optional fp32 upcast,
        then sum, then average by world size."""
        axis = self.axis_name

        def reduce_leaf(g):
            orig = g.dtype
            if self.allreduce_always_fp32:
                g = g.astype(jnp.float32)
            g = lax.psum(g, axis)
            if self.gradient_average:
                g = g / lax.psum(1, axis)
            return g.astype(orig)

        return jax.tree.map(reduce_leaf, grads)

    def broadcast_params(self, params: Any) -> Any:
        """Make every data-parallel rank hold rank 0's params (the
        reference ctor's ``flat_dist_call(..., broadcast)``); call inside
        shard_map."""
        axis = self.axis_name
        rank = lax.axis_index(axis)

        def bcast(p):
            # Masked psum: every rank but 0 contributes exact zeros, so
            # the sum reproduces rank 0's value EXACTLY in the leaf's own
            # dtype — no fp32 round-trip (which would truncate f64 and
            # corrupt wide-int leaves). Bool/int leaves ride through int32
            # (XLA collectives need an arithmetic type for bool).
            if p.dtype == jnp.bool_:
                masked = jnp.where(rank == 0, p.astype(jnp.int32),
                                   jnp.zeros(p.shape, jnp.int32))
                return lax.psum(masked, axis).astype(jnp.bool_)
            masked = jnp.where(rank == 0, p, jnp.zeros_like(p))
            return lax.psum(masked, axis)

        return jax.tree.map(bcast, params)

    # -- GSPMD style ----------------------------------------------------
    def shard_batch(self, batch: Any, mesh=None) -> Any:
        """Place a global batch sharded over the data axis (leading dim)."""
        mesh = mesh or ps.get_mesh()
        spec = PartitionSpec(self.axis_name)
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)

    def replicate(self, tree: Any, mesh=None) -> Any:
        mesh = mesh or ps.get_mesh()
        spec = PartitionSpec()
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec)), tree)

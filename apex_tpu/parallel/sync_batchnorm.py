"""SyncBatchNorm — cross-replica batch normalization.

Reference: ``apex/parallel/sync_batchnorm.py`` + ``optimized_sync_batchnorm*``
(CUDA ``welford`` kernels in ``csrc/welford.cu``): per-GPU partial Welford
stats, allreduced across the process group, then normalization.

TPU version: per-shard mean/mean-of-squares reduced with ``lax.pmean`` over
the mesh ``data`` axis (XLA's allreduce over ICI) — the two-pass Welford
combine collapses into one fused reduction. Runs inside shard_map/pmap;
outside any mapped axis it degrades to plain BatchNorm exactly as the
reference does in a single-process run.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.models import layers as L
from apex_tpu.transformer import parallel_state as ps


class SyncBatchNorm:
    """Module-shaped functional SyncBN (channel-last).

    ``process_group`` of the reference becomes a mesh ``axis_name``.
    ``init() -> (params, running_state)``;
    ``apply(params, state, x, train=...) -> (y, new_state)``.
    """

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 axis_name: Optional[str] = None,
                 channel_last: bool = True):
        # ``momentum`` follows the torch/apex convention (UPDATE fraction,
        # default 0.1): running = (1 - momentum) * running + momentum * batch.
        # layers.batchnorm takes the keep fraction, so it receives
        # ``1 - momentum``.
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.channel_last = channel_last
        self.axis_name = axis_name if axis_name is not None else ps.DATA_AXIS

    def init(self) -> Tuple[Optional[Dict], Optional[Dict]]:
        """(params, running_state); ``affine=False`` → params None,
        ``track_running_stats=False`` → state None (batch stats are then
        used in eval too — torch semantics)."""
        params, state = L.init_batchnorm(self.num_features)
        return (params if self.affine else None,
                state if self.track_running_stats else None)

    def apply(self, params: Optional[Dict], state: Optional[Dict],
              x: jax.Array, *, train: bool = True
              ) -> Tuple[jax.Array, Optional[Dict]]:
        if not self.channel_last:
            # NCHW (torch layout): normalize over all but axis 1. A
            # transpose pair is free here — XLA fuses layout changes into
            # the surrounding reduction/elementwise ops.
            x = jnp.moveaxis(x, 1, -1)
        # stats sync also when eval-ing with batch stats (no running
        # stats tracked) — every replica must normalize identically
        use_batch = train or state is None
        y, new_state = L.batchnorm(
            params, state, x, train=train,
            momentum=1.0 - self.momentum, eps=self.eps,
            axis_name=self.axis_name if use_batch else None)
        if not self.channel_last:
            y = jnp.moveaxis(y, -1, 1)
        return y, new_state

    __call__ = apply


def convert_syncbn_model(apply_fn, axis_name: Optional[str] = None,
                         **partial_kwargs):
    """Reference: ``apex/parallel/__init__.py :: convert_syncbn_model``
    walks a module tree replacing BatchNorm with SyncBatchNorm. Functional
    translation: the model zoo's apply functions thread an ``axis_name``
    into every BatchNorm, so conversion = binding that argument.

        sync_apply = convert_syncbn_model(apply_resnet)   # BN -> SyncBN
        logits, stats = sync_apply(params, stats, x, train=True)
    """
    import functools

    return functools.partial(
        apply_fn, axis_name=axis_name or ps.DATA_AXIS, **partial_kwargs)
